"""Fleet-scale simulation harness (ROADMAP item 1 / ISSUE 9).

Runs N in-process daemon "nodes" — each with an isolated sysfs/devfs
root, its own plugin server (direct servicer surface), and its own DRA
driver + publish pacer — against ONE shared fake apiserver fabric, and
drives the fleet storms production TPU clusters actually see:

  - BOOT STORM: every node discovers, builds its daemon, and publishes
    its guarded ResourceSlice at the same instant — the thundering-herd
    shape the kubeapi.PublishPacer admission window exists for;
  - MASS VMI ATTACH: K claims per node prepared in one concurrent
    burst per node (a popular rollout = thousands of VMs attaching
    simultaneously), riding the PR 4 group-committed checkpoint;
  - HEALTH-FLIP WAVES: per-node flip storms whose guarded PUTs must
    coalesce into bounded publish waves with the FINAL state durable
    (exactly-once, never a lost last transition);
  - ROLLING DRAIN / UPGRADE WAVES: wave-sized groups drain, restart
    their DRA driver against the same checkpoint (daemon upgrade), and
    restore — prepared claims must survive every wave.

The fabric (`FleetApiServer`) models the congestion the RPCAcc paper
(PAPERS.md) targets: per-request latency, a bounded admission capacity
answered with 429 beyond it, and arrival-concurrency tracking (peak
in-flight) so pacing wins are measured, not asserted. Determinism: all
jitter flows from per-node seeded RNGs, and every acceptance fact is
counted (publish logs, generations, claim counts) rather than timed.

Storm fan-out uses ThreadPoolExecutor workers synchronized on a
Barrier — the simulator spawns no raw threads beyond the fabric's one
tracked serve thread (joined by stop()).

Used by `bench.py --fleet` (docs/bench_fleet_r11.json), the fleet test
suite (tests/test_fleetsim.py), and `make fleet-soak`.
"""

from __future__ import annotations

import collections
import json
import math
import os
import random
import shutil
import tempfile
import threading
import time
from concurrent import futures
from dataclasses import replace
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlsplit

from . import placement
from . import trace
from .config import Config
from .discovery import (HostSnapshot, count_reads, discover_passthrough,
                        read_serial)
from .dra import DraDriver, slice_device_name
from .kubeapi import ApiClient, PublishPacer
from .kubeletapi import drapb
from .server import TpuDevicePlugin

# fabric defaults: a conservative in-cluster apiserver RTT (the same 5 ms
# rationale as bench.py's ATTACH_APISERVER_RTT_S) and an admission
# capacity small enough that a 64-node herd actually collides
DEFAULT_LATENCY_S = 0.005
DEFAULT_MAX_INFLIGHT = 8


def _fakehost():
    """The sysfs fixture builder lives in tests/ (it is a simulation
    artifact, not daemon code); the simulator is only runnable from a
    source checkout, like bench.py."""
    try:
        from tests.fakehost import FakeChip, FakeHost
    except ImportError as exc:   # pragma: no cover - checkout-only tool
        raise RuntimeError(
            "fleetsim needs the tests/ tree (tests.fakehost) on "
            "sys.path — run it from a source checkout") from exc
    return FakeChip, FakeHost


def _name_selector(path: str) -> Optional[str]:
    """The metadata.name fieldSelector of a request path, or None for
    an unfiltered read — the one selector shape the fabric honors
    (enough for the per-node slice reflectors; anything else reads as
    unfiltered, which is correct-but-louder)."""
    query = parse_qs(urlsplit(path).query)
    sel = (query.get("fieldSelector") or [""])[0]
    # only a SOLE metadata.name clause filters: a compound selector
    # (metadata.name=a,spec.nodeName=b) must fall back to unfiltered,
    # not filter on the garbage name "a,spec.nodeName=b"
    if sel.startswith("metadata.name=") and "," not in sel:
        return sel[len("metadata.name="):] or None
    return None


class _FleetHTTPServer(ThreadingHTTPServer):
    # listen backlog: the default 5 makes a 64-node barrier-released
    # connect storm hit kernel SYN retransmission timers (seconds of
    # artificial serialization that would masquerade as pacing wins);
    # a real apiserver's accept queue is never the modeled bottleneck
    request_queue_size = 512
    daemon_threads = True

    def handle_error(self, request, client_address):
        # torn connections are ROUTINE here: the watch chaos breaks
        # streams on purpose and reflectors hang up mid-poll on stop —
        # socketserver's default stack-trace print would bury a soak's
        # real output. Anything else still prints.
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            ConnectionAbortedError)):
            return
        super().handle_error(request, client_address)


class FleetApiServer:
    """The shared kube-apiserver fabric with congestion modeling.

    Speaks just enough of the resource.k8s.io + core API for N DRA
    drivers: group discovery, node GETs (owner refs), ResourceSlice
    CRUD with resourceVersion guards (guarded PUTs stay exactly-once),
    and ResourceClaim GETs. Congestion knobs:

      latency_s     — base service time per admitted request, slept with
                      the GIL released (concurrent requests genuinely
                      overlap);
      congestion_k  — when > 0, service time DEGRADES with load:
                      latency_s * (1 + inflight/congestion_k) — the
                      convoy shape an overloaded apiserver (etcd fsync
                      queue, priority-and-fairness queuing) actually
                      shows, and what makes "peak in-flight" and write
                      p99 meaningful herd measurements;
      max_inflight  — admission capacity; arrivals beyond it are
                      answered 429 immediately (kube priority-and-
                      fairness shedding), the signal PublishPacer feeds
                      its window from. 0 = unlimited.

    Counted facts (under one lock): peak arrival concurrency
    (`peak_inflight`), peak admitted concurrency, totals by outcome,
    per-write service walls (p50/p99 surface), and the per-slice log of
    ACCEPTED writes [(monotonic, method, generation)] — the
    exactly-once audit surface.

    Deliberately NOT a subclass of tests/test_dra.py's FakeApiServer:
    that fake is a test fixture this package must not import at module
    scope, and the fleet fabric's contracts diverge on purpose — every
    store access is locked (N nodes hammer one instance), POST of an
    existing slice is 409 AlreadyExists (the exactly-once audit depends
    on it; the test fake last-writer-wins), and admission/congestion/
    write-log accounting wraps every request. Shared behavior is the
    thin REST surface, re-stated here in ~100 lines; keep the two in
    sync when the DRA driver grows a new endpoint.
    """

    def __init__(self, latency_s: float = 0.0, max_inflight: int = 0,
                 congestion_k: int = 0, versions=("v1beta1",),
                 watch_enabled: bool = True, watch_backlog: int = 4096,
                 watch_queue_max: int = 128,
                 watch_timeout_s: float = 30.0,
                 bookmark_interval_s: float = 0.5,
                 commit_crossing_s: float = 0.0):
        self.latency_s = latency_s
        self.max_inflight = max_inflight
        self.congestion_k = congestion_k
        self.versions = list(versions)
        self.slices: Dict[str, dict] = {}
        self.claims: Dict[tuple, dict] = {}
        self._rv = 0
        self._lock = threading.Lock()
        self._inflight = 0
        self._admitted = 0
        # ---- placement-consumption registry (ISSUE 17) -------------------
        # The fabric-side truth a CAS commit races against: per-node device
        # ownership ({node: {raw: multiclaim uid}}) and a per-node placement
        # generation, bumped on every place/release/move. Committed
        # ownership is PROJECTED onto the stored slice objects as a
        # `spec.consumed` overlay + `spec.pool.placementGeneration` (and
        # re-injected over driver PUTs, which never carry it), so peer
        # schedulers converge through the ordinary watch stream. Placements
        # deliberately do NOT touch spec.pool.generation or the accepted-
        # write log: the driver-publish exactly-once audit and the
        # placement plane each keep their own strictly-increasing sequence.
        self.commit_crossing_s = commit_crossing_s
        self.node_placements: Dict[str, Dict[str, str]] = {}
        self.node_placement_gens: Dict[str, int] = {}
        # (t_monotonic, action, uid, node, gen, detail) — the CAS-side
        # exactly-once audit surface (placement_audit)
        self.placement_log: List[tuple] = []
        self._slices_by_node: Dict[str, set] = {}
        self._slice_nodes: Dict[str, str] = {}   # slice name -> nodeName
        # ---- WATCH plane (ISSUE 12) -------------------------------------
        # The push side of the fabric: every accepted slice write appends a
        # pre-serialized event line under _lock, compacted to the newest
        # `watch_backlog` events (a watcher resuming from before the
        # compaction horizon is answered 410 Gone, like etcd compaction).
        # Each live stream holds a BOUNDED queue; a producer that overflows
        # it drops the whole queue and force-closes the stream with an
        # ERROR event (apiserver slow-consumer semantics) — the client's
        # only correct recovery is a relist. Watch requests bypass the 429
        # admission gate and the latency model: a real apiserver accounts
        # long-lived watches separately from request servicing.
        self.watch_enabled = watch_enabled
        self.watch_backlog = watch_backlog
        self.watch_queue_max = watch_queue_max
        self.watch_timeout_s = watch_timeout_s
        self.bookmark_interval_s = bookmark_interval_s
        self._events: collections.deque = collections.deque()  # (rv, bytes)
        self._compacted_rv = 0
        self._watchers: List[dict] = []      # live per-stream queue records
        self._watch_cond = threading.Condition(self._lock)
        # watch chaos knobs (arm_watch_chaos): per-event break/dup
        # probabilities + per-event stall, drawn from a seeded RNG
        self._watch_chaos: Optional[dict] = None
        self.stats = {
            "requests_total": 0,
            "throttled_total": 0,       # 429s sent
            "peak_inflight": 0,         # arrival concurrency
            "peak_admitted": 0,         # concurrency past the 429 gate
            # read/repair accounting (the r14 bench surface): GETs that
            # READ slice state — single-object or collection list — vs
            # long-lived watch streams
            "slice_reads_total": 0,
            "list_total": 0,
            "watch_opened_total": 0,
            "watch_events_sent_total": 0,
            "watch_bookmarks_sent_total": 0,
            "watch_410_total": 0,
            "watch_force_closed_total": 0,   # slow-consumer closes
            "watch_chaos_breaks_total": 0,
            "watch_chaos_dups_total": 0,
            # CAS placement plane (ISSUE 17)
            "placement_conflicts_total": 0,
            "commit_rounds_total": 0,
        }
        # slice name -> [(t_monotonic, method, pool generation), ...]
        self.write_log: Dict[str, List[tuple]] = {}
        # ---- multi-host DRA claim state (ISSUE 10) -----------------------
        # The fabric carries the cross-node claim record a real scheduler/
        # controller would keep in etcd: uid -> {shape, shards, phase}.
        # Every phase change is appended to the commit log, the exactly-
        # once audit surface for multi-node claims: a uid must see begin →
        # (commit | abort) with AT MOST ONE commit ever — a replayed
        # commit is a double-attach, a commit without a begin is a writer
        # bypassing the fabric.
        self.multiclaims: Dict[str, dict] = {}
        self.multiclaim_log: List[tuple] = []   # (t, uid, phase, detail)
        # service wall (seconds) of every ACCEPTED slice write — the
        # apiserver-side publish-latency surface (p50/p99 in snapshot())
        self.write_walls: List[float] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            wbufsize = 65536
            disable_nagle_algorithm = True

            def log_message(self, *a):
                pass

            def _send(self, code, obj=None):
                body = json.dumps(obj or {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _enter(self) -> bool:
                """Arrival accounting + 429 admission gate."""
                with outer._lock:
                    outer.stats["requests_total"] += 1
                    outer._inflight += 1
                    if outer._inflight > outer.stats["peak_inflight"]:
                        outer.stats["peak_inflight"] = outer._inflight
                    if outer.max_inflight and \
                            outer._admitted >= outer.max_inflight:
                        outer.stats["throttled_total"] += 1
                        return False
                    outer._admitted += 1
                    if outer._admitted > outer.stats["peak_admitted"]:
                        outer.stats["peak_admitted"] = outer._admitted
                return True

            def _exit(self, admitted: bool) -> None:
                with outer._lock:
                    outer._inflight -= 1
                    if admitted:
                        outer._admitted -= 1

            def _handle(self, method):
                # trace propagation (r17): the client stamps its active
                # span's context on every request (kubeapi Traceparent
                # header); the fabric threads it into the watch events
                # the write causes, so a watch-driven repair can link
                # the causal write's trace
                self._traceparent = self.headers.get("Traceparent")
                # watch streams bypass the admission gate + latency model
                # (a real apiserver budgets watches separately from request
                # servicing; a 64-node fleet's 64 idle streams must not eat
                # the max_inflight capacity storms are measured against)
                if method == "GET" and "watch=" in (self.path or ""):
                    return self._do_watch()
                admitted = self._enter()
                # service-wall start for _log_write_locked: only writes
                # the store ACCEPTS are recorded (409 guard conflicts /
                # 404s never reach the log), so write_wall percentiles
                # measure successful publish service time, not refusals
                self._req_t0 = time.monotonic()
                try:
                    if not admitted:
                        return self._send(429, {"reason": "TooManyRequests"})
                    if outer.latency_s:
                        delay = outer.latency_s
                        if outer.congestion_k:
                            # load-dependent degradation: the more
                            # concurrent requests, the slower each one —
                            # the herd makes ITSELF slow, which is the
                            # whole case for client-side pacing
                            with outer._lock:
                                n = outer._inflight
                            delay *= 1 + n / outer.congestion_k
                        time.sleep(delay)   # GIL released: overlaps
                    return getattr(self, f"_do_{method}")()
                finally:
                    self._exit(admitted)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

            def _do_GET(self):
                path = self.path
                if path.rstrip("/") == "/apis/resource.k8s.io":
                    return self._send(200, {
                        "kind": "APIGroup", "name": "resource.k8s.io",
                        "versions": [
                            {"groupVersion": f"resource.k8s.io/{v}",
                             "version": v} for v in outer.versions]})
                if path.startswith("/api/v1/nodes/"):
                    name = path.rsplit("/", 1)[-1]
                    return self._send(200, {"metadata": {
                        "name": name, "uid": f"uid-{name}"}})
                if path.split("?", 1)[0].rstrip("/").endswith(
                        "/resourceslices"):
                    # collection LIST: the reflector's relist/resync read.
                    # metadata.resourceVersion is the fabric's current rv —
                    # the watch-resume cursor a client continues from. A
                    # fieldSelector=metadata.name=X narrows the answer
                    # like a real apiserver (the cursor stays global).
                    sel = _name_selector(self.path)
                    with outer._lock:
                        outer.stats["list_total"] += 1
                        outer.stats["slice_reads_total"] += 1
                        items = [dict(o) for n, o in outer.slices.items()
                                 if sel is None or n == sel]
                        rv = outer._rv
                    return self._send(200, {
                        "kind": "ResourceSliceList",
                        "metadata": {"resourceVersion": str(rv)},
                        "items": items})
                if "/resourceslices/" in path:
                    name = path.rsplit("/", 1)[-1]
                    with outer._lock:
                        outer.stats["slice_reads_total"] += 1
                        obj = outer.slices.get(name)
                    if obj is not None:
                        return self._send(200, obj)
                    return self._send(404, {"reason": "NotFound"})
                if "/resourceclaims/" in path:
                    parts = path.split("/")
                    ns, name = parts[-3], parts[-1]
                    obj = outer.claims.get((ns, name))
                    if obj is not None:
                        return self._send(200, obj)
                    return self._send(404, {"reason": "NotFound"})
                return self._send(404, {})

            def _do_POST(self):
                obj = self._body()
                name = obj["metadata"]["name"]
                with outer._lock:
                    if name in outer.slices:
                        # a real apiserver 409s a duplicate create — the
                        # exactly-once audit depends on this
                        return self._send(409, {"reason": "AlreadyExists"})
                    outer._rv += 1
                    obj["metadata"]["resourceVersion"] = str(outer._rv)
                    outer.slices[name] = obj
                    outer._index_slice_locked(name, obj)
                    outer._inject_consumed_locked(obj)
                    outer._log_write_locked(name, "POST", obj,
                                            self._req_t0)
                    outer._append_event_locked("ADDED", obj,
                                               self._traceparent)
                return self._send(201, obj)

            def _do_PUT(self):
                name = self.path.rsplit("/", 1)[-1]
                obj = self._body()
                with outer._lock:
                    live = outer.slices.get(name)
                    if live is None:
                        return self._send(404, {})
                    if (obj["metadata"].get("resourceVersion")
                            != live["metadata"]["resourceVersion"]):
                        return self._send(409, {"reason": "Conflict"})
                    outer._rv += 1
                    obj["metadata"]["resourceVersion"] = str(outer._rv)
                    outer.slices[name] = obj
                    outer._index_slice_locked(name, obj)
                    # a driver's read-modify-write round-trips whatever it
                    # fetched, but a driver that lost the guarded-PUT race
                    # re-reads and re-projects from ITS state — the fabric
                    # owns the consumed overlay, so re-stamp it on every
                    # accepted write rather than trust the client copy
                    outer._inject_consumed_locked(obj)
                    outer._log_write_locked(name, "PUT", obj,
                                            self._req_t0)
                    outer._append_event_locked("MODIFIED", obj,
                                               self._traceparent)
                return self._send(200, obj)

            def _do_DELETE(self):
                name = self.path.rsplit("/", 1)[-1]
                with outer._lock:
                    live = outer.slices.pop(name, None)
                    if live is None:
                        return self._send(404, {})
                    outer._unindex_slice_locked(name, live)
                    # deletes carry a fresh rv like any other write, so a
                    # watcher's resume cursor advances past the tombstone
                    outer._rv += 1
                    tomb = dict(live, metadata=dict(
                        live.get("metadata") or {},
                        resourceVersion=str(outer._rv)))
                    outer._append_event_locked("DELETED", tomb,
                                               self._traceparent)
                return self._send(200, {})

            # ------------------------------------------- WATCH (ISSUE 12)

            def _chunk(self, data: bytes) -> None:
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                self.wfile.flush()

            def _do_watch(self):
                """Chunked long-poll watch stream over the resourceslices
                collection: newline-delimited JSON events from the resume
                resourceVersion forward, BOOKMARK events on idle, ERROR +
                close on slow-consumer overflow, 410 when the resume rv
                predates the compaction horizon."""
                parts = urlsplit(self.path)
                if not parts.path.rstrip("/").endswith("/resourceslices") \
                        or not outer.watch_enabled:
                    # watch is a slice-collection surface; elsewhere (or
                    # with the plane disabled) answer like an apiserver
                    # that does not serve it — the client's degradation
                    # ladder, not its retry loop, owns this signal. The
                    # refusal is still a served request (a degraded
                    # fleet's per-cycle probes must show in the load
                    # accounting)
                    with outer._lock:
                        outer.stats["requests_total"] += 1
                    return self._send(400, {"reason": "watch unsupported"})
                query = parse_qs(parts.query)
                try:
                    resume_rv = int((query.get("resourceVersion")
                                     or ["0"])[0])
                except ValueError:
                    resume_rv = 0
                try:
                    timeout_s = float((query.get("timeoutSeconds")
                                       or [outer.watch_timeout_s])[0])
                except ValueError:
                    timeout_s = outer.watch_timeout_s
                sel = _name_selector(self.path)
                with outer._lock:
                    outer.stats["requests_total"] += 1
                    if resume_rv < outer._compacted_rv:
                        # the resume point was compacted away: the client
                        # cannot be caught up event-by-event — relist
                        outer.stats["watch_410_total"] += 1
                        gone = True
                    else:
                        gone = False
                        watcher = {
                            "queue": collections.deque(
                                (rv, line) for rv, name, line
                                in outer._events
                                if rv > resume_rv
                                and (sel is None or name == sel)),
                            "name": sel,
                            "overflowed": False,
                            "closed": False,
                        }
                        if len(watcher["queue"]) > outer.watch_queue_max:
                            watcher["overflowed"] = True
                            watcher["queue"].clear()
                        outer._watchers.append(watcher)
                        outer.stats["watch_opened_total"] += 1
                if gone:
                    return self._send(410, {"reason": "Expired",
                                            "code": 410})
                deadline = time.monotonic() + timeout_s
                clean = True
                # the watcher is registered from here on: every exit —
                # including a client that tore the connection before the
                # header flush below made it out — must pass the finally
                # that deregisters it, or the dead record would keep
                # receiving (and overflowing on) every subsequent event
                # for the fabric's lifetime
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    # flush NOW: wbufsize buffers the headers, and an
                    # idle stream's first write is its first bookmark —
                    # without this the client's getresponse() blocks a
                    # whole bookmark interval per establishment
                    self.wfile.flush()
                    while True:
                        if time.monotonic() >= deadline:
                            # rotation applies to BUSY streams too: a
                            # steady event flow must not pin a long-poll
                            # open forever, or the client's rotation-
                            # resume path only ever runs idle
                            return
                        with outer._watch_cond:
                            bookmark_at = (time.monotonic()
                                           + outer.bookmark_interval_s)
                            while (not watcher["queue"]
                                   and not watcher["overflowed"]
                                   and not watcher["closed"]):
                                now = time.monotonic()
                                wake = min(deadline, bookmark_at)
                                if now >= wake:
                                    break
                                outer._watch_cond.wait(timeout=wake - now)
                            if watcher["closed"]:
                                clean = False   # abrupt: chaos/shutdown
                                return
                            overflowed = watcher["overflowed"]
                            if overflowed:
                                outer.stats["watch_force_closed_total"] \
                                    += 1
                            batch = list(watcher["queue"])
                            watcher["queue"].clear()
                            rv_now = outer._rv
                        if overflowed:
                            # slow consumer: the queue overflowed and was
                            # dropped — events are LOST on this stream,
                            # so force-close with the 410-shaped ERROR a
                            # real apiserver sends; the client must
                            # relist. Written OUTSIDE the fabric lock: a
                            # slow consumer is by definition not draining
                            # its socket, and a sendall blocked on its
                            # full TCP buffer must not stall every other
                            # request the fabric is serving
                            err = json.dumps({
                                "type": "ERROR",
                                "object": {"code": 410,
                                           "reason": "Expired",
                                           "message": "slow consumer"}})
                            self._chunk(err.encode() + b"\n")
                            return
                        if not batch:
                            if time.monotonic() >= deadline:
                                return   # clean rotation: client re-watches
                            # idle past the bookmark interval: advance the
                            # client's resume cursor without data
                            with outer._lock:
                                outer.stats[
                                    "watch_bookmarks_sent_total"] += 1
                            bookmark = json.dumps({
                                "type": "BOOKMARK",
                                "object": {"metadata": {
                                    "resourceVersion": str(rv_now)}}})
                            self._chunk(bookmark.encode() + b"\n")
                            continue
                        delivered = 0
                        for _rv, line in batch:
                            # re-read per delivery: chaos armed MID-
                            # STREAM must bite the already-open streams
                            chaos = outer._watch_chaos
                            if chaos is not None:
                                clean = self._chaos_deliver(chaos, line)
                                if not clean:
                                    break
                            else:
                                self._chunk(line + b"\n")
                            delivered += 1
                        # one lock crossing per BATCH, not per event —
                        # this loop runs on every watcher thread and a
                        # per-event acquisition serializes busy streams
                        # against the whole fabric
                        if delivered:
                            with outer._lock:
                                outer.stats[
                                    "watch_events_sent_total"] += delivered
                        if not clean:
                            return
                except (BrokenPipeError, ConnectionResetError, OSError):
                    clean = False   # client went away mid-write
                finally:
                    with outer._lock:
                        try:
                            outer._watchers.remove(watcher)
                        except ValueError:
                            pass
                    if clean:
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                            self.wfile.flush()
                        except OSError:
                            pass
                    self.close_connection = True

            def _chaos_deliver(self, chaos: dict, line: bytes) -> bool:
                """Deliver one event under the armed watch chaos: stall,
                duplicate, or break the stream. Returns False when the
                stream was broken (caller closes abruptly)."""
                rng = chaos["rng"]
                if chaos["stall_s"] > 0:
                    time.sleep(chaos["stall_s"])
                if chaos["break_p"] > 0 and rng.random() < chaos["break_p"]:
                    # abrupt mid-stream break: no terminating chunk — the
                    # client sees a torn chunked body (IncompleteRead)
                    with outer._lock:
                        outer.stats["watch_chaos_breaks_total"] += 1
                    return False
                self._chunk(line + b"\n")
                if chaos["dup_p"] > 0 and rng.random() < chaos["dup_p"]:
                    with outer._lock:
                        outer.stats["watch_chaos_dups_total"] += 1
                    self._chunk(line + b"\n")
                return True

        self.server = _FleetHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True,
            name="fleet-apiserver")
        self.thread.start()

    def _log_write_locked(self, name: str, method: str, obj: dict,
                          t0: float) -> None:
        now = time.monotonic()
        gen = (((obj.get("spec") or {}).get("pool") or {})
               .get("generation")) or 1
        self.write_log.setdefault(name, []).append((now, method, gen))
        self.write_walls.append(now - t0)

    # --------------------------------------------- watch plane (ISSUE 12)

    def _append_event_locked(self, etype: str, obj: dict,
                             traceparent: Optional[str] = None) -> None:
        """Append one pre-serialized watch event (caller holds _lock):
        fan out to every live watcher's bounded queue (overflow = the
        whole queue drops and the stream force-closes), compact the
        global log to `watch_backlog`, wake the streams. `traceparent`
        (the causing write's request header, r17) rides the event
        top-level, so a watch consumer can link the causal trace."""
        rv = int((obj.get("metadata") or {}).get("resourceVersion")
                 or self._rv)
        name = (obj.get("metadata") or {}).get("name")
        evt = {"type": etype, "object": obj}
        if traceparent:
            evt["traceparent"] = traceparent
        line = json.dumps(evt).encode()
        self._events.append((rv, name, line))
        while len(self._events) > self.watch_backlog:
            old_rv, _name, _old = self._events.popleft()
            self._compacted_rv = old_rv
        for watcher in self._watchers:
            if watcher["overflowed"]:
                continue
            if watcher["name"] is not None and watcher["name"] != name:
                continue   # fieldSelector'd stream: not its object
            watcher["queue"].append((rv, line))
            if len(watcher["queue"]) > self.watch_queue_max:
                watcher["overflowed"] = True
                watcher["queue"].clear()
        self._watch_cond.notify_all()

    def arm_watch_chaos(self, break_p: float = 0.0, dup_p: float = 0.0,
                        stall_s: float = 0.0, seed: int = 0) -> None:
        """Arm per-event watch-stream chaos: `break_p` = probability an
        event delivery abruptly tears the stream (client must relist or
        re-watch), `dup_p` = probability an event is delivered twice
        (at-least-once pressure on handler idempotency), `stall_s` =
        per-event delivery stall. Seeded so soaks replay."""
        self._watch_chaos = {"break_p": break_p, "dup_p": dup_p,
                             "stall_s": stall_s,
                             "rng": random.Random(seed)}

    def disarm_watch_chaos(self) -> None:
        self._watch_chaos = None

    def close_watch_streams(self) -> int:
        """Force-close every live watch stream abruptly (deterministic
        break injection for tests). Returns the number closed."""
        with self._watch_cond:
            n = len(self._watchers)
            for watcher in self._watchers:
                watcher["closed"] = True
            self._watch_cond.notify_all()
        return n

    def watch_streams_active(self) -> int:
        with self._lock:
            return len(self._watchers)

    @property
    def url(self) -> str:
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    # ------------------------------------------- multi-host claim records

    def multiclaim_begin(self, uid: str, shape, shards,
                         traceparent: Optional[str] = None) -> None:
        with self._lock:
            self.multiclaims[uid] = {
                "shape": list(shape),
                "shards": [(node, list(raws)) for node, raws in shards],
                "phase": "pending",
                # the scheduler decision's trace context (r17): the
                # fabric's cross-node claim record names the trace a
                # /debug/fleet/trace query reconstructs
                "traceparent": traceparent,
            }
            self.multiclaim_log.append(
                (time.monotonic(), uid, "begin", len(shards)))

    def multiclaim_commit(self, uid: str, observed=None) -> dict:
        """Commit one multiclaim — a batch of one (see
        multiclaim_commit_batch for the CAS + crossing semantics).
        Legacy callers that pass no `observed` keep the unconditional
        PR 14 commit behavior and can ignore the return value."""
        return self.multiclaim_commit_batch([(uid, observed)])[uid]

    def multiclaim_commit_batch(self, commits) -> Dict[str, dict]:
        """ONE commit round for a wave of multiclaims (ISSUE 17):
        `commits` is [(uid, observed)] where `observed` is the per-node
        placement generation map the scheduler planned against
        ({node: gen}), or None for the legacy unconditional commit.

        The round pays `commit_crossing_s` ONCE — outside the lock, GIL
        released — modeling the etcd txn round-trip a real batched
        commit amortizes across the wave; then every uid is CAS-checked
        and applied under one lock crossing. A CAS loss (any planned
        node's placement generation moved, or any planned chip already
        owned) is a counted clean refusal: the claim record is NOT
        committed, nothing is registered, and the caller rolls back its
        prepared shards and replans. A CAS win registers device
        ownership, bumps the per-node placement generations, and
        re-projects the consumed overlay onto the stored slices (one
        MODIFIED watch event per touched slice) so every peer
        scheduler's cache converges on the new truth."""
        if self.commit_crossing_s:
            time.sleep(self.commit_crossing_s)   # one crossing per ROUND
        out: Dict[str, dict] = {}
        with self._lock:
            self.stats["commit_rounds_total"] += 1
            # restamps are coalesced per ROUND per node: a wave packing
            # eight claims onto one host emits ONE slice MODIFIED event,
            # not eight — the watch fan-out cost scales with touched
            # hosts, matching the accountant's O(request) delta claim
            touched: Dict[str, Optional[str]] = {}
            for uid, observed in commits:
                out[uid] = self._commit_one_locked(uid, observed, touched)
            restamped = {
                node: self._restamp_node_slices_locked(node, tp)
                for node, tp in touched.items()}
        for res in out.values():
            nodes = res.pop("nodes", None)
            if res.get("committed"):
                res["slices"] = [
                    rec for node in dict.fromkeys(nodes or ())
                    for rec in restamped.get(node, ())]
        return out

    def _commit_one_locked(self, uid: str, observed,
                           touched: Dict[str, Optional[str]]) -> dict:
        now = time.monotonic()
        rec = self.multiclaims.get(uid)
        shards = rec["shards"] if rec is not None else []
        traceparent = rec.get("traceparent") if rec is not None else None
        if observed is not None and rec is not None:
            conflicts = []
            for node, raws in shards:
                if observed.get(node, 0) != \
                        self.node_placement_gens.get(node, 0):
                    conflicts.append(node)
                    continue
                owners = self.node_placements.get(node) or {}
                if any(r in owners for r in raws):
                    conflicts.append(node)
            if conflicts:
                conflicts = sorted(set(conflicts))
                self.stats["placement_conflicts_total"] += 1
                self.multiclaim_log.append(
                    (now, uid, "conflict", conflicts))
                self.placement_log.append(
                    (now, "conflict", uid, conflicts[0], None, conflicts))
                return {"committed": False, "conflicts": conflicts,
                        "gens": {node: self.node_placement_gens.get(node, 0)
                                 for node, _raws in shards}}
        if rec is not None:
            rec["phase"] = "committed"
        # the log records the attempt even when the record is absent/
        # already committed — that is exactly what the audit flags
        self.multiclaim_log.append((now, uid, "commit", None))
        gens: Dict[str, int] = {}
        committed_nodes: List[str] = []
        if observed is not None and rec is not None:
            for node, raws in shards:
                owners = self.node_placements.setdefault(node, {})
                for r in raws:
                    owners[r] = uid
                gen = self.node_placement_gens.get(node, 0) + 1
                self.node_placement_gens[node] = gen
                gens[node] = gen
                self.placement_log.append(
                    (now, "place", uid, node, gen, sorted(raws)))
                committed_nodes.append(node)
                if node not in touched:
                    touched[node] = traceparent
        return {"committed": True, "gens": gens, "nodes": committed_nodes}

    def release_placement(self, uid: str) -> Dict[str, object]:
        """Free every chip the placement registry holds for multiclaim
        `uid` (tenant departure / post-abort hygiene): bump the touched
        nodes' placement generations, log, and re-project the consumed
        overlay. Returns {"gens": {node: gen}, "slices": [restamp
        deltas]} — the deltas feed the releasing scheduler's accountant
        the same way commit feedback does, so its views free the chips
        without waiting on the watch round-trip. Idempotent — an
        unknown uid frees nothing."""
        with self._lock:
            now = time.monotonic()
            gens: Dict[str, int] = {}
            deltas: List[dict] = []
            for node, owners in self.node_placements.items():
                raws = sorted(r for r, o in owners.items() if o == uid)
                if not raws:
                    continue
                for r in raws:
                    del owners[r]
                gen = self.node_placement_gens.get(node, 0) + 1
                self.node_placement_gens[node] = gen
                gens[node] = gen
                self.placement_log.append(
                    (now, "release", uid, node, gen, raws))
                deltas.extend(self._restamp_node_slices_locked(node))
            return {"gens": gens, "slices": deltas}

    def move_placement(self, source_node: str, target_node: str,
                       source_raws, target_raws) -> Dict[str, object]:
        """Defrag-migration ownership handoff: re-home each owned source
        chip to its paired target chip under the SAME multiclaim owner.
        Executor-authoritative (no CAS — the migration machinery already
        serialized the move); a source chip with no registered owner is
        skipped, so fleets that never CAS-commit see a no-op.
        Returns {"gens": ..., "slices": [restamp deltas]} like
        release_placement — the deltas feed the coordinating
        scheduler's accountant."""
        with self._lock:
            now = time.monotonic()
            src = self.node_placements.get(source_node) or {}
            moved = [(s, t) for s, t in zip(source_raws, target_raws)
                     if s in src]
            if not moved:
                return {"gens": {}, "slices": []}
            dst = self.node_placements.setdefault(target_node, {})
            by_uid: Dict[str, List[tuple]] = {}
            for s, t in moved:
                by_uid.setdefault(src[s], []).append((s, t))
            gens: Dict[str, int] = {}
            for uid, pairs in sorted(by_uid.items()):
                for s, t in pairs:
                    del src[s]
                    dst[t] = uid
                for node, raws, action in (
                        (source_node, [s for s, _ in pairs], "move_out"),
                        (target_node, [t for _, t in pairs], "move_in")):
                    gen = self.node_placement_gens.get(node, 0) + 1
                    self.node_placement_gens[node] = gen
                    gens[node] = gen
                    self.placement_log.append(
                        (now, action, uid, node, gen, sorted(raws)))
            deltas: List[dict] = []
            for node in (source_node, target_node):
                deltas.extend(self._restamp_node_slices_locked(node))
            return {"gens": gens, "slices": deltas}

    def placement_audit(self) -> dict:
        """Exactly-once audit over the placement log (the CAS-side
        third of the ISSUE 17 triple audit): replaying place/release/
        move must never double-own a (node, chip), per-node placement
        generations must be strictly increasing, and the replay must
        land exactly on the live registry."""
        with self._lock:
            log_copy = list(self.placement_log)
            live = {(n, r): u for n, owners in self.node_placements.items()
                    for r, u in owners.items()}
        owned: Dict[tuple, str] = {}
        double: List[tuple] = []
        regressed: List[tuple] = []
        gens_seen: Dict[str, int] = {}
        conflicts = 0
        placements = 0
        for _t, action, uid, node, gen, detail in log_copy:
            if action == "conflict":
                conflicts += 1
                continue
            if gen <= gens_seen.get(node, 0):
                regressed.append((node, gen))
            gens_seen[node] = gen
            if action in ("place", "move_in"):
                if action == "place":
                    placements += 1
                for raw in detail:
                    if (node, raw) in owned:
                        double.append((node, raw, owned[(node, raw)], uid))
                    owned[(node, raw)] = uid
            else:   # release / move_out
                for raw in detail:
                    owned.pop((node, raw), None)
        return {"placements_audited": placements,
                "conflicts_total": conflicts,
                "double_placements": double,
                "regressed_generations": regressed,
                "log_matches_registry": owned == live,
                "exactly_once": (not double and not regressed
                                 and owned == live)}

    # ----------------------------------- consumed-overlay projection

    def _index_slice_locked(self, name: str, obj: dict) -> None:
        node = (obj.get("spec") or {}).get("nodeName")
        old = self._slice_nodes.get(name)
        if old is not None and old != node:
            self._slices_by_node.get(old, set()).discard(name)
        if node:
            self._slice_nodes[name] = node
            self._slices_by_node.setdefault(node, set()).add(name)

    def _unindex_slice_locked(self, name: str, obj: dict) -> None:
        node = self._slice_nodes.pop(name, None)
        if node is not None:
            self._slices_by_node.get(node, set()).discard(name)

    def _inject_consumed_locked(self, obj: dict) -> None:
        """Stamp the fabric-owned placement projection onto a slice
        object: spec.consumed = {raw: owner uid} and
        spec.pool.placementGeneration. Caller holds _lock and owns the
        dict (fresh request body or a _restamp copy)."""
        spec = obj.setdefault("spec", {})
        node = spec.get("nodeName")
        if not node:
            return
        owners = self.node_placements.get(node)
        if owners:
            spec["consumed"] = dict(owners)
        else:
            spec.pop("consumed", None)
        gen = self.node_placement_gens.get(node, 0)
        if gen:
            spec.setdefault("pool", {})["placementGeneration"] = gen

    def _restamp_node_slices_locked(self, node: str,
                                    traceparent=None) -> List[dict]:
        """Re-project the consumed overlay onto every stored slice of
        `node` with a fresh resourceVersion + MODIFIED watch event.
        Copy-on-write (a concurrent GET may be serializing the old
        object outside the lock). Returns the per-slice delta records
        the committing scheduler feeds its own accountant, so its cache
        converges without waiting on the watch round-trip."""
        out: List[dict] = []
        for name in sorted(self._slices_by_node.get(node, ())):
            live = self.slices.get(name)
            if live is None:
                continue
            obj = dict(live)
            obj["metadata"] = dict(live.get("metadata") or {})
            spec = dict(live.get("spec") or {})
            spec["pool"] = dict(spec.get("pool") or {})
            obj["spec"] = spec
            self._rv += 1
            obj["metadata"]["resourceVersion"] = str(self._rv)
            self._inject_consumed_locked(obj)
            self.slices[name] = obj
            self._append_event_locked("MODIFIED", obj, traceparent)
            out.append({"name": name, "node": node,
                        "resource_version": obj["metadata"]
                        ["resourceVersion"],
                        "generation": spec["pool"].get("generation"),
                        "placement_generation": spec["pool"]
                        .get("placementGeneration", 0),
                        "consumed": dict(spec.get("consumed") or {})})
        return out

    def seed_slices(self, objs) -> int:
        """Bulk-insert pre-built ResourceSlice objects directly into the
        store (the SyntheticFleet boot path: 4096 nodes need no HTTP
        herd to EXIST — the storms under test are scheduling storms).
        Each insert is an accepted write for the exactly-once audit;
        no watch events are emitted (seeding precedes every watcher,
        which LISTs first)."""
        now = time.monotonic()
        with self._lock:
            for obj in objs:
                name = obj["metadata"]["name"]
                if name in self.slices:
                    raise AssertionError(f"seed of duplicate slice {name}")
                self._rv += 1
                obj["metadata"]["resourceVersion"] = str(self._rv)
                self.slices[name] = obj
                self._index_slice_locked(name, obj)
                self._inject_consumed_locked(obj)
                self._log_write_locked(name, "POST", obj, now)
            return len(self.slices)

    def multiclaim_abort(self, uid: str, reason: str) -> None:
        with self._lock:
            rec = self.multiclaims.get(uid)
            if rec is not None:
                rec["phase"] = "aborted"
            self.multiclaim_log.append(
                (time.monotonic(), uid, "abort", reason))

    def multiclaim_audit(self) -> dict:
        """Counted exactly-once facts over the multi-node claim commit
        log (the multi-host analogue of exactly_once_audit)."""
        with self._lock:
            log_copy = list(self.multiclaim_log)
        phases: Dict[str, List[str]] = {}
        for _t, uid, phase, _detail in log_copy:
            phases.setdefault(uid, []).append(phase)
        duplicated = sorted(u for u, ps in phases.items()
                            if ps.count("commit") > 1)
        unbegun = sorted(u for u, ps in phases.items()
                         if ("commit" in ps or "abort" in ps)
                         and ps[0] != "begin")
        dangling = sorted(u for u, ps in phases.items()
                          if "commit" not in ps and "abort" not in ps)
        return {"claims_audited": len(phases),
                "committed": sorted(u for u, ps in phases.items()
                                    if "commit" in ps),
                "duplicated_commits": duplicated,
                "unbegun_commits": unbegun,
                "pending": dangling,
                "exactly_once": not duplicated and not unbegun}

    def remove_claim(self, ns, name) -> None:
        with self._lock:
            self.claims.pop((ns, name), None)

    def add_claim(self, ns, name, uid, driver, results) -> None:
        self.claims[(ns, name)] = {
            "metadata": {"namespace": ns, "name": name, "uid": uid},
            "status": {"allocation": {"devices": {"results": [
                {"request": r.get("request", "tpu"), "driver": driver,
                 "pool": r.get("pool", "fleet"), "device": r["device"]}
                for r in results
            ]}}},
        }

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["slices"] = len(self.slices)
            out["accepted_writes"] = sum(
                len(v) for v in self.write_log.values())
            walls = sorted(self.write_walls)
        if walls:
            out["write_wall_p50_ms"] = round(
                1e3 * walls[len(walls) // 2], 1)
            out["write_wall_p99_ms"] = round(
                1e3 * walls[min(len(walls) - 1,
                               int(len(walls) * 0.99))], 1)
            out["write_wall_max_ms"] = round(1e3 * walls[-1], 1)
        return out

    def exactly_once_audit(self) -> dict:
        """Counted exactly-once facts over the accepted-write log: every
        slice's generation sequence must be strictly increasing with no
        duplicates (a duplicated generation = a replayed publish; a gap
        is fine — unchanged projections skip publishes, never the other
        way around)."""
        with self._lock:
            logs = {k: list(v) for k, v in self.write_log.items()}
        duplicated = []
        regressed = []
        for name, entries in logs.items():
            gens = [g for _, _, g in entries]
            if len(gens) != len(set(gens)):
                duplicated.append(name)
            if any(b <= a for a, b in zip(gens, gens[1:])):
                regressed.append(name)
        return {"slices_audited": len(logs),
                "duplicated_generations": sorted(duplicated),
                "regressed_generations": sorted(regressed),
                "exactly_once": not duplicated and not regressed}

    def stop(self) -> None:
        self.close_watch_streams()   # unblock long-poll handler threads
        self.server.shutdown()
        self.server.server_close()
        if self.thread.is_alive():
            self.thread.join(timeout=2)


class FleetNode:
    """One simulated node: isolated sysfs root, plugin server (direct
    servicer surface — no gRPC socket; the kubelet side of a fleet storm
    is exercised through the same handlers the socket would call), and a
    DRA driver whose pacer jitter is seeded per node."""

    def __init__(self, root: str, index: int, apiserver: FleetApiServer,
                 n_devices: int = 4, pace_max_s: float = 2.0,
                 pace_base_s: float = 0.0, pace: bool = True,
                 seed: int = 0, device_id: str = "0063",
                 watch: bool = False, watch_resync_s: float = 5.0,
                 watch_poll_s: float = 0.5, watch_timeout_s: float = 2.0,
                 host_coords=None):
        FakeChip, FakeHost = _fakehost()
        self._pace = pace
        # watch-driven convergence (ISSUE 12): sim-speed reflector knobs
        self._watch = watch
        self._watch_knobs = (watch_resync_s, watch_poll_s, watch_timeout_s)
        self.index = index
        self.name = f"node-{index:03d}"
        self.root = os.path.join(root, self.name)
        self.apiserver = apiserver
        host = FakeHost(self.root)
        for i in range(n_devices):
            host.add_chip(FakeChip(
                f"0000:{i // 32:02x}:{4 + i % 32:02x}.0",
                device_id=device_id, iommu_group=str(11 + i),
                numa_node=i // max(1, n_devices // 2)))
        self.cfg = replace(Config().with_root(self.root),
                           publish_pace_base_s=pace_base_s,
                           publish_pace_max_s=pace_max_s,
                           lw_debounce_s=0.0,
                           # the node's slot on the pod-level host grid
                           # (published as hostX/hostY slice attributes,
                           # carried on every HostView) — the fleet
                           # scheduler's cross-host mesh model
                           host_coords=tuple(host_coords)
                           if host_coords is not None else None)
        os.makedirs(self.cfg.device_plugin_path, exist_ok=True)
        self.registry, self.generations = discover_passthrough(self.cfg)
        self.device_id = device_id
        self.devices = self.registry.devices_by_model[device_id]
        self.bdfs = [d.bdf for d in self.devices]
        self._seed = seed
        self.driver = self._build_driver()
        info = self.generations.get(device_id)
        suffix = info.name if info is not None else f"tpu-{device_id}"
        # the plugin's ANDed health verdicts feed the driver exactly like
        # cli.py wires the production daemon: one health observer, no
        # second driftable watcher
        self.plugin = TpuDevicePlugin(
            self.cfg, suffix, self.registry, self.devices,
            torus_dims=info.host_topology if info is not None else None,
            health_listener=self._health_listener)

    def _build_driver(self) -> DraDriver:
        driver = DraDriver(
            self.cfg, self.registry, self.generations,
            node_name=self.name,
            api=ApiClient(self.apiserver.url, token_path="/nonexistent"))
        # deterministic jitter: the fleet's pacing behavior replays
        # exactly under a fixed fleet seed. The unpaced control keeps
        # the same plumbing with a zero window and a deep retry budget —
        # the naive keep-hammering client the pacer replaces.
        driver.pacer = PublishPacer(
            api=driver.api,
            base_window_s=self.cfg.publish_pace_base_s if self._pace
            else 0.0,
            max_window_s=self.cfg.publish_pace_max_s if self._pace
            else 0.0,
            max_attempts=16 if self._pace else 50,
            rng=random.Random((self._seed << 16) ^ self.index))
        if self._watch:
            resync_s, poll_s, timeout_s = self._watch_knobs
            driver.start_watch_reconciler(resync_interval_s=resync_s,
                                          poll_interval_s=poll_s,
                                          watch_timeout_s=timeout_s)
        return driver

    def _health_listener(self, current: Dict[str, bool]) -> None:
        self.driver.apply_health(current)

    # ------------------------------------------------------------ storms

    def boot(self) -> bool:
        """One node's boot-storm contribution: publish the guarded
        ResourceSlice and assemble the initial ListAndWatch send from
        the current epoch (the kubelet-visible boot payload)."""
        ok = self.driver.publish_resource_slices()
        self.plugin._lw_response(self.plugin._store.current)
        return ok

    def register_claims(self, k: int, wave: int = 0) -> List[str]:
        uids = [f"{self.name}-w{wave}-c{i}" for i in range(k)]
        for i, uid in enumerate(uids):
            self.apiserver.add_claim(
                "fleet", uid, uid, self.driver.driver_name,
                [{"device": slice_device_name(
                    self.bdfs[i % len(self.bdfs)])}])
        return uids

    def attach(self, uids: List[str]):
        claims = [drapb.Claim(namespace="fleet", name=uid, uid=uid)
                  for uid in uids]
        return self.driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=claims), None)

    def detach(self, uids: List[str]):
        claims = [drapb.Claim(namespace="fleet", name=uid, uid=uid)
                  for uid in uids]
        return self.driver.NodeUnprepareResources(
            drapb.NodeUnprepareResourcesRequest(claims=claims), None)

    # ------------------------------------------------------- placement

    def host_view(self) -> "placement.HostView":
        """This node's placement snapshot for its (single) generation."""
        views = self.driver.host_views()
        return views[next(iter(sorted(views)))]

    def claim_devices(self, uid: str, raws: List[str]) -> None:
        """Pin a claim onto SPECIFIC chips (fragmentation scripting for
        placement tests/benches): register + prepare, raising on error."""
        names = self.host_view().names
        self.apiserver.add_claim(
            "fleet", uid, uid, self.driver.driver_name,
            [{"device": names[r]} for r in raws])
        resp = self.attach([uid])
        if resp.claims[uid].error:
            raise AssertionError(
                f"{self.name}: claim {uid} on {raws} failed: "
                f"{resp.claims[uid].error}")

    def flip_storm(self, flips: int) -> None:
        """Alternate one device unhealthy/healthy `flips` times: each
        EFFECTIVE transition publishes (paced, coalescible); the final
        state must still land exactly (asserted fleet-wide)."""
        for i in range(flips):
            self.plugin.set_devices_health(
                [self.bdfs[0]], healthy=(i % 2 == 1), source="storm")
        # end healthy: an even flip count leaves the last verdict
        # unhealthy, so normalize for the convergence audit
        self.plugin.set_devices_health([self.bdfs[0]], healthy=True,
                                       source="storm")

    def drain(self) -> None:
        self.plugin.set_all_health(False, source="drain")

    def restore(self) -> None:
        self.plugin.set_all_health(True, source="drain")

    def upgrade(self) -> bool:
        """Daemon upgrade: stop the driver, rebuild it against the SAME
        checkpoint (claims must survive), republish."""
        before = self.driver.prepared_claim_count()
        self.driver.stop()
        self.driver = self._build_driver()
        if self.driver.prepared_claim_count() != before:
            raise AssertionError(
                f"{self.name}: upgrade lost claims "
                f"({before} -> {self.driver.prepared_claim_count()})")
        return self.driver.publish_resource_slices()

    def restart_with_discovery(self, warm: bool = True,
                               sysfs_read_cost_s: float = 0.0) -> dict:
        """Full daemon restart INCLUDING host re-learning (upgrade()
        above models only the driver swap): stop the driver, rediscover
        the host — the classic cold walk plus per-device identity reads,
        or the persisted-snapshot fast path (load + one revalidation
        pass, serials straight from the cache) — then rebuild the driver
        from its checkpoint and republish. The node is unavailable for
        the whole measured window; claims must survive exactly.

        `sysfs_read_cost_s` models per-access host IO the way
        FleetApiServer.latency_s models fabric service time: the sim's
        tmpfs makes a sysfs read ~free, where real silicon pays config-
        space/driver latency per access — the modeled delay is counted
        reads x cost, charged inside the unready window so both the
        cold walk and the snapshot path pay for exactly the IO they do.

        Returns {"unready_s", "reads", "path"}."""
        before = self.driver.prepared_claim_count()
        snap_path = self.cfg.discovery_snapshot_path
        t0 = time.monotonic()
        self.driver.stop()
        with count_reads() as counter:
            snap = HostSnapshot(self.cfg)
            path = "cold"
            if warm and snap_path:
                if snap.load_cache(snap_path) == "loaded":
                    path = "snapshot"
                    invalidated = snap.revalidate()
                    self.registry, self.generations = snap.rescan(
                        dirty=snap.taint_groups(invalidated))
                else:
                    # untrusted/missing cache: counted cold walk through
                    # the snapshot (so THIS restart seeds the next one)
                    self.registry, self.generations = snap.rescan()
                for d in self.registry.devices_by_model[self.device_id]:
                    snap.serial_of(d.bdf)
            else:
                self.registry, self.generations = discover_passthrough(
                    self.cfg)
                # cold boot identity cost: the lifecycle FSM re-reads
                # every device's serial before admitting it
                for d in self.registry.devices_by_model[self.device_id]:
                    read_serial(self.cfg.pci_base_path, d.bdf)
            if sysfs_read_cost_s:
                time.sleep(counter.reads * sysfs_read_cost_s)
            self.devices = self.registry.devices_by_model[self.device_id]
            self.bdfs = [d.bdf for d in self.devices]
            self.driver = self._build_driver()
            if self.driver.prepared_claim_count() != before:
                raise AssertionError(
                    f"{self.name}: restart lost claims ({before} -> "
                    f"{self.driver.prepared_claim_count()})")
            info = self.generations.get(self.device_id)
            suffix = (info.name if info is not None
                      else f"tpu-{self.device_id}")
            self.plugin = TpuDevicePlugin(
                self.cfg, suffix, self.registry, self.devices,
                torus_dims=info.host_topology if info is not None else None,
                health_listener=self._health_listener)
            ok = self.driver.publish_resource_slices()
        unready_s = time.monotonic() - t0
        if not ok:
            raise AssertionError(f"{self.name}: restart republish failed")
        # persist (atomic temp+rename) so the NEXT restart can go warm;
        # outside the unready window — the node is already serving. A
        # baseline (warm=False) restart never scans the snapshot, so it
        # saves nothing and stays cold forever, as a pre-snapshot
        # daemon would.
        if snap_path and warm:
            snap.save_cache(snap_path)
        return {"unready_s": unready_s, "reads": counter.reads,
                "path": path}

    def pacer_stats(self) -> dict:
        return self.driver.pacer.snapshot()

    def stop(self) -> None:
        self.driver.stop()


class ManagedFleetNode:
    """One fleetsim node with the FULL production wiring cli.main builds
    (ROADMAP item 1 follow-on): a real PluginManager — shared HealthHub,
    per-device lifecycle FSM, incremental rediscovery, plugin servers
    registering against an in-process kubelet devicemanager simulator —
    with the DRA driver attached through the same three seams the daemon
    uses (on_inventory sink, plugin health listener, attach_lifecycle),
    publishing to the shared fleet fabric.

    Unlike FleetNode (a lean plugin+driver pair for storm fan-out), this
    node exists to drive the PR 7 lifecycle scenarios through the REAL
    wiring: hot_unplug() removes a chip's sysfs dir + vfio node, tick()
    runs one rediscovery pass exactly like the manager's run loop would,
    and the resulting orphan + slice republish land in the fabric's
    accepted-write generation log where the exactly-once audit sees
    them. Claims prepare through the driver's direct servicer surface,
    like FleetNode."""

    def __init__(self, root: str, apiserver: FleetApiServer,
                 name: str = "mnode-000", n_devices: int = 4,
                 device_id: str = "0063", spawn_broker: bool = False):
        FakeChip, FakeHost = _fakehost()
        from .lifecycle import PluginManager
        from .registry import Registry
        try:
            from tests.kubelet_sim import DeviceManagerSim
        except ImportError as exc:   # pragma: no cover - checkout-only
            raise RuntimeError(
                "ManagedFleetNode needs the tests/ tree "
                "(tests.kubelet_sim) on sys.path") from exc
        self.name = name
        self.root = os.path.join(root, name)
        self.apiserver = apiserver
        self.host = FakeHost(self.root)
        self.bdfs = []
        self.groups = {}
        for i in range(n_devices):
            bdf = f"0000:00:{4 + i:02x}.0"
            self.host.add_chip(FakeChip(
                bdf, device_id=device_id, iommu_group=str(11 + i),
                numa_node=i // max(1, n_devices // 2),
                serial=f"sn-{name}-{i}"))
            self.bdfs.append(bdf)
            self.groups[bdf] = str(11 + i)
        self.cfg = replace(Config().with_root(self.root),
                           publish_pace_base_s=0.0, lw_debounce_s=0.0,
                           broker_mode="spawn" if spawn_broker
                           else "inproc")
        # Privilege separation (broker.py): a broker-backed node runs a
        # REAL privileged broker process rooted at this node's fixture
        # tree and points the process-global seam at it BEFORE any
        # planner or health shim is built — the whole boot/claim-storm
        # path then crosses the versioned IPC exactly as the production
        # spawn mode does. One spawn-mode node per process at a time
        # (the seam is process-global); stop() restores the previous
        # client.
        self.broker_proc = None
        self._prev_broker_client = None
        if spawn_broker:
            from . import broker as broker_mod
            self.broker_proc = broker_mod.spawn_broker(
                self.cfg.broker_socket_path, root=self.root)
            self._prev_broker_client = broker_mod.set_client(
                broker_mod.SocketBrokerClient(self.cfg.broker_socket_path))
        os.makedirs(self.cfg.device_plugin_path, exist_ok=True)
        self.kubelet = DeviceManagerSim(self.cfg.device_plugin_path)
        self.driver = DraDriver(
            self.cfg, Registry(), {}, node_name=name,
            api=ApiClient(apiserver.url, token_path="/nonexistent"))

        def dra_sink(reg, gens, _d=self.driver):
            _d.set_inventory(reg, gens)
            return _d.publish_resource_slices()

        self.manager = PluginManager(
            self.cfg, on_inventory=dra_sink,
            health_listener=self.driver.apply_health)
        self.driver.attach_lifecycle(self.manager.device_lifecycle)
        self.manager.start()
        self.manager.running.set()

    def attach(self, uids: List[str]):
        claims = [drapb.Claim(namespace="fleet", name=uid, uid=uid)
                  for uid in uids]
        return self.driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=claims), None)

    def claim_devices(self, uid: str, raws: List[str]) -> None:
        names: Dict[str, str] = {}
        for v in self.driver.host_views().values():
            names.update(v.names)
        self.apiserver.add_claim(
            "fleet", uid, uid, self.driver.driver_name,
            [{"device": names[r]} for r in raws])
        resp = self.attach([uid])
        if resp.claims[uid].error:
            raise AssertionError(
                f"{self.name}: claim {uid} failed: {resp.claims[uid].error}")

    def hot_unplug(self, bdf: str) -> None:
        """PCIe surprise removal: the chip's sysfs dir AND vfio node
        vanish (the corroborated shape — a vfio flap alone stays a
        health event, PR 7)."""
        shutil.rmtree(os.path.join(self.root, "sys/bus/pci/devices", bdf),
                      ignore_errors=True)
        try:
            os.unlink(os.path.join(self.root, "dev/vfio", self.groups[bdf]))
        except FileNotFoundError:
            pass

    def tick(self) -> None:
        """One rediscovery pass, exactly the run loop's tick body."""
        self.manager._apply_inventory(self.manager._rediscover())

    def slice_log(self) -> List[tuple]:
        with self.apiserver._lock:
            return list(self.apiserver.write_log.get(
                self.driver.slice_name(), ()))

    def published_devices(self) -> set:
        with self.apiserver._lock:
            obj = self.apiserver.slices.get(self.driver.slice_name())
        return {d["name"] for d in obj["spec"]["devices"]} if obj else set()

    def kill_broker(self) -> None:
        """kill -9 the privileged broker (chaos): subsequent privileged
        operations degrade to typed BrokerUnavailable errors."""
        if self.broker_proc is None:
            raise RuntimeError(f"{self.name} is not broker-backed")
        self.broker_proc.kill()
        self.broker_proc.wait(timeout=5)

    def respawn_broker(self) -> None:
        """Respawn the broker and re-handshake the live client — the
        recovery path the acceptance criteria pin."""
        from . import broker as broker_mod
        self.broker_proc = broker_mod.spawn_broker(
            self.cfg.broker_socket_path, root=self.root)
        client = broker_mod.get_client()
        client.reconnect()

    def stop(self) -> None:
        self.manager.running.clear()
        self.manager.stop()
        self.driver.stop()
        self.kubelet.stop()
        if self.broker_proc is not None:
            from . import broker as broker_mod
            client = broker_mod.set_client(self._prev_broker_client)
            if client is not None:
                client.close()
            if self.broker_proc.poll() is None:
                self.broker_proc.terminate()
                try:
                    self.broker_proc.wait(timeout=5)
                except Exception:
                    self.broker_proc.kill()


class FleetSim:
    """N FleetNodes against one FleetApiServer, plus the storm drivers.

    `pace=False` builds the control fleet: the same pacer plumbing with
    a zero-ceiling window — throttled publishes retry IMMEDIATELY (the
    naive thundering-herd client) so paced-vs-unpaced comparisons
    differ only in the admission window adaptation.
    """

    def __init__(self, n_nodes: int, devices_per_node: int = 4,
                 latency_s: float = DEFAULT_LATENCY_S,
                 max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 congestion_k: int = 0,
                 pace: bool = True, pace_max_s: float = 2.0,
                 pace_base_s: float = 0.0,
                 seed: int = 0, root: Optional[str] = None,
                 build_workers: int = 16, device_id: str = "0063",
                 watch: bool = False, watch_resync_s: float = 5.0,
                 watch_poll_s: float = 0.5, watch_timeout_s: float = 2.0,
                 bookmark_interval_s: float = 0.5,
                 pod_dims: Optional[tuple] = None):
        self.n_nodes = n_nodes
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="tdpfleet-")
        # the pod-level host grid: node i sits at (i // cols, i % cols),
        # wrap-around ICI links closing each axis (the fleetplace mesh
        # model). Default: the tightest near-square grid holding the
        # fleet (256 nodes -> 16x16).
        if pod_dims is None:
            cols = math.isqrt(n_nodes - 1) + 1 if n_nodes > 1 else 1
            pod_dims = (-(-n_nodes // cols), cols)
        self.pod_dims = tuple(pod_dims)
        cols = self.pod_dims[-1]
        self.apiserver = FleetApiServer(
            latency_s=latency_s, max_inflight=max_inflight,
            congestion_k=congestion_k,
            bookmark_interval_s=bookmark_interval_s)
        with futures.ThreadPoolExecutor(
                max_workers=min(build_workers, max(1, n_nodes))) as pool:
            self.nodes: List[FleetNode] = list(pool.map(
                lambda i: FleetNode(self.root, i, self.apiserver,
                                    n_devices=devices_per_node,
                                    pace_max_s=pace_max_s,
                                    pace_base_s=pace_base_s,
                                    pace=pace, seed=seed,
                                    device_id=device_id,
                                    watch=watch,
                                    watch_resync_s=watch_resync_s,
                                    watch_poll_s=watch_poll_s,
                                    watch_timeout_s=watch_timeout_s,
                                    host_coords=(i // cols, i % cols)),
                range(n_nodes)))

    def _storm(self, fn) -> List:
        """Run fn(node) on every node concurrently, all released from
        one barrier (the coordinated-storm shape). Exceptions propagate
        — a storm that errored must fail the run, not vanish into a
        worker thread."""
        barrier = threading.Barrier(self.n_nodes)

        def run_one(node):
            barrier.wait(timeout=60)
            return fn(node)

        with futures.ThreadPoolExecutor(max_workers=self.n_nodes) as pool:
            return list(pool.map(run_one, self.nodes))

    # --------------------------------------------------------- scenarios

    def boot_storm(self) -> dict:
        t0 = time.monotonic()
        results = self._storm(lambda n: n.boot())
        wall_s = time.monotonic() - t0
        audit = self.apiserver.exactly_once_audit()
        return {
            "nodes": self.n_nodes,
            "published_ok": sum(bool(r) for r in results),
            "wall_s": round(wall_s, 3),
            "apiserver": self.apiserver.snapshot(),
            "pacing": self.pacer_totals(),
            "exactly_once": audit["exactly_once"],
            "audit": audit,
        }

    def attach_storm(self, claims_per_node: int, wave: int = 0) -> dict:
        uids_by_node = {n.index: n.register_claims(claims_per_node, wave)
                        for n in self.nodes}
        commits_before = sum(
            n.driver.checkpoint_stats()["checkpoint_commits_total"]
            for n in self.nodes)
        t0 = time.monotonic()

        def attach(node):
            """One node's storm contribution, with the kubelet's retry
            behavior: NodePrepareResources claims that error (e.g. a
            throttled claim GET that exhausted the client's bounded 429
            retries) are re-prepared — prepare is idempotent — until all
            land or the retry budget is spent. Returns (errors, retries)."""
            pending = list(uids_by_node[node.index])
            retries = 0
            failures: List[str] = []
            for round_no in range(6):
                resp = node.attach(pending)
                failures = [uid for uid in pending
                            if resp.claims[uid].error]
                if not failures:
                    return [], retries
                retries += len(failures)
                pending = failures
                time.sleep(0.05 * (round_no + 1))
            return [f"{uid}: {resp.claims[uid].error}"
                    for uid in failures], retries

        results = self._storm(attach)
        errors = [e for errs, _ in results for e in errs]
        retried = sum(r for _, r in results)
        wall_s = time.monotonic() - t0
        commits = sum(
            n.driver.checkpoint_stats()["checkpoint_commits_total"]
            for n in self.nodes) - commits_before
        total = claims_per_node * self.n_nodes
        return {
            "nodes": self.n_nodes,
            "claims_per_node": claims_per_node,
            "claims_total": total,
            "errors": errors,
            "claim_retries": retried,
            "wall_s": round(wall_s, 3),
            "claims_per_s": round(total / max(1e-9, wall_s), 1),
            "checkpoint_commits": commits,
            "prepared_total": sum(n.driver.prepared_claim_count()
                                  for n in self.nodes),
        }

    def flip_wave(self, flips_per_node: int) -> dict:
        writes_before = self.apiserver.snapshot()["accepted_writes"]
        t0 = time.monotonic()
        self._storm(lambda n: n.flip_storm(flips_per_node))
        self.settle()
        wall_s = time.monotonic() - t0
        converged = self.assert_converged()
        return {
            "nodes": self.n_nodes,
            "flips_per_node": flips_per_node,
            "wall_s": round(wall_s, 3),
            "accepted_writes": (self.apiserver.snapshot()["accepted_writes"]
                                - writes_before),
            "pacing": self.pacer_totals(),
            "converged": converged,
            "exactly_once":
                self.apiserver.exactly_once_audit()["exactly_once"],
        }

    def drain_upgrade_wave(self, wave_size: int) -> dict:
        """Rolling drain → upgrade → restore in wave_size-node groups
        (the fleet rollout shape); claims survive every upgrade by
        assertion inside FleetNode.upgrade."""
        t0 = time.monotonic()
        waves = 0
        for start in range(0, self.n_nodes, wave_size):
            group = self.nodes[start:start + wave_size]
            waves += 1
            barrier = threading.Barrier(len(group))

            def roll(node, barrier=barrier):
                barrier.wait(timeout=60)
                node.drain()
                ok = node.upgrade()
                node.restore()
                return ok

            with futures.ThreadPoolExecutor(
                    max_workers=len(group)) as pool:
                list(pool.map(roll, group))
        self.settle()
        wall_s = time.monotonic() - t0
        return {
            "nodes": self.n_nodes,
            "wave_size": wave_size,
            "waves": waves,
            "wall_s": round(wall_s, 3),
            "converged": self.assert_converged(),
            "exactly_once":
                self.apiserver.exactly_once_audit()["exactly_once"],
            "prepared_total": sum(n.driver.prepared_claim_count()
                                  for n in self.nodes),
        }

    # ---------------------------------------- multi-host slice placement

    def host_views(self) -> List["placement.HostView"]:
        return [n.host_view() for n in self.nodes]

    def _node_by_name(self) -> Dict[str, FleetNode]:
        return {n.name: n for n in self.nodes}

    def prepare_slice(self, shape, uid: str, best_effort: bool = False,
                      fail_node: Optional[str] = None) -> dict:
        """Plan + prepare one multi-host slice claim end to end.

        The fabric carries the cross-node claim record (multiclaim_begin/
        commit/abort — the exactly-once audit surface); each involved
        node's DRA driver prepares its LOCAL shard as a per-node
        sub-claim `<uid>-<node>` (the shape a real controller slices a
        multi-node allocation into, since a node driver can only prepare
        devices it owns). ALL-OR-NOTHING: any shard failure unprepares
        every already-prepared shard, deletes the sub-claims, and aborts
        the fabric record — no orphaned per-node specs survive
        (slice_residue() is the counted check).

        `fail_node` is the failure-injection knob: that node's sub-claim
        is registered against a device name the node does not publish,
        so its prepare fails deterministically mid-slice.
        """
        shape = placement.parse_shape(shape)
        plan = placement.plan_slice(shape, self.host_views(),
                                    best_effort=best_effort,
                                    pod_dims=self.pod_dims)
        if plan is None:
            return {"uid": uid, "placed": False, "reason": "unplaceable"}
        return self.execute_plan(plan, uid, fail_node=fail_node)

    def execute_plan(self, plan: "placement.SlicePlan", uid: str,
                     fail_node: Optional[str] = None,
                     observer=None, observed=None) -> dict:
        """Execute an already-made placement decision through the
        multiclaim fabric — the fleetplace.FleetScheduler executor seam
        (prepare_slice delegates here after planning locally).
        `observer(kind, uid, detail)` mirrors every lifecycle step —
        shard prepared / failed / rolled back, aborted, committed —
        into the caller's commit log, so the scheduler's cluster-wide
        exactly-once audit spans decision → per-node sub-claims →
        rollback on ONE log. `observed` ({node: placement generation},
        ISSUE 17) arms the optimistic-concurrency commit: the fabric
        refuses the commit if any planned node's placement state moved
        since the scheduler's snapshot, and the refusal unwinds exactly
        like a shard failure — prepared shards unprepared, sub-claims
        deleted, fabric record aborted, zero residue — then surfaces
        `conflict: True` so the caller replans."""
        note = observer if observer is not None \
            else (lambda kind, u, detail=None: None)
        by_node = self._node_by_name()
        self.apiserver.multiclaim_begin(uid, plan.shape, plan.shards,
                                        traceparent=trace.propagate())
        prepared: List[tuple] = []
        error = None
        for node_name, raws in plan.shards:
            node = by_node[node_name]
            sub_uid = f"{uid}-{node_name}"
            names = node.host_view().names
            devices = ["fleetsim-injected-missing-device"] \
                if node_name == fail_node else [names[r] for r in raws]
            self.apiserver.add_claim(
                "fleet", sub_uid, sub_uid, node.driver.driver_name,
                [{"device": nm} for nm in devices])
            resp = node.attach([sub_uid])
            err = resp.claims[sub_uid].error
            if err:
                error = f"{node_name}: {err}"
                note("shard_failed", uid, sub_uid)
                break
            prepared.append((node, sub_uid))
            note("shard_prepared", uid, sub_uid)
        commit = None
        conflicts = None
        if error is None:
            commit = self.apiserver.multiclaim_commit(uid,
                                                      observed=observed)
            if not commit.get("committed", True):
                conflicts = commit.get("conflicts") or []
                error = f"placement conflict on {conflicts}"
        if error is not None:
            # whole-claim rollback: unprepare is idempotent and durable
            # (the deletion rides the group commit before ACK), so after
            # this loop NO node's checkpoint or CDI dir knows the claim
            for node, sub_uid in prepared:
                resp = node.detach([sub_uid])
                if resp.claims[sub_uid].error:
                    raise AssertionError(
                        f"rollback unprepare of {sub_uid} failed: "
                        f"{resp.claims[sub_uid].error}")
                note("shard_rolled_back", uid, sub_uid)
            # ... and neither does the fabric: every registered sub-claim
            # (prepared or not, including the failed node's) is deleted,
            # like the controller garbage-collecting its slice of an
            # aborted allocation
            for node_name, _raws in plan.shards:
                self.apiserver.remove_claim("fleet", f"{uid}-{node_name}")
            self.apiserver.multiclaim_abort(uid, error)
            note("aborted", uid, error)
            out = {"uid": uid, "placed": False, "rolled_back": True,
                   "error": error,
                   "residue": self.slice_residue(uid)}
            if conflicts is not None:
                out["conflict"] = True
                out["conflicts"] = conflicts
                out["placement_gens"] = commit.get("gens") or {}
            return out
        note("committed", uid, None)
        return {"uid": uid, "placed": True, "score": plan.score,
                "hosts": plan.hosts,
                "shards": [(node, list(raws))
                           for node, raws in plan.shards],
                "sub_claims": [sub for _n, sub in prepared],
                "placement": commit}

    def execute_wave(self, items, observer=None) -> Dict[str, dict]:
        """Batched-commit executor seam (ISSUE 17): prepare every
        wave member's shards, then settle the whole wave through ONE
        multiclaim_commit_batch round (one amortized fabric crossing).
        `items` is a list of {plan, uid, observed, traceparent?};
        returns {uid: result} shaped exactly like execute_plan. A CAS
        loser is rolled back as cleanly as a lone conflicted claim; a
        shard-prepare failure aborts that member before the commit
        round (it never reaches the batch)."""
        note = observer if observer is not None \
            else (lambda kind, u, detail=None: None)
        by_node = self._node_by_name()
        results: Dict[str, dict] = {}
        ready: List[dict] = []
        for item in items:
            plan, uid = item["plan"], item["uid"]
            self.apiserver.multiclaim_begin(
                uid, plan.shape, plan.shards,
                traceparent=item.get("traceparent") or trace.propagate())
            prepared: List[tuple] = []
            error = None
            for node_name, raws in plan.shards:
                node = by_node[node_name]
                sub_uid = f"{uid}-{node_name}"
                names = node.host_view().names
                self.apiserver.add_claim(
                    "fleet", sub_uid, sub_uid, node.driver.driver_name,
                    [{"device": names[r]} for r in raws])
                resp = node.attach([sub_uid])
                err = resp.claims[sub_uid].error
                if err:
                    error = f"{node_name}: {err}"
                    note("shard_failed", uid, sub_uid)
                    break
                prepared.append((node, sub_uid))
                note("shard_prepared", uid, sub_uid)
            if error is not None:
                results[uid] = self._unwind_wave_member(
                    plan, uid, prepared, error, note)
                continue
            ready.append(dict(item, prepared=prepared))
        if ready:
            commits = self.apiserver.multiclaim_commit_batch(
                [(item["uid"], item.get("observed")) for item in ready])
            for item in ready:
                plan, uid = item["plan"], item["uid"]
                commit = commits[uid]
                if commit.get("committed", True):
                    note("committed", uid, None)
                    results[uid] = {
                        "uid": uid, "placed": True, "score": plan.score,
                        "hosts": plan.hosts,
                        "shards": [(n, list(r)) for n, r in plan.shards],
                        "sub_claims": [s for _n, s in item["prepared"]],
                        "placement": commit}
                else:
                    conflicts = commit.get("conflicts") or []
                    out = self._unwind_wave_member(
                        plan, uid, item["prepared"],
                        f"placement conflict on {conflicts}", note)
                    out["conflict"] = True
                    out["conflicts"] = conflicts
                    out["placement_gens"] = commit.get("gens") or {}
                    results[uid] = out
        return results

    def _unwind_wave_member(self, plan, uid, prepared, error,
                            note) -> dict:
        """Shared all-or-nothing unwind for a wave member that failed
        prepare or lost its CAS: identical guarantees to the
        execute_plan rollback path."""
        for node, sub_uid in prepared:
            resp = node.detach([sub_uid])
            if resp.claims[sub_uid].error:
                raise AssertionError(
                    f"rollback unprepare of {sub_uid} failed: "
                    f"{resp.claims[sub_uid].error}")
            note("shard_rolled_back", uid, sub_uid)
        for node_name, _raws in plan.shards:
            self.apiserver.remove_claim("fleet", f"{uid}-{node_name}")
        self.apiserver.multiclaim_abort(uid, error)
        note("aborted", uid, error)
        return {"uid": uid, "placed": False, "rolled_back": True,
                "error": error, "residue": self.slice_residue(uid)}

    def release_subclaims(self, pairs) -> List[dict]:
        """Release node-level sub-claims by explicit (sub_uid, node)
        identity — the scheduler's tenant-departure path, correct even
        after defrag migrations moved a sub-claim to a host other than
        the one its id was minted on. Idempotent like unprepare.
        Returns the fabric's restamp deltas (accountant feedback)."""
        by_node = self._node_by_name()
        for sub_uid, node_name in pairs:
            node = by_node[node_name]
            resp = node.detach([sub_uid])
            if resp.claims[sub_uid].error:
                raise AssertionError(
                    f"release unprepare of {sub_uid} on {node_name} "
                    f"failed: {resp.claims[sub_uid].error}")
            self.apiserver.remove_claim("fleet", sub_uid)
        # free any CAS-registered chips the departing parents owned
        # (idempotent no-op for legacy non-CAS placements); the restamp
        # deltas go back to the releasing scheduler so its views free
        # the chips synchronously (the watch event then lands as an
        # unchanged-identity skip)
        deltas: List[dict] = []
        for parent in sorted({sub_uid[:-(len(node_name) + 1)]
                              for sub_uid, node_name in pairs
                              if sub_uid.endswith(f"-{node_name}")}):
            rec = self.apiserver.release_placement(parent)
            deltas.extend(rec.get("slices") or ())
        return deltas

    def release_plan(self, uid: str, shards) -> None:
        """Release a committed multi-host claim's per-node sub-claims
        by their placement-time (node, raws) shards — callers that
        tracked migrations use release_subclaims directly."""
        self.release_subclaims([(f"{uid}-{node_name}", node_name)
                                for node_name, _raws in shards])

    def _views_by_gen(self) -> Dict[str, List["placement.HostView"]]:
        """Every node's driver-side host views grouped by generation —
        the scheduler's views_source when no watch plane is wired."""
        out: Dict[str, List["placement.HostView"]] = {}
        for node in self.nodes:
            for gen, view in node.driver.host_views().items():
                out.setdefault(gen, []).append(view)
        return out

    def scheduler(self, watch: bool = True, resync_s: float = 5.0,
                  poll_s: float = 0.5, timeout_s: float = 2.0,
                  **sched_kwargs):
        """Build the fleet placement control plane over THIS fleet
        (fleetplace.FleetScheduler): decisions consume the PR 12
        watch-stream Reflector's slice cache — LIST seeds it, watch
        events converge it, published topology attributes rebuild the
        host grids — and execute through the multiclaim fabric.
        `watch=False` falls back to direct driver views (deterministic
        unit tests without a reflector thread). Extra keyword args
        (shard_index/shard_count/partition/wave knobs, ISSUE 17) pass
        through to the FleetScheduler — build one per shard over the
        same fabric for a sharded control plane."""
        from .fleetplace import FleetScheduler, SliceCache
        from .kubeapi import Reflector
        if not watch:
            return FleetScheduler(executor=self,
                                  views_source=self._views_by_gen,
                                  pod_dims=self.pod_dims,
                                  **sched_kwargs)
        cache = SliceCache(pod_dims=self.pod_dims)
        api = ApiClient(self.apiserver.url, token_path="/nonexistent")
        reflector = Reflector(
            api, "/apis/resource.k8s.io/v1beta1/resourceslices",
            on_event=cache.on_event, on_sync=cache.on_sync,
            name="fleetplace-slices", resync_interval_s=resync_s,
            poll_interval_s=poll_s, watch_timeout_s=timeout_s)
        return FleetScheduler(executor=self, cache=cache,
                              reflector=reflector,
                              pod_dims=self.pod_dims,
                              **sched_kwargs)

    def fleet_flight(self):
        """The fleet's trace collector (fleetplace.FleetFlight). This
        in-process sim shares ONE recorder across every node, so the
        collector reads it ONCE per query (a per-node source each
        re-merging the same rings would cost N+1 full scans for an
        identical result — the dedupe would collapse them anyway) and
        labels each record by the ``node`` attr its driver stamps on
        every RPC root / repair span; control-plane spans carry no node
        attr and label as ``scheduler``. Production fleets register
        add_http_source per daemon — that is where multi-source merging
        actually happens, under the same /debug/flight body shape this
        source serves."""
        from .fleetplace import FleetFlight
        ff = FleetFlight()
        ff.add_source(
            "scheduler",
            lambda query: {"spans": trace.snapshot(
                trace=query.get("trace"))})
        return ff

    def slice_residue(self, uid: str) -> List[str]:
        """State left behind by multi-host claim `uid`: per-node sub-claim
        checkpoint entries, CDI spec files, or fabric claim records.
        Empty after a clean commit-less rollback — THE no-orphaned-specs
        assertion."""
        residue = []
        for node in self.nodes:
            sub_uid = f"{uid}-{node.name}"
            if sub_uid in node.driver._checkpoint:
                residue.append(f"{node.name}:checkpoint:{sub_uid}")
            if os.path.exists(node.driver._claim_spec_path(sub_uid)):
                residue.append(f"{node.name}:spec:{sub_uid}")
            with self.apiserver._lock:
                stale = ("fleet", sub_uid) in self.apiserver.claims
            if stale:
                residue.append(f"fabric:claim:{sub_uid}")
        return residue

    def propose_defrag(self, shape) -> dict:
        """Cluster-wide defrag advisory over every node's view (the
        per-node /debug/defrag serves the same proposal with only its
        own view; here migration targets resolve across the fleet)."""
        return placement.propose_defrag(placement.parse_shape(shape),
                                        self.host_views())

    def apply_defrag(self, proposal: dict,
                     deltas_out: Optional[List[dict]] = None) -> int:
        """Apply a defrag advisory by riding the PR 7 migration-handoff
        machinery claim by claim: unprepare at the source (emits the
        durable handoff record), re-point the fabric claim at the target
        devices, import the record at the destination, and prepare there
        (which VALIDATES the handoff — uid + allocation generation —
        before attaching, and counts handoffs_completed_total). Returns
        the number of migrations applied."""
        by_node = self._node_by_name()
        moves = 0
        for mig in proposal.get("migrations", ()):
            uid = mig["claim"]
            if mig.get("target_node") is None:
                raise AssertionError(
                    f"migration of {uid} has no target (free capacity "
                    f"exhausted); cannot apply")
            src = by_node[mig["source_node"]]
            dst = by_node[mig["target_node"]]
            resp = src.detach([uid])
            if resp.claims[uid].error:
                raise AssertionError(
                    f"defrag unprepare of {uid} on {src.name} failed: "
                    f"{resp.claims[uid].error}")
            record = src.driver.export_handoff(uid)
            names = dst.host_view().names
            self.apiserver.add_claim(
                "fleet", uid, uid, dst.driver.driver_name,
                [{"device": names[r]} for r in mig["target_devices"]])
            if record is not None:
                dst.driver.import_handoff(record)
            resp = dst.attach([uid])
            if resp.claims[uid].error:
                raise AssertionError(
                    f"defrag prepare of {uid} on {dst.name} failed: "
                    f"{resp.claims[uid].error}")
            # keep the CAS placement registry truthful across the move
            # (no-op for claims that never CAS-committed)
            rec = self.apiserver.move_placement(
                mig["source_node"], mig["target_node"],
                mig.get("devices") or (), mig["target_devices"])
            if deltas_out is not None:
                deltas_out.extend(rec.get("slices") or ())
            moves += 1
        return moves

    # ------------------------------------------------------------- audit

    def _expected_devices(self, node: FleetNode) -> set:
        return {slice_device_name(b) for b in node.bdfs} \
            - {slice_device_name(b)
               for b in node.driver.unhealthy_devices()}

    def _node_matches(self, node: FleetNode) -> bool:
        with self.apiserver._lock:
            obj = self.apiserver.slices.get(node.driver.slice_name())
        if obj is None:
            return False
        return {d["name"] for d in obj["spec"]["devices"]} \
            == self._expected_devices(node)

    def settle(self, rounds: int = 5) -> None:
        """Compress the production republish-retry timer: a publish that
        exhausted its throttle budget under a storm returns False and
        arms a jittered 5-30 s retry (dra._arm_republish_retry) — far
        too slow for a deterministic storm assertion. Re-drive exactly
        the nodes whose slice does not yet match; an already-matching
        node's republish is a no-op GET (unchanged projection), so
        settling never disturbs the exactly-once write audit."""
        for _ in range(rounds):
            pending = [n for n in self.nodes
                       if not self._node_matches(n)]
            if not pending:
                return
            for node in pending:
                node.driver.publish_resource_slices()

    def assert_converged(self) -> bool:
        """Every node's published slice must advertise exactly its
        healthy device set (counted, not timed)."""
        for node in self.nodes:
            name = node.driver.slice_name()
            with self.apiserver._lock:
                obj = self.apiserver.slices.get(name)
            if obj is None:
                raise AssertionError(f"{node.name}: slice missing")
            published = {d["name"] for d in obj["spec"]["devices"]}
            expected = self._expected_devices(node)
            if published != expected:
                raise AssertionError(
                    f"{node.name}: slice devices {sorted(published)} != "
                    f"expected {sorted(expected)}")
        return True

    def rolling_upgrade_wave(self, batch_size: int = 16,
                             warm: bool = True,
                             sysfs_read_cost_s: float = 0.0) -> dict:
        """Rolling daemon upgrade across the fleet: batches of nodes
        restart concurrently WITH their discovery cost
        (FleetNode.restart_with_discovery) while the rest keep serving —
        the fleet-operations shape of the restart-to-ready problem. The
        headline is aggregate node-seconds-unready: sum over nodes of
        the stop→republished wall, the capacity the wave takes offline.
        `warm=False` is the pre-snapshot baseline (every node pays the
        full cold walk + identity reads every upgrade);
        `sysfs_read_cost_s` models per-access host IO (see
        restart_with_discovery) and is recorded in the result."""
        unready: List[float] = []
        reads_total = 0
        paths: Dict[str, int] = {}
        t0 = time.monotonic()
        for start in range(0, self.n_nodes, batch_size):
            nodes = self.nodes[start:start + batch_size]
            with futures.ThreadPoolExecutor(
                    max_workers=len(nodes),
                    thread_name_prefix="fleet-upgrade") as pool:
                results = list(pool.map(
                    lambda n: n.restart_with_discovery(
                        warm=warm, sysfs_read_cost_s=sysfs_read_cost_s),
                    nodes))
            for r in results:
                unready.append(r["unready_s"])
                reads_total += r["reads"]
                paths[r["path"]] = paths.get(r["path"], 0) + 1
        mid = sorted(unready)
        return {
            "nodes": self.n_nodes,
            "batch_size": batch_size,
            "warm": warm,
            "sysfs_read_cost_ms": round(sysfs_read_cost_s * 1e3, 3),
            "wall_s": round(time.monotonic() - t0, 3),
            "node_seconds_unready": round(sum(unready), 4),
            "p50_unready_ms": round(mid[len(mid) // 2] * 1e3, 3),
            "max_unready_ms": round(max(unready) * 1e3, 3),
            "reads_total": reads_total,
            "paths": paths,
        }

    def pacer_totals(self) -> dict:
        totals = {"publish_waves_total": 0, "publishes_coalesced_total": 0,
                  "publish_throttled_total": 0, "pacing_delays_total": 0}
        for node in self.nodes:
            snap = node.pacer_stats()
            for key in totals:
                totals[key] += snap[key]
        return totals

    def watch_totals(self) -> dict:
        """Fleet-wide watch-plane counters (sums of every driver's
        watch_stats; `watch_degraded_nodes` counts nodes currently in
        the degraded paced-relist mode)."""
        totals: Dict[str, int] = {"watch_degraded_nodes": 0}
        for node in self.nodes:
            snap = node.driver.watch_stats()
            totals["watch_degraded_nodes"] += snap.pop(
                "watch_degraded_mode", 0)
            snap.pop("enabled", None)
            for key, value in snap.items():
                totals[key] = totals.get(key, 0) + value
        return totals

    def stop(self) -> None:
        # node.stop() blocks on reflector/server joins that can each
        # wait out an in-flight relist against a congested fabric; at
        # fleet scale a serial march multiplies that into minutes, so
        # tear nodes down in parallel and keep the fabric up until the
        # last node has let go of it
        if len(self.nodes) > 1:
            with futures.ThreadPoolExecutor(
                    max_workers=min(32, len(self.nodes)),
                    thread_name_prefix="fleet-stop") as pool:
                list(pool.map(lambda node: node.stop(), self.nodes))
        else:
            for node in self.nodes:
                node.stop()
        self.apiserver.stop()
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)


# ---------------------------------------- continuous invariants (ISSUE 12)


def fleet_invariants(sim: FleetSim, torn_down_multiclaims=(),
                     confirm=None) -> dict:
    """One pass of the soak invariant checks, shared by the autopilot's
    continuous checker and the fleet-soak suite — asserted DURING a run,
    not only at its end:

      1. exactly-once fabric write audit (strictly-increasing, never-
         duplicated slice generations);
      2. exactly-once multiclaim audit (≤1 commit per uid, begin-first);
      3. zero residue for TORN-DOWN multiclaims (aborted or fully
         unprepared): no per-node checkpoint entries, CDI specs, or
         fabric sub-claim records survive;
      4. checkpoint/fabric claim agreement: every non-orphaned prepared
         claim on every node is known to the fabric's claim registry —
         a prepared claim the fabric forgot is a LOST claim;
      5. zero orphaned spec files: every per-claim CDI spec on disk
         belongs to a checkpointed claim.

    Checks 4 and 5 race in-flight prepares by design (a spec is written
    moments before its checkpoint entry); suspects are therefore
    re-verified once through `confirm` (a callable run between the two
    looks, default ~50 ms sleep) and only REPEATED offenders are
    violations. Returns {"ok", "violations", "orphaned_claims",
    "prepared_total", "audit", "multiclaim"}."""
    if confirm is None:
        confirm = lambda: time.sleep(0.05)   # noqa: E731
    violations: List[str] = []
    audit = sim.apiserver.exactly_once_audit()
    if not audit["exactly_once"]:
        violations.append(
            f"fabric write audit: duplicated={audit['duplicated_generations']}"
            f" regressed={audit['regressed_generations']}")
    maudit = sim.apiserver.multiclaim_audit()
    if not maudit["exactly_once"]:
        violations.append(
            f"multiclaim audit: duplicated={maudit['duplicated_commits']} "
            f"unbegun={maudit['unbegun_commits']}")
    for uid in torn_down_multiclaims:
        residue = sim.slice_residue(uid)
        if residue:
            violations.append(f"multiclaim {uid} residue: {residue}")

    def _suspects():
        found: List[tuple] = []
        with sim.apiserver._lock:
            fabric_claims = {name for (_ns, name) in sim.apiserver.claims}
        orphaned = 0
        prepared = 0
        for node in sim.nodes:
            driver = node.driver
            checkpoint = dict(driver._checkpoint)   # C-atomic copy
            for uid, entry in checkpoint.items():
                if "orphaned" in entry:
                    orphaned += 1
                    continue
                prepared += 1
                if uid not in fabric_claims:
                    found.append(("lost", node.name, uid))
            prefix = f"{driver._driver_fs}-claim-"
            try:
                names = os.listdir(driver.cdi_dir)
            except OSError:
                names = []
            for fn in names:
                if not (fn.startswith(prefix) and fn.endswith(".json")):
                    continue
                uid = fn[len(prefix):-len(".json")]
                if uid not in checkpoint:
                    found.append(("orphan-spec", node.name, uid))
        return found, orphaned, prepared

    # the clean case (no suspects) pays exactly one full-fleet sweep —
    # this runs every invariant_interval_s at soak scale, so the counts
    # ride along with whichever pass ran last instead of a third sweep
    first, orphaned, prepared = _suspects()
    if first:
        confirm()
        second, orphaned, prepared = _suspects()
        for kind, node_name, uid in sorted(set(first) & set(second)):
            if kind == "lost":
                violations.append(
                    f"{node_name}: claim {uid} prepared in the checkpoint "
                    f"but unknown to the fabric (lost claim)")
            else:
                violations.append(
                    f"{node_name}: claim spec {uid} on disk with no "
                    f"checkpoint entry (orphaned spec)")
    return {"ok": not violations, "violations": violations,
            "orphaned_claims": orphaned, "prepared_total": prepared,
            "audit": audit, "multiclaim": maudit}


def assert_fleet_invariants(sim: FleetSim,
                            torn_down_multiclaims=()) -> dict:
    """fleet_invariants, raising AssertionError on any violation."""
    report = fleet_invariants(sim, torn_down_multiclaims)
    if not report["ok"]:
        raise AssertionError("fleet invariants violated: "
                             + "; ".join(report["violations"]))
    return report


# ====================================================================
# synthetic scheduler-tier fleet (ISSUE 17: 4096-node storms)
# ====================================================================


def synthetic_slice_objects(n_nodes: int, devices_per_node: int = 8,
                            generation: str = "v5e",
                            pod_dims: Optional[tuple] = None):
    """Mint `n_nodes` ResourceSlice objects in EXACTLY the shape
    dra._device_entry publishes (v1beta1 basic-nested typed attributes:
    generation/bdf/ici*/torus*/ringSize/hostId/host*), so
    fleetplace._parse_slice_grids sees a synthetic fleet and a
    driver-published one identically. Per-host chips form the tightest
    near-square 2D torus holding `devices_per_node`; hosts sit on the
    near-square pod grid FleetSim uses (node i at (i // cols, i %
    cols)). Returns (objects, pod_dims)."""
    if pod_dims is None:
        cols = math.isqrt(n_nodes - 1) + 1 if n_nodes > 1 else 1
        pod_dims = (-(-n_nodes // cols), cols)
    pod_dims = tuple(pod_dims)
    cols = pod_dims[-1]
    rows = 1
    for d in range(math.isqrt(devices_per_node), 0, -1):
        if devices_per_node % d == 0:
            rows = d
            break
    dims = (rows, devices_per_node // rows)
    objs = []
    for i in range(n_nodes):
        node = f"node-{i:04d}"
        host = (i // cols, i % cols)
        devices = []
        for j in range(devices_per_node):
            coords = (j // dims[1], j % dims[1])
            bdf = f"0000:{j:02x}:00.0"
            attrs = {
                "type": {"string": "passthrough"},
                "generation": {"string": generation},
                "bdf": {"string": bdf},
                "iciX": {"int": coords[0]},
                "iciY": {"int": coords[1]},
                "torusX": {"int": dims[0]},
                "torusY": {"int": dims[1]},
                "ringSize": {"int": max(dims)},
                "hostId": {"string": node},
                "hostX": {"int": host[0]},
                "hostY": {"int": host[1]},
            }
            devices.append({"name": f"{node}-tpu{j}",
                            "basic": {"attributes": attrs}})
        objs.append({
            "kind": "ResourceSlice",
            "metadata": {"name": f"{node}-slice"},
            "spec": {"nodeName": node,
                     "pool": {"name": node, "generation": 1},
                     "driver": "tpu.fleetsim.synthetic",
                     "devices": devices}})
    return objs, pod_dims


class SyntheticFleet:
    """Scheduler-tier harness at fleet scale: a REAL FleetApiServer
    fabric (watch plane, CAS placement registry, all three audit logs)
    seeded with synthetic node slices — no per-node daemons, no sysfs
    roots, no CDI dirs — so 4096-node / 16k-claim scheduling storms
    run in one process. A checkpoint ledger stands in for the node
    drivers' prepare/unprepare, giving the triple exactly-once audit
    (multiclaim commit log, per-slice write log, checkpoint) the same
    teeth FleetSim's real drivers give it, and the executor seam
    (execute_plan / execute_wave / release_subclaims / slice_residue)
    keeps FleetSim's all-or-nothing unwind contract bit-for-bit: a CAS
    loser or prepare failure leaves zero residue."""

    def __init__(self, n_nodes: int, devices_per_node: int = 8,
                 pod_dims: Optional[tuple] = None,
                 generation: str = "v5e",
                 commit_crossing_s: float = 0.0,
                 latency_s: float = 0.0,
                 watch_backlog: int = 65536,
                 watch_queue_max: int = 16384):
        objs, dims = synthetic_slice_objects(
            n_nodes, devices_per_node, generation=generation,
            pod_dims=pod_dims)
        self.n_nodes = n_nodes
        self.pod_dims = dims
        self.apiserver = FleetApiServer(
            latency_s=latency_s,
            commit_crossing_s=commit_crossing_s,
            watch_backlog=watch_backlog,
            watch_queue_max=watch_queue_max)
        self.apiserver.seed_slices(objs)
        self._ckpt_lock = threading.Lock()
        # node -> {sub_uid: sorted raws} — the stand-in for each node
        # driver's durable checkpoint
        self.checkpoints: Dict[str, Dict[str, list]] = {}
        # append-only (action, node, sub_uid): the replayable third
        # audit log
        self.checkpoint_log: List[tuple] = []
        self._schedulers: List = []

    # ------------------------------------------------- executor seam

    def execute_plan(self, plan: "placement.SlicePlan", uid: str,
                     fail_node: Optional[str] = None,
                     observer=None, observed=None) -> dict:
        """FleetSim.execute_plan's contract over the synthetic
        checkpoint ledger: a wave of one."""
        return self.execute_wave(
            [{"plan": plan, "uid": uid, "observed": observed,
              "fail_node": fail_node}],
            observer=observer)[uid]

    def execute_wave(self, items, observer=None) -> Dict[str, dict]:
        """Batched-commit executor seam: checkpoint-prepare every wave
        member's shards, then settle the whole wave through ONE
        multiclaim_commit_batch round. CAS losers and prepare failures
        unwind to zero residue before the result is returned."""
        note = observer if observer is not None \
            else (lambda kind, u, detail=None: None)
        results: Dict[str, dict] = {}
        ready: List[dict] = []
        for item in items:
            plan, uid = item["plan"], item["uid"]
            self.apiserver.multiclaim_begin(
                uid, plan.shape, plan.shards,
                traceparent=item.get("traceparent") or trace.propagate())
            prepared: List[tuple] = []
            error = None
            for node_name, raws in plan.shards:
                sub_uid = f"{uid}-{node_name}"
                if node_name == item.get("fail_node"):
                    error = f"{node_name}: injected prepare failure"
                    note("shard_failed", uid, sub_uid)
                    break
                with self._ckpt_lock:
                    node_ckpt = self.checkpoints.setdefault(node_name, {})
                    if sub_uid in node_ckpt:
                        error = (f"{node_name}: duplicate prepare of "
                                 f"{sub_uid}")
                        note("shard_failed", uid, sub_uid)
                        break
                    node_ckpt[sub_uid] = sorted(raws)
                    self.checkpoint_log.append(
                        ("prepare", node_name, sub_uid))
                prepared.append((node_name, sub_uid))
                note("shard_prepared", uid, sub_uid)
            if error is not None:
                results[uid] = self._unwind_member(uid, prepared,
                                                   error, note)
                continue
            ready.append(dict(item, prepared=prepared))
        if ready:
            commits = self.apiserver.multiclaim_commit_batch(
                [(item["uid"], item.get("observed")) for item in ready])
            for item in ready:
                plan, uid = item["plan"], item["uid"]
                commit = commits[uid]
                if commit.get("committed", True):
                    note("committed", uid, None)
                    results[uid] = {
                        "uid": uid, "placed": True, "score": plan.score,
                        "hosts": plan.hosts,
                        "shards": [(n, list(r)) for n, r in plan.shards],
                        "sub_claims": [s for _n, s in item["prepared"]],
                        "placement": commit}
                else:
                    conflicts = commit.get("conflicts") or []
                    out = self._unwind_member(
                        uid, item["prepared"],
                        f"placement conflict on {conflicts}", note)
                    out["conflict"] = True
                    out["conflicts"] = conflicts
                    out["placement_gens"] = commit.get("gens") or {}
                    results[uid] = out
        return results

    def _unwind_member(self, uid, prepared, error, note) -> dict:
        """All-or-nothing unwind: every prepared checkpoint entry is
        rolled back (a rollback of an entry the ledger does not hold is
        an invariant violation, not a no-op), the fabric record
        aborted — then the member's residue is re-checked empty."""
        with self._ckpt_lock:
            for node_name, sub_uid in prepared:
                if self.checkpoints.get(node_name, {}).pop(
                        sub_uid, None) is None:
                    raise AssertionError(
                        f"rollback of {sub_uid}: not in checkpoint")
                self.checkpoint_log.append(
                    ("rollback", node_name, sub_uid))
        for _node_name, sub_uid in prepared:
            note("shard_rolled_back", uid, sub_uid)
        self.apiserver.multiclaim_abort(uid, error)
        note("aborted", uid, error)
        return {"uid": uid, "placed": False, "rolled_back": True,
                "error": error, "residue": self.slice_residue(uid)}

    def release_subclaims(self, pairs) -> List[dict]:
        """Tenant departure: drop the checkpoint entries, then free the
        parents' CAS-registered chips (idempotent, like unprepare).
        Returns the fabric's restamp deltas (accountant feedback)."""
        with self._ckpt_lock:
            for sub_uid, node_name in pairs:
                if self.checkpoints.get(node_name, {}).pop(
                        sub_uid, None) is not None:
                    self.checkpoint_log.append(
                        ("release", node_name, sub_uid))
        deltas: List[dict] = []
        for parent in sorted({sub_uid[:-(len(node_name) + 1)]
                              for sub_uid, node_name in pairs
                              if sub_uid.endswith(f"-{node_name}")}):
            rec = self.apiserver.release_placement(parent)
            deltas.extend(rec.get("slices") or ())
        return deltas

    def slice_residue(self, uid: str) -> List[str]:
        """Checkpoint entries left behind by multi-host claim `uid` —
        empty after a clean rollback, THE no-orphaned-sub-claims
        assertion (FleetSim.slice_residue's contract minus the specs/
        fabric-claims planes this harness does not model)."""
        prefix = f"{uid}-"
        residue = []
        with self._ckpt_lock:
            for node_name in sorted(self.checkpoints):
                for sub_uid in self.checkpoints[node_name]:
                    if sub_uid.startswith(prefix):
                        residue.append(
                            f"{node_name}:checkpoint:{sub_uid}")
        return residue

    # ------------------------------------------------------- audits

    def checkpoint_audit(self) -> dict:
        """The THIRD exactly-once log: replaying the checkpoint
        prepare/rollback/release stream must never double-prepare a
        live sub-claim, never drop one that is not held, and must land
        exactly on the live checkpoint state."""
        with self._ckpt_lock:
            log_copy = list(self.checkpoint_log)
            live = {(n, s) for n, ckpt in self.checkpoints.items()
                    for s in ckpt}
        held: set = set()
        double_prepares: List[str] = []
        phantom_drops: List[str] = []
        for action, node_name, sub_uid in log_copy:
            key = (node_name, sub_uid)
            if action == "prepare":
                if key in held:
                    double_prepares.append(sub_uid)
                held.add(key)
            else:
                if key not in held:
                    phantom_drops.append(sub_uid)
                held.discard(key)
        matches = held == live
        return {"entries_audited": len(log_copy),
                "held": len(live),
                "double_prepares": sorted(set(double_prepares)),
                "phantom_drops": sorted(set(phantom_drops)),
                "log_matches_checkpoints": matches,
                "exactly_once": (not double_prepares
                                 and not phantom_drops and matches)}

    def audits(self) -> dict:
        """All three exactly-once audit logs in one read — what every
        bench cell folds through fleetplace.fleet_audit."""
        return {"multiclaim": self.apiserver.multiclaim_audit(),
                "writes": self.apiserver.exactly_once_audit(),
                "placement": self.apiserver.placement_audit(),
                "checkpoint": self.checkpoint_audit()}

    # ---------------------------------------------------- schedulers

    def scheduler(self, shard_index: int = 0, shard_count: int = 1,
                  partition: bool = True, resync_s: float = 30.0,
                  poll_s: float = 0.2, timeout_s: float = 2.0,
                  **sched_kwargs):
        """One shard of the sharded control plane: a watch-fed
        FleetScheduler (Reflector -> SliceCache -> FragAccountant)
        over THIS fabric. Build N of these for N-way sharding; they
        are tracked for stop()."""
        from .fleetplace import FleetScheduler, SliceCache
        from .kubeapi import Reflector
        cache = SliceCache(pod_dims=self.pod_dims)
        api = ApiClient(self.apiserver.url, token_path="/nonexistent")
        reflector = Reflector(
            api, "/apis/resource.k8s.io/v1beta1/resourceslices",
            on_event=cache.on_event, on_sync=cache.on_sync,
            name=f"fleetsched-{shard_index}",
            resync_interval_s=resync_s,
            poll_interval_s=poll_s, watch_timeout_s=timeout_s)
        sched = FleetScheduler(
            executor=self, cache=cache, reflector=reflector,
            pod_dims=self.pod_dims, shard_index=shard_index,
            shard_count=shard_count, partition=partition,
            **sched_kwargs)
        self._schedulers.append(sched)
        return sched

    def stop(self) -> None:
        for sched in self._schedulers:
            try:
                sched.stop()
            except Exception:
                pass
        self._schedulers.clear()
        self.apiserver.stop()
