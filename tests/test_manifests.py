"""Manifest/example consistency: the YAML the e2e + users apply must parse
and agree with the fixture host and the plugin's resource naming, so the
kind e2e (scripts/e2e_kind.sh) cannot drift from what the plugin serves."""

import glob
import os

import pytest

yaml = pytest.importorskip("yaml")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_all(path):
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d is not None]


def all_yaml_paths():
    return (glob.glob(os.path.join(REPO, "manifests", "**", "*.yaml"),
                      recursive=True)
            + glob.glob(os.path.join(REPO, "examples", "*.yaml")))


def test_every_manifest_parses():
    paths = all_yaml_paths()
    assert len(paths) >= 10
    for p in paths:
        docs = load_all(p)
        assert docs, f"{p} is empty"
        for d in docs:
            assert "kind" in d and "apiVersion" in d, p


def test_e2e_vmi_matches_fixture_generation():
    """The e2e VMI must request the generation the fixture host advertises
    (make_fixture_host.py default device_id 0062 -> v4, allocatable 4)."""
    vmi = load_all(os.path.join(REPO, "manifests/e2e/vmi-tpu-e2e.yaml"))[0]
    assert vmi["kind"] == "VirtualMachineInstance"
    gpus = vmi["spec"]["domain"]["devices"]["gpus"]
    assert gpus[0]["deviceName"] == "cloud-tpus.google.com/v4"
    # CI-sized: must fit a ~7 GB runner alongside KubeVirt itself
    assert vmi["spec"]["domain"]["resources"]["requests"]["memory"] == "512Mi"


def test_e2e_consumer_pod_matches_fixture_generation():
    pod = load_all(os.path.join(REPO, "manifests/e2e/tpu-consumer-pod.yaml"))[0]
    limits = pod["spec"]["containers"][0]["resources"]["limits"]
    assert limits == {"cloud-tpus.google.com/v4": "2"}


def test_kubevirt_cr_whitelists_every_generation_example():
    """The example CR must whitelist with externalResourceProvider: true —
    the whole env contract exists to serve it (reference:
    examples/kubevirt-featuregate-cm.yaml:10-18)."""
    cr = load_all(os.path.join(REPO, "examples/kubevirt-featuregate-cm.yaml"))[0]
    devs = cr["spec"]["configuration"]["permittedHostDevices"]["pciHostDevices"]
    names = {d["resourceName"] for d in devs}
    assert {"cloud-tpus.google.com/v4", "cloud-tpus.google.com/v5e",
            "cloud-tpus.google.com/v5p"} <= names
    assert all(d["externalResourceProvider"] is True for d in devs)


def test_example_vmis_use_plugin_resource_names():
    for name in ("vmi-tpu.yaml", "vmi-vtpu.yaml", "vmi-tpu-slice.yaml"):
        vmi = load_all(os.path.join(REPO, "examples", name))[0]
        gpus = vmi["spec"]["domain"]["devices"]["gpus"]
        for g in gpus:
            assert g["deviceName"].startswith("cloud-tpus.google.com/"), name
