"""remediation engine — SLO-closed-loop self-healing (ISSUE 16).

What must hold: a latched SLO breach queued via on_transition turns a
knob on tick() (pacer floor, admission throttle, node bias + drain,
defrag wave) — every turn policy-gated, exemplar-trace-linked, audited;
a latched recovery rolls the knob back; hysteresis (cool-downs, window
budget, holder sets) means no flapping and no storms; every shed is
typed and counted, never silent."""

import threading

import pytest

from tpu_device_plugin import trace
from tpu_device_plugin.policy import PolicyEngine
from tpu_device_plugin.remediation import (RemediationEngine, TokenBucket,
                                           render_prometheus)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakePacer:
    def __init__(self):
        self.floor = None
        self.cleared = 0

    def set_backoff_floor(self, floor_s):
        self.floor = floor_s

    def clear_backoff_floor(self):
        self.floor = None
        self.cleared += 1


class FakeScheduler:
    """Just the seams remediation drives; stats mimics the
    AtomicCounter dict shape."""

    class _Counter:
        def __init__(self):
            self.value = 0

    def __init__(self):
        self.stats = {"unplaceable_total": self._Counter()}
        self.biased = []
        self.cleared = []
        self.drains = []
        self.waves = []
        self.proposal = {"placeable": False, "migrations": [
            {"claim": "c1", "source_node": "node-1",
             "target_node": "node-2", "devices": [], "target_devices": []}]}

    def bias_away(self, node, reason=""):
        self.biased.append((node, reason))

    def clear_bias(self, node):
        self.cleared.append(node)

    def plan_drain(self, node, generation=None):
        self.drains.append(node)
        return {"node": node, "generation": "g1", "moves": 1,
                "resolved": 1, "migrations": [
                    {"claim": "c9", "source_node": node,
                     "target_node": "node-2", "devices": [],
                     "target_devices": []}]}

    def plan_defrag_wave(self, shape, generation=None, selector=""):
        return dict(self.proposal)

    def apply_defrag_wave(self, proposal):
        self.waves.append(proposal)
        moves = [m for m in proposal.get("migrations", ())
                 if m.get("target_node")]
        return {"wave": f"w{len(self.waves)}", "moves_planned": len(moves),
                "moves_applied": len(moves)}


class FakeFlight:
    def __init__(self, nodes=("scheduler", "node-3")):
        self.nodes = list(nodes)
        self.queries = []

    def trace(self, trace_id, limit=None):
        self.queries.append(trace_id)
        return {"trace": trace_id, "spans": [], "nodes": list(self.nodes),
                "ops": [], "sources": 1, "source_errors": {}}


TID = "ab" * 16
TID2 = "cd" * 16


def _breach(slo="attach-p99", histogram="tdp_attach_wall_ms", tid=TID):
    return {"slo": slo, "kind": "breach", "histogram": histogram,
            "burn_fast": 20.0, "burn_slow": 8.0,
            "exemplar": {"trace_id": tid, "le": 250.0, "ts": 0.0}}


def _recovered(slo="attach-p99", histogram="tdp_attach_wall_ms"):
    return {"slo": slo, "kind": "recovered", "histogram": histogram,
            "burn_fast": 0.0, "burn_slow": 0.1, "exemplar": None}


@pytest.fixture(autouse=True)
def _trace_ring():
    trace.configure(enabled=True)
    trace.reset()
    yield
    trace.reset()


def _engine(clock=None, **kw):
    clock = clock or FakeClock()
    kw.setdefault("pacer", FakePacer())
    kw.setdefault("scheduler", FakeScheduler())
    kw.setdefault("now", clock)
    return RemediationEngine(**kw), clock


# ------------------------------------------------------------ TokenBucket

def test_token_bucket_burst_then_rate_refill():
    clock = FakeClock()
    bucket = TokenBucket(rate=2.0, burst=3, now=clock)
    assert [bucket.take() for _ in range(3)] == [True, True, True]
    assert bucket.take() is False
    clock.advance(0.5)  # 1 token back at 2/s
    assert bucket.take() is True
    assert bucket.take() is False
    clock.advance(10.0)  # refill caps at burst
    assert [bucket.take() for _ in range(3)] == [True, True, True]
    assert bucket.take() is False


def test_token_bucket_rejects_nonpositive_config():
    with pytest.raises(ValueError):
        TokenBucket(rate=0, burst=1)
    with pytest.raises(ValueError):
        TokenBucket(rate=1, burst=0)


# ------------------------------------------------- breach → action → audit

def test_breach_turns_pacer_and_admission_knobs():
    eng, _ = _engine()
    eng.on_transition(_breach())
    # queue-only: nothing acted yet
    assert eng.pacer.floor is None
    assert eng.counters["transitions_total"] == 1
    report = eng.tick()
    assert report["processed"] == 1
    assert report["actions"] == 2
    assert eng.pacer.floor == pytest.approx(eng.pacer_floor_s)
    assert eng.admit() is None  # within burst
    snap = eng.snapshot()
    assert snap["actions_total"] == 2
    assert {a["action"] for a in snap["active_actions"]} == \
        {"pacer_backoff", "admission_throttle"}
    assert all(a["trace_id"] == TID for a in snap["active_actions"])
    assert snap["last_trace_ids"]["pacer_backoff"] == TID
    audit = eng.debug()["audit"]
    assert [e["status"] for e in audit] == ["applied", "applied"]
    assert all(e["slo"] == "attach-p99" for e in audit)


def test_action_span_links_breach_exemplar_trace():
    eng, _ = _engine()
    eng.on_transition(_breach())
    eng.tick()
    # the linked ROOT span adopts the breach trace id — ONE
    # /debug/fleet/trace?trace=<exemplar> query shows the whole chain
    spans = trace.snapshot(op="remediation.action", trace=TID)
    assert len(spans) == 2
    assert {s["attrs"]["action"] for s in spans} == \
        {"pacer_backoff", "admission_throttle"}
    assert all(s["trace_id"] == TID for s in spans)


def test_admission_shed_is_typed_and_counted():
    eng, _ = _engine(shed_burst=2, shed_rate=1.0)
    assert eng.admit() is None  # no throttle armed: lock-free pass
    eng.on_transition(_breach())
    eng.tick()
    assert eng.admit() is None
    assert eng.admit() is None
    reason = eng.admit()  # burst of 2 exhausted, clock frozen
    assert reason is not None
    assert "attach-p99" in reason and TID in reason
    assert eng.counters["sheds_total"] == 1


def test_kubeapi_histogram_gets_pacer_only():
    eng, _ = _engine()
    eng.on_transition(_breach(slo="kubeapi-rtt",
                              histogram="tdp_kubeapi_rtt_ms"))
    eng.tick()
    assert eng.pacer.floor is not None
    assert eng._shed_bucket is None
    assert eng.admit() is None


def test_unknown_histogram_defaults_to_admission_throttle_only():
    eng, _ = _engine()
    eng.on_transition(_breach(slo="custom", histogram="tdp_custom_ms"))
    eng.tick()
    assert eng.pacer.floor is None
    assert eng._shed_bucket is not None


# ------------------------------------------------------------- hysteresis

def test_cooldown_skips_are_counted_and_audited():
    eng, clock = _engine(cooldown_s=30.0)
    eng.on_transition(_breach())
    eng.tick()
    applied = eng.counters["actions_total"]
    clock.advance(5.0)  # inside cool-down
    eng.on_transition(_breach(tid=TID2))
    eng.tick()
    assert eng.counters["actions_total"] == applied
    assert eng.counters["cooldown_skips_total"] == 2
    assert any(e["status"] == "skipped_cooldown"
               for e in eng.debug()["audit"])
    snap = eng.snapshot()
    assert snap["cooldowns"]  # live countdowns surfaced


def test_action_window_budget_blocks_storms():
    eng, clock = _engine(cooldown_s=0.0, max_actions_per_window=3,
                         action_window_s=300.0)
    for i in range(4):
        eng.on_transition(_breach(slo=f"slo-{i}",
                                  histogram=f"tdp_h{i}_ms"))
        eng.tick()
        clock.advance(1.0)
    # 4 distinct SLOs each want the admission knob; budget caps at 3
    assert eng.counters["actions_total"] == 3
    assert eng.counters["window_skips_total"] == 1
    clock.advance(400.0)  # window slides: budget refills
    eng.on_transition(_breach(slo="slo-9", histogram="tdp_h9_ms"))
    eng.tick()
    assert eng.counters["actions_total"] == 4


def test_no_flapping_under_oscillating_transitions():
    """The engine-side half of the no-flap guarantee (the SLO latch is
    the other half, tests/test_slo.py): repeated breach events inside
    the cool-down re-turn nothing, and only a latched recovery rolls
    back — counters stay at one apply / one rollback per incident."""
    eng, clock = _engine(cooldown_s=60.0)
    for _ in range(5):
        eng.on_transition(_breach())
        eng.tick()
        clock.advance(5.0)
    assert eng.counters["actions_total"] == 2
    assert eng.counters["rollbacks_total"] == 0
    eng.on_transition(_recovered())
    eng.tick()
    assert eng.counters["rollbacks_total"] == 2
    assert eng.snapshot()["active_actions"] == []
    assert eng.pacer.cleared == 1


# ------------------------------------------------------------ policy gate

def test_policy_veto_is_counted_and_knob_untouched():
    policy = PolicyEngine()
    policy.load_source("ops", (
        "def remediate(ctx):\n"
        "    if ctx['action'] == 'pacer_backoff':\n"
        "        return 'pacer is being babysat manually'\n"
        "    return None\n"))
    eng, _ = _engine(policy=policy)
    eng.on_transition(_breach())
    eng.tick()
    assert eng.pacer.floor is None  # vetoed knob untouched
    assert eng._shed_bucket is not None  # approved knob applied
    assert eng.counters["vetoes_total"] == 1
    assert eng.counters["actions_total"] == 1
    vetoed = [e for e in eng.debug()["audit"] if e["status"] == "vetoed"]
    assert len(vetoed) == 1
    assert vetoed[0]["detail"] == "pacer is being babysat manually"


def test_policy_approval_passes_action_context():
    seen = []
    policy = PolicyEngine()
    policy.load_source("ops", "def remediate(ctx):\n    return None\n")
    # observe through the policy decision log instead of the sandbox
    eng, _ = _engine(policy=policy)
    eng.on_transition(_breach())
    eng.tick()
    del seen
    assert eng.counters["vetoes_total"] == 0
    assert eng.counters["actions_total"] == 2
    snap = policy.snapshot()
    remediate = [h for h in snap["hooks"]
                 if h["hook"] == "remediate"]
    assert remediate and remediate[0]["calls"] == 2


# ---------------------------------------------------- rollback semantics

def test_rollback_waits_for_last_holding_slo():
    eng, clock = _engine(cooldown_s=0.0)
    eng.on_transition(_breach(slo="attach-p99"))
    eng.tick()
    clock.advance(1.0)
    eng.on_transition(_breach(slo="prepare-p99",
                              histogram="tdp_prepare_wall_ms", tid=TID2))
    eng.tick()
    # both SLOs hold both knobs
    snap = eng.snapshot()
    holders = {a["action"]: a["slos"] for a in snap["active_actions"]}
    assert holders["admission_throttle"] == ["attach-p99", "prepare-p99"]
    eng.on_transition(_recovered(slo="attach-p99"))
    eng.tick()
    assert eng.counters["rollbacks_total"] == 0  # prepare still burning
    assert eng.pacer.floor is not None
    eng.on_transition(_recovered(slo="prepare-p99",
                                 histogram="tdp_prepare_wall_ms"))
    eng.tick()
    assert eng.counters["rollbacks_total"] == 2
    assert eng.pacer.floor is None
    assert eng.admit() is None  # throttle cleared


def test_rollback_span_links_original_breach_trace():
    eng, _ = _engine(cooldown_s=0.0)
    eng.on_transition(_breach())
    eng.tick()
    eng.on_transition(_recovered())
    eng.tick()
    spans = trace.snapshot(op="remediation.rollback", trace=TID)
    # recovery events carry no exemplar — the rollback span links the
    # ORIGINAL breach trace id kept on the active-knob entry
    assert len(spans) == 2
    assert all(s["trace_id"] == TID for s in spans)


def test_recovery_without_active_actions_is_noop():
    eng, _ = _engine()
    eng.on_transition(_recovered())
    report = eng.tick()
    assert report["rollbacks"] == 0
    assert eng.counters["rollbacks_total"] == 0


# ----------------------------------------------- exemplar → node → bias

def test_node_attribution_biases_and_drains_repeat_offender():
    flight = FakeFlight(nodes=["scheduler", "node-3"])
    eng, clock = _engine(fleet_flight=flight, cooldown_s=0.0,
                         node_hits_threshold=2)
    eng.on_transition(_breach())
    eng.tick()
    assert eng.scheduler.biased == []  # one hit: below threshold
    clock.advance(1.0)
    eng.on_transition(_breach(tid=TID2))
    eng.tick()
    assert eng.scheduler.biased == [("node-3", "slo=attach-p99")]
    assert eng.scheduler.drains == ["node-3"]
    assert len(eng.scheduler.waves) == 1  # drain fed the handoff path
    assert eng.snapshot()["node_hits"] == {"node-3": 2}
    active = {a["action"]: a for a in eng.snapshot()["active_actions"]}
    assert active["node_bias"]["target"] == "node-3"


def test_node_bias_rolls_back_on_recovery():
    flight = FakeFlight(nodes=["scheduler", "node-3"])
    eng, clock = _engine(fleet_flight=flight, cooldown_s=0.0,
                         node_hits_threshold=1)
    eng.on_transition(_breach())
    eng.tick()
    assert eng.scheduler.biased
    eng.on_transition(_recovered())
    eng.tick()
    assert eng.scheduler.cleared == ["node-3"]


def test_scheduler_only_attribution_never_biases():
    # control-plane-only waterfall: no node label crosses threshold
    flight = FakeFlight(nodes=["scheduler"])
    eng, _ = _engine(fleet_flight=flight, node_hits_threshold=1)
    eng.on_transition(_breach())
    eng.tick()
    assert eng.scheduler.biased == []


# --------------------------------------------------- fragmentation burst

def test_unplaceable_burst_triggers_defrag_wave():
    eng, _ = _engine(unplaceable_burst=5, cooldown_s=0.0)
    sched = eng.scheduler
    eng.tick()  # establishes the baseline, no action
    assert len(sched.waves) == 0
    sched.stats["unplaceable_total"].value = 3
    eng.tick()  # delta 3 < 5: below burst
    assert len(sched.waves) == 0
    sched.stats["unplaceable_total"].value = 20
    report = eng.tick()  # delta 17 ≥ 5: wave
    assert report["burst"] == 17
    assert len(sched.waves) == 1
    audit = [e for e in eng.debug()["audit"] if e["status"] == "applied"]
    assert audit[-1]["slo"] == "unplaceable_burst"


def test_defrag_wave_skips_when_already_placeable():
    eng, _ = _engine(unplaceable_burst=1, cooldown_s=0.0)
    sched = eng.scheduler
    sched.proposal = {"placeable": True, "migrations": []}
    eng.tick()
    sched.stats["unplaceable_total"].value = 10
    eng.tick()
    assert sched.waves == []  # action ran, applied nothing
    applied = [e for e in eng.debug()["audit"] if e["status"] == "applied"]
    assert applied[-1]["detail"] == {"moves_applied": 0,
                                    "reason": "already placeable"}


# -------------------------------------------------- containment/surface

def test_failing_knob_is_counted_not_raised():
    class BrokenPacer(FakePacer):
        def set_backoff_floor(self, floor_s):
            raise RuntimeError("pacer wedged")

    eng, _ = _engine(pacer=BrokenPacer())
    eng.on_transition(_breach())
    eng.tick()  # must not raise
    assert eng.counters["errors_total"] == 1
    assert eng.counters["actions_total"] == 1  # throttle still applied
    errs = [e for e in eng.debug()["audit"] if e["status"] == "error"]
    assert "pacer wedged" in errs[0]["detail"]


def test_missing_components_skip_gracefully():
    eng = RemediationEngine()  # nothing wired at all
    eng.on_transition(_breach())
    report = eng.tick()
    # admission throttle needs no wiring; pacer action skipped silently
    assert report["actions"] == 1
    assert eng.counters["errors_total"] == 0


def test_on_transition_is_safe_under_concurrent_ticks():
    eng, _ = _engine(cooldown_s=0.0)

    def pump():
        for _ in range(200):
            eng.on_transition(_breach())

    threads = [threading.Thread(target=pump) for _ in range(3)]
    for t in threads:
        t.start()
    for _ in range(5):
        eng.tick()
    for t in threads:
        t.join()
    eng.tick()
    snap = eng.snapshot()
    assert snap["transitions_total"] == 600
    assert snap["ticks_total"] == 6
    assert snap["pending_transitions"] == 0


def test_background_thread_start_stop():
    eng, _ = _engine()
    eng.start(interval_s=0.01)
    eng.start(interval_s=0.01)  # idempotent
    eng.on_transition(_breach())
    deadline = threading.Event()
    for _ in range(200):
        if eng.counters["actions_total"]:
            break
        deadline.wait(0.01)
    eng.stop()
    assert eng.counters["actions_total"] >= 1
    assert eng._thread is None


def test_render_prometheus_strict_families():
    eng, _ = _engine()
    eng.on_transition(_breach())
    eng.tick()
    lines = render_prometheus(eng)
    text = "\n".join(lines)
    assert "# HELP tpu_plugin_remediation_actions_total" in text
    assert "# TYPE tpu_plugin_remediation_actions_total counter" in text
    assert "tpu_plugin_remediation_actions_total 2" in text
    assert "tpu_plugin_remediation_active_actions 2" in text
    # strict shape: every sample line's family has HELP+TYPE above it
    helped = {l.split()[2] for l in lines if l.startswith("# HELP")}
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    sampled = {l.split()[0] for l in lines if not l.startswith("#")}
    assert sampled <= helped == typed
