"""Watch-stream convergence plane tests (ISSUE 12).

Fabric side (fleetsim.FleetApiServer WATCH semantics): monotonic
resourceVersions on chunked long-poll streams, bookmark events, 410 Gone
on compacted resume, bounded per-watcher queues whose overflow
force-closes the stream (slow-consumer semantics), injectable breaks /
duplicate deliveries.

Client side (kubeapi.Reflector): list+watch with resourceVersion
tracking, relist on 410/stream break through the resilience backoff,
periodic resync, the at-least-once delivery contract, and the typed
degraded paced-relist mode when watch support is missing.

Daemon side (dra.DraDriver.start_watch_reconciler): a slice wiped or
mutated behind the driver is observed and repaired through the guarded
write path — exactly-once audited — and duplicate deliveries are
idempotent on the DRA inventory.
"""

import json
import time

import pytest

from tpu_device_plugin import faults
from tpu_device_plugin.fleetsim import FleetApiServer, FleetSim
from tpu_device_plugin.kubeapi import ApiClient, ApiError, Reflector
from tpu_device_plugin.resilience import BackoffPolicy

SLICES = "/apis/resource.k8s.io/v1beta1/resourceslices"


def _post_slice(api, name, generation=1, devices=()):
    return api.post_json(SLICES, {
        "metadata": {"name": name},
        "spec": {"pool": {"generation": generation},
                 "devices": [{"name": d} for d in devices]}})


def _put_slice(api, obj):
    return api.put_json(f"{SLICES}/{obj['metadata']['name']}", obj)


@pytest.fixture()
def fabric():
    servers = []

    def build(**kw):
        kw.setdefault("bookmark_interval_s", 0.1)
        srv = FleetApiServer(**kw)
        servers.append(srv)
        return srv

    yield build
    for srv in servers:
        srv.stop()


@pytest.fixture()
def reflect():
    refs = []

    def build(api, **kw):
        kw.setdefault("resync_interval_s", 60.0)
        kw.setdefault("poll_interval_s", 0.1)
        kw.setdefault("watch_timeout_s", 1.0)
        ref = Reflector(api, SLICES, **kw)
        refs.append(ref)
        ref.start()
        return ref

    yield build
    for ref in refs:
        ref.stop()


def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# --------------------------------------------------------------- fabric


def test_fabric_watch_delivers_events_with_monotonic_rvs(fabric):
    """Each write lands on the stream exactly once, in order, carrying a
    strictly increasing resourceVersion; the list's resourceVersion is a
    valid resume cursor (no replay of pre-list events)."""
    srv = fabric()
    api = ApiClient(srv.url, token_path="/nonexistent")
    _post_slice(api, "pre")                    # lands BEFORE the list
    lst = api.get_json(SLICES)
    resume = lst["metadata"]["resourceVersion"]
    with api.stream(f"{SLICES}?watch=1&resourceVersion={resume}"
                    f"&timeoutSeconds=3", read_timeout_s=5) as resp:
        _post_slice(api, "s1")
        obj = _put_slice(api, api.get_json(f"{SLICES}/s1")
                         | {"spec": {"pool": {"generation": 2},
                                     "devices": []}})
        api.delete(f"{SLICES}/s1")
        events, rvs = [], []
        deadline = time.monotonic() + 5
        while len(events) < 3 and time.monotonic() < deadline:
            line = resp.readline()
            if not line:
                break
            evt = json.loads(line)
            if evt["type"] == "BOOKMARK":
                continue
            events.append((evt["type"],
                           evt["object"]["metadata"]["name"]))
            rvs.append(int(
                evt["object"]["metadata"]["resourceVersion"]))
    assert events == [("ADDED", "s1"), ("MODIFIED", "s1"),
                      ("DELETED", "s1")]
    assert rvs == sorted(rvs) and len(set(rvs)) == 3
    assert rvs[0] > int(resume)        # "pre" was not replayed
    assert obj["metadata"]["name"] == "s1"


def test_fabric_watch_410_on_compacted_resume(fabric):
    """A resume cursor older than the compaction horizon answers 410
    Gone — the client cannot be caught up event-by-event."""
    srv = fabric(watch_backlog=4)
    api = ApiClient(srv.url, token_path="/nonexistent")
    for i in range(8):                 # 8 events, backlog 4: compaction
        _post_slice(api, f"s{i}")
    with pytest.raises(ApiError) as exc:
        with api.stream(f"{SLICES}?watch=1&resourceVersion=1"
                        f"&timeoutSeconds=1"):
            pass
    assert exc.value.code == 410
    assert srv.snapshot()["watch_410_total"] == 1
    # a fresh cursor still works
    lst = api.get_json(SLICES)
    with api.stream(
            f"{SLICES}?watch=1&resourceVersion="
            f"{lst['metadata']['resourceVersion']}&timeoutSeconds=0.2"):
        pass


def test_fabric_watch_bypasses_the_admission_gate(fabric):
    """Long-lived watch streams must not eat the 429 admission capacity
    the storms are measured against."""
    srv = fabric(max_inflight=1)
    api = ApiClient(srv.url, token_path="/nonexistent")
    with api.stream(f"{SLICES}?watch=1&resourceVersion=0"
                    f"&timeoutSeconds=5", read_timeout_s=10):
        # the single admission slot is still free for a plain request
        node = api.get_json("/api/v1/nodes/n1")
        assert node["metadata"]["name"] == "n1"
    assert srv.snapshot()["throttled_total"] == 0


# ------------------------------------------------------------ reflector


class _Store:
    """An idempotent materialized view + per-(name, rv) apply counts —
    the double-apply detector."""

    def __init__(self):
        self.state = {}
        self.applied = {}
        self.syncs = 0

    def on_event(self, evt):
        obj = evt["object"]
        name = obj["metadata"]["name"]
        key = (name, obj["metadata"]["resourceVersion"])
        self.applied[key] = self.applied.get(key, 0) + 1
        if evt["type"] == "DELETED":
            self.state.pop(name, None)
        else:
            self.state[name] = obj

    def on_sync(self, items):
        self.syncs += 1
        self.state = {o["metadata"]["name"]: o for o in items}


def test_reflector_resume_after_410_relists_without_loss_or_double_apply(
        fabric, reflect):
    """The kubeapi.watch.stale fault poisons the resume cursor; the 410
    answer forces a relist. Nothing is lost (the view converges to the
    fabric) and nothing is double-applied (absent the dup fault, no
    (object, rv) event is delivered twice)."""
    srv = fabric()
    api = ApiClient(srv.url, token_path="/nonexistent")
    store = _Store()
    ref = reflect(api, on_event=store.on_event, on_sync=store.on_sync)
    _wait(lambda: ref.snapshot()["watch_streams_established_total"] >= 1)
    _post_slice(api, "a")
    _wait(lambda: "a" in store.state)
    faults.arm("kubeapi.watch.stale", kind="drop", count=1)
    try:
        srv.close_watch_streams()      # force re-establishment
        _wait(lambda: ref.snapshot()["watch_410_total"] >= 1,
              msg="410 relist")
        _post_slice(api, "b")
        _wait(lambda: "b" in store.state)
    finally:
        faults.reset()
    with srv._lock:
        live = set(srv.slices)
    assert set(store.state) == live
    doubles = {k: n for k, n in store.applied.items() if n > 1}
    assert not doubles, f"events double-applied: {doubles}"
    snap = ref.snapshot()
    assert snap["watch_breaks_total"] >= 1
    assert snap["watch_relists_total"] >= 2     # initial + post-410


def test_reflector_bookmark_only_stream_advances_the_cursor(
        fabric, reflect):
    """An idle stream's bookmarks advance the resume cursor without
    data events, so the next rotation resumes at the server's rv and
    never replays."""
    srv = fabric()
    api = ApiClient(srv.url, token_path="/nonexistent")
    _post_slice(api, "idle")           # history BEFORE the reflector
    store = _Store()
    ref = reflect(api, on_event=store.on_event, on_sync=store.on_sync,
                  watch_timeout_s=0.5)
    # wait through at least one clean rotation AND several bookmarks
    _wait(lambda: (
        ref.snapshot()["watch_streams_established_total"] >= 2
        and ref.snapshot()["watch_bookmarks_total"] >= 3),
        msg="bookmark-carrying rotations")
    # zero data events were delivered, yet the cursor tracked the
    # server's rv across rotations — no replay of the pre-list history
    snap = ref.snapshot()
    assert snap["watch_events_total"] == 0
    assert snap["watch_relists_total"] == 1      # the seeding list only
    with srv._lock:
        assert ref._rv == srv._rv


def test_reflector_slow_consumer_force_close_recovers_via_relist(
        fabric, reflect):
    """A consumer that cannot keep up overflows its bounded server-side
    queue; the fabric drops the queue and force-closes the stream with
    the 410-shaped ERROR event; the reflector relists and converges."""
    srv = fabric(watch_queue_max=4)
    api = ApiClient(srv.url, token_path="/nonexistent")
    store = _Store()
    ref = reflect(api, on_event=store.on_event, on_sync=store.on_sync)
    _wait(lambda: ref.snapshot()["watch_streams_established_total"] >= 1)
    # the injected per-event delivery STALL makes the consumer slow:
    # the producer outruns the 4-event queue bound while the handler
    # sleeps inside a delivery
    srv.arm_watch_chaos(stall_s=0.08, seed=3)
    writer = ApiClient(srv.url, token_path="/nonexistent")
    for i in range(24):
        _post_slice(writer, f"flood-{i}")
    _wait(lambda: srv.snapshot()["watch_force_closed_total"] >= 1,
          msg="force close")
    srv.disarm_watch_chaos()
    _wait(lambda: ref.snapshot()["watch_410_total"] >= 1,
          msg="410-shaped error → relist")
    _wait(lambda: len(store.state) == 24, msg="relist convergence")
    with srv._lock:
        assert set(store.state) == set(srv.slices)


def test_reflector_degrades_to_paced_relist_and_recovers(fabric, reflect):
    """A fabric without watch support (400s every watch request) pushes
    the reflector into the TYPED degraded mode: paced relists keep the
    view converging, the gauge reads 1, and restoring watch support
    heals it — event-driven again, gauge back to 0."""
    srv = fabric(watch_enabled=False)
    api = ApiClient(srv.url, token_path="/nonexistent")
    store = _Store()
    ref = reflect(api, on_event=store.on_event, on_sync=store.on_sync,
                  degrade_after=2)
    _wait(lambda: ref.snapshot()["watch_degraded_mode"] == 1,
          msg="degraded entry")
    assert ref.snapshot()["watch_degraded_entries_total"] == 1
    assert not ref.stream_live()
    relists0 = ref.snapshot()["watch_relists_total"]
    _post_slice(api, "while-degraded")
    _wait(lambda: "while-degraded" in store.state,
          msg="paced-relist convergence")
    assert ref.snapshot()["watch_relists_total"] > relists0
    srv.watch_enabled = True           # the apiserver upgrade
    _wait(lambda: ref.snapshot()["watch_degraded_mode"] == 0,
          msg="degraded exit")
    _post_slice(api, "after-recovery")
    _wait(lambda: "after-recovery" in store.state)
    assert ref.stream_live()


def test_reflector_relist_failures_climb_the_degradation_ladder(reflect):
    """A permanently failing LIST is a failing convergence plane: it
    climbs the SAME typed degradation ladder as stream breaks
    (watch_degraded_mode=1, paced polling) instead of looping on
    backoff forever with the gauge still 0 — and a relist failure
    never counts as a stream break."""
    api = ApiClient("http://127.0.0.1:9", token_path="/nonexistent")
    ref = reflect(api, degrade_after=2,
                  backoff=BackoffPolicy(base_s=0.01, cap_s=0.05))
    _wait(lambda: ref.snapshot()["watch_degraded_mode"] == 1,
          msg="degraded entry from relist failures")
    snap = ref.snapshot()
    assert snap["watch_breaks_total"] == 0
    assert snap["watch_relists_total"] == 0
    assert not ref.stream_live()


def test_reflector_error_event_first_line_still_climbs_the_ladder():
    """A watch stream that establishes (200) but only ever delivers a
    server-sent non-410 ERROR event is a FAILING stream: the ERROR
    line itself must not count as stream health, or the ladder resets
    every establishment and degraded mode can never engage."""
    class Resp:
        def __init__(self):
            self._data = json.dumps(
                {"type": "ERROR",
                 "object": {"code": 500, "message": "boom"}}
            ).encode() + b"\n"

        def read1(self, n):
            data, self._data = self._data, b""
            return data

    class Stream:
        def __enter__(self):
            return Resp()

        def __exit__(self, *exc):
            return False

        def close(self):
            pass

    class Api:
        def get_json(self, path):
            return {"metadata": {"resourceVersion": "1"}, "items": []}

        def stream(self, path, read_timeout_s=None):
            return Stream()

    ref = Reflector(Api(), SLICES, name="err-stream",
                    poll_interval_s=0.02, degrade_after=2,
                    backoff=BackoffPolicy(base_s=0.005, cap_s=0.02))
    ref.start()
    try:
        _wait(lambda: ref.snapshot()["watch_degraded_mode"] == 1,
              msg="degraded entry from ERROR-event streams")
    finally:
        ref.stop()
    assert ref.snapshot()["watch_breaks_total"] >= 2
    assert not ref.stream_live()


def test_reflector_stop_unblocks_a_stream_mid_establishment():
    """stop() must be prompt even when the watch stream is still
    ESTABLISHING (parked in connect/getresponse against a stalled
    apiserver/LB): the stream handle is published before establishment
    and close() latches, so stop() tears it down NOW instead of the
    thread outliving stop() by a full read timeout."""
    import threading

    established = threading.Event()

    class Stream:
        def __init__(self):
            self.closed = threading.Event()

        def __enter__(self):
            established.set()
            # park like getresponse() against a stalled LB until
            # close() wakes us
            self.closed.wait(timeout=30)
            raise ApiError("torn by close", code=0)

        def __exit__(self, *exc):
            return False

        def close(self):
            self.closed.set()

    class Api:
        def get_json(self, path):
            return {"metadata": {"resourceVersion": "1"}, "items": []}

        def stream(self, path, read_timeout_s=None):
            return Stream()

    ref = Reflector(Api(), SLICES, name="parked",
                    poll_interval_s=0.05,
                    backoff=BackoffPolicy(base_s=0.01, cap_s=0.02))
    ref.start()
    assert established.wait(5), "stream never began establishing"
    t0 = time.monotonic()
    ref.stop()
    assert time.monotonic() - t0 < 5, "stop() was not prompt"
    assert not ref._thread.is_alive()


def test_reflector_relist_404_reresolves_a_callable_path():
    """A 404 on LIST may mean the collection's API version was dropped
    by a control-plane upgrade: the on_list_404 hook invalidates the
    owner's cached version and the CALLABLE path re-resolves on the
    next attempt — the reflector recovers instead of 404ing forever."""
    state = {"version": "v1beta1", "listed": []}

    class Api:
        def get_json(self, path):
            state["listed"].append(path)
            if "v1beta1" in path:
                raise ApiError("dropped version", code=404)
            return {"metadata": {"resourceVersion": "5"}, "items": []}

        def stream(self, path, read_timeout_s=None):
            raise ApiError("watch unsupported", code=400)

    def resolve():
        return (f"/apis/resource.k8s.io/{state['version']}"
                "/resourceslices")

    def on_404():
        state["version"] = "v1"

    ref = Reflector(Api(), resolve, on_list_404=on_404, name="re404",
                    poll_interval_s=0.05,
                    backoff=BackoffPolicy(base_s=0.01, cap_s=0.05))
    ref.start()
    try:
        _wait(lambda: ref.snapshot()["watch_relists_total"] >= 1,
              msg="relist on the re-resolved path")
    finally:
        ref.stop()
    assert any("/v1/" in p for p in state["listed"]), state["listed"]
    assert ref.path.endswith("/v1/resourceslices")


# ------------------------------------------------- DRA driver integration


@pytest.fixture()
def watch_fleet():
    sims = []

    def build(**kw):
        kw.setdefault("n_nodes", 2)
        kw.setdefault("latency_s", 0.0)
        kw.setdefault("max_inflight", 0)
        kw.setdefault("watch", True)
        kw.setdefault("watch_resync_s", 30.0)
        kw.setdefault("watch_poll_s", 0.2)
        kw.setdefault("watch_timeout_s", 1.0)
        sim = FleetSim(**kw)
        sims.append(sim)
        return sim

    yield build
    for sim in sims:
        sim.stop()


def test_dra_watch_repairs_wipe_and_divergence_exactly_once(watch_fleet):
    """THE convergence acceptance: a slice wiped behind the driver is
    healed by a watch-triggered repair (generation sequence CONTINUED,
    not reset — the exactly-once audit must stay green), and a foreign
    writer's mutation is repaired back to the desired projection."""
    sim = watch_fleet()
    assert sim.boot_storm()["published_ok"] == 2
    node = sim.nodes[0]
    name = node.driver.slice_name()
    api = node.driver.api
    # wipe
    api.delete(f"{SLICES}/{name}")
    _wait(lambda: name in sim.apiserver.slices, msg="wipe healed")
    assert node.driver.watch_repairs.value >= 1
    # foreign mutation (impersonating writer bumps the generation)
    live = api.get_json(f"{SLICES}/{name}")
    live["spec"]["devices"] = live["spec"]["devices"][:1]
    live["spec"]["pool"]["generation"] += 1
    api.put_json(f"{SLICES}/{name}", live)

    def converged():
        try:
            return sim.assert_converged()
        except AssertionError:
            return False

    _wait(converged, msg="divergence healed")
    audit = sim.apiserver.exactly_once_audit()
    assert audit["exactly_once"], audit


def test_watch_repair_links_causal_write_trace_and_observes_convergence(
        watch_fleet):
    """r17 propagation through the watch plane: a foreign write made
    inside a span carries its traceparent to the fabric (request
    header), the fabric stamps it on the watch events the write causes,
    and the repairing driver (a) links the causal trace on its
    dra.watch.repair event and (b) observes tdp_watch_convergence_ms
    with that trace as the bucket exemplar — the SLO plane's
    watch-convergence objective fed end-to-end."""
    from tpu_device_plugin import trace
    trace.reset()
    conv_before = trace.histogram(
        "tdp_watch_convergence_ms").snapshot()["count"]
    sim = watch_fleet()
    assert sim.boot_storm()["published_ok"] == 2
    node = sim.nodes[0]
    name = node.driver.slice_name()
    api = node.driver.api
    with trace.span("foreign.writer"):
        foreign_tid = trace.current_context()["trace_id"]
        live = api.get_json(f"{SLICES}/{name}")
        live["spec"]["devices"] = live["spec"]["devices"][:1]
        live["spec"]["pool"]["generation"] += 1
        api.put_json(f"{SLICES}/{name}", live)
    _wait(lambda: node.driver.watch_repairs.value >= 1,
          msg="watch repair triggered")
    _wait(lambda: trace.histogram(
        "tdp_watch_convergence_ms").snapshot()["count"] > conv_before,
        msg="convergence lag observed")
    repairs = trace.snapshot(op="dra.watch.repair")
    linked = [r for r in repairs
              if (r.get("link") or {}).get("trace_id") == foreign_tid]
    assert linked, repairs
    # the causal write's trace is the convergence histogram's exemplar
    snap = trace.histogram("tdp_watch_convergence_ms").snapshot()
    assert any(ex["trace_id"] == foreign_tid
               for ex in snap["exemplars"]), snap["exemplars"]
    # ...and resolves on the fleet trace query, joining writer + repair
    story = sim.fleet_flight().trace(foreign_tid)
    assert "dra.watch.repair" in story["ops"]
    trace.reset()


def test_dra_unchanged_republish_skips_reads_only_while_watch_live(
        watch_fleet):
    """Steady-state read/repair churn: with a live stream an unchanged
    republish pays ZERO fabric reads (counted skip); with the watch
    stopped the liveness GET comes back — the ladder never trades a
    read away for a blind spot."""
    sim = watch_fleet(n_nodes=1)
    sim.boot_storm()
    node = sim.nodes[0]
    _wait(node.driver._watch_live, msg="stream live")
    reads0 = sim.apiserver.snapshot()["slice_reads_total"]
    assert node.driver.publish_resource_slices()
    assert sim.apiserver.snapshot()["slice_reads_total"] == reads0
    assert node.driver.publish_stats["watch_read_skips"] == 1
    # stop the watch: the next unchanged republish GETs again
    node.driver._slice_watch.stop()
    node.driver._slice_watch = None
    assert node.driver.publish_resource_slices()
    assert sim.apiserver.snapshot()["slice_reads_total"] == reads0 + 1
    assert node.driver.publish_stats["watch_read_skips"] == 1


def test_dra_deferred_watch_evidence_forces_the_liveness_get(watch_fleet):
    """A DELETED observation arriving while a publish holds the lock is
    DEFERRED, not dropped: the next unchanged-projection publish pays
    its classic liveness GET (healing a wipe within one republish
    period) instead of taking the watch_read_skips fast path — and the
    consumed deferral restores the fast path afterwards."""
    sim = watch_fleet(n_nodes=1)
    sim.boot_storm()
    node = sim.nodes[0]
    d = node.driver
    _wait(d._watch_live, msg="stream live")
    with d._publish_lock:
        d._on_slice_watch_event({"type": "DELETED", "object": {
            "metadata": {"name": d.slice_name(),
                         "resourceVersion": str(10 ** 9)}}})
        # never acted on against the half-updated window
        assert d.watch_repairs.value == 0
    assert d._watch_evidence_pending()
    # a FAILED attempt must keep the deferral for the retry: the
    # republish retry would otherwise skip straight back over it
    faults.arm("kubeapi.request", kind="error", count=1)
    try:
        assert not d.publish_resource_slices()
    finally:
        faults.reset()
    assert d._watch_evidence_pending()
    reads0 = sim.apiserver.snapshot()["slice_reads_total"]
    assert d.publish_resource_slices()
    assert sim.apiserver.snapshot()["slice_reads_total"] == reads0 + 1
    assert d.publish_stats["watch_read_skips"] == 0
    assert not d._watch_evidence_pending()
    assert d.publish_resource_slices()
    assert sim.apiserver.snapshot()["slice_reads_total"] == reads0 + 1
    assert d.publish_stats["watch_read_skips"] == 1


def test_dra_duplicate_watch_deliveries_are_idempotent_on_inventory(
        watch_fleet):
    """kubeapi.watch.dup fires on every event: duplicates must trigger
    NO repairs (an event matching the desired projection is a no-op),
    the inventory converges, and the write audit stays exactly-once."""
    sim = watch_fleet()
    sim.boot_storm()
    node = sim.nodes[0]
    faults.arm("kubeapi.watch.dup", kind="drop", count=None,
               probability=1.0)
    try:
        # one flip at a time, letting deliveries drain against a STABLE
        # desired state between writes — so any repair the duplicates
        # trigger is attributable to the duplicates, not to an event
        # racing an in-flight publish
        for healthy in (False, True):
            node.plugin.set_devices_health([node.bdfs[0]],
                                           healthy=healthy, source="t")
            _wait(lambda: sim.watch_totals()["watch_events_total"] > 0)
            time.sleep(0.3)
        _wait(lambda: sim.watch_totals()
              ["watch_duplicate_deliveries_total"] >= 2, msg="dups")
    finally:
        faults.reset()
    totals = sim.watch_totals()
    assert totals["watch_duplicate_deliveries_total"] >= 2, totals
    assert sim.assert_converged()
    assert sim.apiserver.exactly_once_audit()["exactly_once"]
    # duplicates never read as divergence: no repairs fired
    assert totals.get("watch_repairs_total", 0) == 0, totals


def test_watch_stats_zero_surface_without_reconciler(short_root):
    """A driver in pre-watch polling mode still serves the full
    fixed-key watch surface (zeros, enabled: False) so /status paths
    and the counter-drift audit always resolve."""
    from tests.fakehost import FakeChip, FakeHost
    from tests.test_dra import FakeApiServer, make_driver
    from tpu_device_plugin.config import Config

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", device_id="0063",
                           iommu_group="11"))
    cfg = Config().with_root(host.root)
    apiserver = FakeApiServer()
    try:
        driver = make_driver(cfg, apiserver)
        stats = driver.watch_stats()
        assert stats["enabled"] is False
        for key in ("watch_streams_active", "watch_events_total",
                    "watch_relists_total", "watch_resyncs_total",
                    "watch_degraded_mode", "watch_repairs_total"):
            assert stats[key] == 0
    finally:
        apiserver.stop()
