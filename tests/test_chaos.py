"""Chaos suite: seeded, randomized failure schedules asserting invariants.

Turns the repo's robustness claims into executed tests. Failures are
injected two ways — from outside (deleting sockets and device nodes,
stopping the fake kubelet, like a hostile node would) and from inside via
the faults registry (tpu_device_plugin/faults.py) at the named sites the
production code consults. Every schedule is drawn from a seeded RNG
($TDP_CHAOS_SEED, default 1337) so a failure replays exactly.

Invariants checked:
  - kubelet restart storm → every plugin eventually re-registers and
    advertises its full healthy device set;
  - injected registration failures → the jittered restart loop retries
    until the kubelet accepts (and the typed-error path logs it right);
  - flapping /dev/vfio nodes (with inotify event drops injected) → no
    device is permanently lost once its node is back;
  - API-server failure bursts → the ApiClient circuit breaker trips,
    publishes fail fast while open, recovery goes through the half-open
    probe, and NO apiserver write is ever duplicated;
  - drain state survives a kubelet restart storm.

The long randomized soak variant is @pytest.mark.slow and additionally
gated on TDP_CHAOS_SOAK=1 so `make test` (tier-1) stays fast; run it via
`make chaos-soak`.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import replace

import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tests.kubelet_sim import DeviceManagerSim
from tpu_device_plugin import faults
from tpu_device_plugin.config import Config
from tpu_device_plugin.lifecycle import PluginManager
from tpu_device_plugin.resilience import BackoffPolicy, CircuitBreaker

SEED = int(os.environ.get("TDP_CHAOS_SEED", "1337"))


@pytest.fixture(autouse=True)
def clean_faults():
    faults.reset()
    faults.seed(SEED)
    yield
    faults.reset()


def _wait(pred, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _fast_restart_policies(manager, rng):
    """Swap each plugin's restart backoff for a seeded, fast policy so a
    storm round resolves in tenths of seconds instead of tens."""
    for plugin in manager.plugins:
        plugin._restart_backoff = BackoffPolicy(
            base_s=0.05, cap_s=0.4, rng=random.Random(rng.random()))


def _make_node(root, chips):
    host = FakeHost(root)
    for chip in chips:
        host.add_chip(chip)
    cfg = replace(Config().with_root(root),
                  health_poll_s=0.1, grpc_timeout_s=1.0)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    return host, cfg


TWO_MODEL_CHIPS = [
    FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"),
    FakeChip("0000:00:05.0", device_id="0062", iommu_group="12"),
    FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"),
    FakeChip("0000:01:01.0", device_id="0063", iommu_group="22"),
]


@pytest.fixture
def node(short_root):
    """Two-resource node (2 chips each) + devicemanager sim + manager."""
    host, cfg = _make_node(short_root, TWO_MODEL_CHIPS)
    sim = DeviceManagerSim(cfg.device_plugin_path)
    manager = PluginManager(cfg)
    manager.start()
    assert not manager.pending
    yield host, cfg, sim, manager
    manager.stop()
    sim.stop()


def _kubelet_restart(cfg, sim, manager, rng, down_s):
    """One kubelet bounce: server gone, socket dir wiped, then back."""
    sim.stop()
    try:
        os.unlink(cfg.kubelet_socket)
    except FileNotFoundError:
        pass
    for plugin in manager.plugins:
        try:
            os.unlink(plugin.socket_path)
        except FileNotFoundError:
            pass
    time.sleep(down_s)
    return DeviceManagerSim(cfg.device_plugin_path)


def test_kubelet_restart_storm_every_plugin_reregisters(node):
    host, cfg, sim, manager = node
    rng = random.Random(SEED)
    _fast_restart_policies(manager, rng)
    resources = sorted(p.resource_name for p in manager.plugins)
    assert len(resources) == 2
    for r in resources:
        assert sim.wait_for_resource(r), f"{r} never registered"

    for round_no in range(3):
        sim = _kubelet_restart(cfg, sim, manager, rng,
                               down_s=rng.uniform(0.05, 0.4))
        for r in resources:
            assert sim.wait_for_resource(r, timeout=20), \
                f"round {round_no}: {r} did not re-register"
            assert sim.wait_for_allocatable(r, 2, timeout=20), \
                f"round {round_no}: {r} lost devices after re-registration"
    # the node still admits pods after the storm
    picked, resp = sim.admit_pod(resources[0], 2)
    assert len(picked) == 2
    assert resp.container_responses[0].devices
    sim.stop()


def test_injected_registration_failures_eventually_register(short_root):
    """faults: kubelet.register armed for 3 failures — the restart loop's
    jittered backoff must absorb them and register on the 4th try."""
    host, cfg = _make_node(short_root, TWO_MODEL_CHIPS[:1])
    kubelet = FakeKubelet(cfg.kubelet_socket)
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert not manager.pending
        (plugin,) = manager.plugins
        plugin._restart_backoff = BackoffPolicy(
            base_s=0.02, cap_s=0.1, rng=random.Random(SEED))
        assert kubelet.wait_for(1, timeout=5)
        faults.arm("kubelet.register", kind="error", count=3)
        os.unlink(plugin.socket_path)          # kubelet "restart"
        assert kubelet.wait_for(2, timeout=15), \
            "plugin never re-registered through the injected failures"
        assert faults.stats().get("kubelet.register") == 3
        assert plugin.status_snapshot()[
            "restart_backoff"]["total_attempts"] >= 3
    finally:
        manager.stop()
        kubelet.stop()


def test_boot_race_uses_typed_kubelet_unavailable(short_root, caplog):
    """No kubelet at start(): the failure must surface as the typed
    KubeletUnavailable (routine boot race, logged at INFO by lifecycle),
    not a bare grpc exception logged as an error."""
    import logging

    host, cfg = _make_node(short_root, TWO_MODEL_CHIPS[:1])
    manager = PluginManager(cfg)
    with caplog.at_level(logging.INFO, logger="tpu_device_plugin.lifecycle"):
        manager.start()                         # no kubelet listening
    try:
        assert manager.pending, "start should have left the plugin pending"
        records = [r for r in caplog.records
                   if "kubelet not ready" in r.getMessage()]
        assert records and all(r.levelno == logging.INFO for r in records)
        # kubelet appears -> pending start succeeds
        kubelet = FakeKubelet(cfg.kubelet_socket)
        try:
            manager._try_start_pending()
            assert not manager.pending
            assert kubelet.wait_for(1, timeout=5)
        finally:
            kubelet.stop()
    finally:
        manager.stop()


def test_vfio_flap_no_device_permanently_lost(node):
    """Seeded flapping of /dev/vfio group nodes — with inotify event drops
    injected underneath — must never lose a device whose node came back:
    the periodic existence scan reconciles what inotify missed."""
    host, cfg, sim, manager = node
    rng = random.Random(SEED + 1)
    resources = sorted(p.resource_name for p in manager.plugins)
    for r in resources:
        assert sim.wait_for_allocatable(r, 2, timeout=10)

    groups = ["11", "12", "21", "22"]
    # drop ~30% of inotify batches for the whole flap schedule
    faults.arm("inotify.poll", kind="drop", count=None, probability=0.3)
    down: set = set()
    for _ in range(12):
        g = rng.choice(groups)
        if g in down:
            with open(os.path.join(host.devfs, "vfio", g), "w"):
                pass
            down.discard(g)
        else:
            host.remove_vfio_group(g)
            down.add(g)
        time.sleep(rng.uniform(0.01, 0.15))
    # restore everything; every device must come back
    for g in sorted(down):
        with open(os.path.join(host.devfs, "vfio", g), "w"):
            pass
    faults.disarm("inotify.poll")
    for r in resources:
        assert sim.wait_for_allocatable(r, 2, timeout=20), \
            f"{r} lost devices permanently after flapping stopped"


def test_native_probe_fault_prunes_then_recovers(node):
    """An injected native-probe failure marks the chip Unhealthy on the
    stream; once the fault budget is exhausted the next poll restores it."""
    host, cfg, sim, manager = node
    resources = sorted(p.resource_name for p in manager.plugins)
    for r in resources:
        assert sim.wait_for_allocatable(r, 2, timeout=10)
    # each poll probes every bdf; 2 polls x 4 chips — bound the budget so
    # exactly one resource's chips flap for ~2 poll cycles
    faults.arm("native.probe", kind="false", count=8)
    dropped = _wait(lambda: any(sim.allocatable(r) < 2 for r in resources),
                    timeout=10)
    assert dropped, "injected probe failures never surfaced as Unhealthy"
    for r in resources:
        assert sim.wait_for_allocatable(r, 2, timeout=20), \
            f"{r} did not recover after probe faults exhausted"


def test_drain_survives_kubelet_restart_storm(node):
    host, cfg, sim, manager = node
    rng = random.Random(SEED + 2)
    _fast_restart_policies(manager, rng)
    resources = sorted(p.resource_name for p in manager.plugins)
    for r in resources:
        assert sim.wait_for_allocatable(r, 2, timeout=10)
    manager.drain(True)
    for r in resources:
        assert sim.wait_for_allocatable(r, 0, timeout=10)
    sim = _kubelet_restart(cfg, sim, manager, rng, down_s=0.1)
    for r in resources:
        assert sim.wait_for_resource(r, timeout=20)
        # re-registered, but the drain verdict must still hold: the fresh
        # initial ListAndWatch snapshot advertises every device Unhealthy
        assert sim.allocatable(r) == 0, \
            f"{r}: drain state lost across kubelet restart"
    manager.drain(False)
    for r in resources:
        assert sim.wait_for_allocatable(r, 2, timeout=10)
    sim.stop()


# ------------------------------------------------------- apiserver chaos


@pytest.fixture
def dra_rig(short_root):
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin.discovery import discover
    from tpu_device_plugin.dra import DraDriver
    from tpu_device_plugin.kubeapi import ApiClient

    host, cfg = _make_node(short_root, TWO_MODEL_CHIPS[:2])
    apiserver = FakeApiServer()
    breaker = CircuitBreaker(failure_threshold=3, reset_timeout_s=0.15,
                             name="chaos")
    api = ApiClient(apiserver.url, token_path="/nonexistent-token",
                    breaker=breaker)
    registry, generations = discover(cfg)
    driver = DraDriver(cfg, registry, generations, node_name="chaos-node",
                       api=api)
    driver.republish_backoff = BackoffPolicy(
        base_s=0.02, cap_s=0.1, rng=random.Random(SEED))
    yield host, cfg, apiserver, driver, breaker
    driver.stop()
    apiserver.stop()


def _slice_writes(apiserver):
    return [(m, p) for (m, p) in apiserver.requests
            if m in ("POST", "PUT") and "resourceslices" in p]


def test_apiserver_burst_trips_breaker_and_recovers_without_dup_writes(
        dra_rig):
    from tpu_device_plugin.kubeapi import ApiError

    host, cfg, apiserver, driver, breaker = dra_rig
    assert driver.publish_resource_slices()
    assert len(_slice_writes(apiserver)) == 1          # the initial POST

    # 5xx burst: every request fails before the wire until disarmed
    faults.arm("kubeapi.request", count=None,
               exc=lambda: ApiError("injected 503", code=503))
    assert driver.apply_health({"0000:00:04.0": False}) is True
    # the failed republish self-arms its jittered retry; successive retry
    # failures must trip the breaker
    assert _wait(lambda: breaker.snapshot()["state"] == "open", timeout=10), \
        "breaker never tripped under the injected failure burst"

    # while open: publishes fail fast without touching the apiserver
    requests_at_open = len(apiserver.requests)
    assert driver.publish_resource_slices() is False
    assert len(apiserver.requests) == requests_at_open

    # burst ends; the retry loop must recover through the half-open probe
    faults.disarm("kubeapi.request")
    assert _wait(lambda: len(_slice_writes(apiserver)) >= 2, timeout=10), \
        "slice never republished after the burst ended"
    assert _wait(lambda: breaker.snapshot()["state"] == "closed", timeout=5)
    assert _wait(lambda: driver._republish_timer is None, timeout=5)

    # invariants: the pruned slice landed, exactly once — one POST
    # (create) + one PUT (prune), zero duplicated writes
    (slice_obj,) = apiserver.slices.values()
    assert len(slice_obj["spec"]["devices"]) == 1
    assert slice_obj["spec"]["pool"]["generation"] == 2
    assert _slice_writes(apiserver) == [
        ("POST", w[1]) if i == 0 else ("PUT", w[1])
        for i, w in enumerate(_slice_writes(apiserver))]
    assert len(_slice_writes(apiserver)) == 2


def test_apiserver_timeout_burst_never_duplicates_writes(dra_rig):
    """TimeoutError-kind faults at the transport: the client must never
    replay a write (the kubeapi.py:30 duplicate-write hazard), so after
    recovery each logical publish still lands exactly once."""
    host, cfg, apiserver, driver, breaker = dra_rig
    assert driver.publish_resource_slices()
    faults.arm("kubeapi.request", kind="timeout", count=4)
    assert driver.apply_health({"0000:00:04.0": False}) is True
    # retries burn the 4-fault budget, then the next retry succeeds
    assert _wait(lambda: len(_slice_writes(apiserver)) >= 2, timeout=10)
    assert _wait(lambda: driver._republish_timer is None, timeout=5)
    writes = _slice_writes(apiserver)
    assert len(writes) == 2                      # POST + exactly one PUT
    (slice_obj,) = apiserver.slices.values()
    assert len(slice_obj["spec"]["devices"]) == 1


# --------------------------------------------------------------- soak


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("TDP_CHAOS_SOAK") != "1",
                    reason="soak: set TDP_CHAOS_SOAK=1 (make chaos-soak)")
def test_soak_mixed_failure_schedule(node):
    """Long randomized mix of kubelet bounces, vfio flaps, probe faults
    and inotify drops. Invariant at the end of every round: all resources
    re-register and recover their full device set."""
    host, cfg, sim, manager = node
    rng = random.Random(SEED + 3)
    _fast_restart_policies(manager, rng)
    resources = sorted(p.resource_name for p in manager.plugins)
    groups = ["11", "12", "21", "22"]
    for r in resources:
        assert sim.wait_for_allocatable(r, 2, timeout=10)

    for round_no in range(8):
        action = rng.choice(["kubelet", "flap", "probe", "inotify"])
        if action == "kubelet":
            sim = _kubelet_restart(cfg, sim, manager, rng,
                                   down_s=rng.uniform(0.05, 0.5))
        elif action == "flap":
            downed = rng.sample(groups, k=rng.randint(1, len(groups)))
            for g in downed:
                host.remove_vfio_group(g)
            time.sleep(rng.uniform(0.05, 0.3))
            for g in downed:
                with open(os.path.join(host.devfs, "vfio", g), "w"):
                    pass
        elif action == "probe":
            faults.arm("native.probe", kind="false",
                       count=rng.randint(1, 8), probability=0.5)
            time.sleep(rng.uniform(0.1, 0.4))
            faults.disarm("native.probe")
        else:
            faults.arm("inotify.poll", kind="drop", count=None,
                       probability=0.5)
            g = rng.choice(groups)
            host.remove_vfio_group(g)
            time.sleep(rng.uniform(0.05, 0.3))
            with open(os.path.join(host.devfs, "vfio", g), "w"):
                pass
            faults.disarm("inotify.poll")
        for r in resources:
            assert sim.wait_for_resource(r, timeout=30), \
                f"soak round {round_no} ({action}): {r} not registered"
            assert sim.wait_for_allocatable(r, 2, timeout=30), \
                f"soak round {round_no} ({action}): {r} degraded"
    sim.stop()


def test_checkpoint_write_fault_errors_claims_never_silent_acks(dra_rig):
    """faults: checkpoint.write armed at the group-commit writer — every
    claim waiting on the failed commit window must surface a per-claim
    error and roll back (no silent ACK of an entry that never reached
    disk); after the fault clears, a kubelet retry prepares exactly once
    and the on-disk checkpoint recovers every claim."""
    from tpu_device_plugin.dra import slice_device_name
    from tpu_device_plugin.kubeletapi import drapb

    host, cfg, apiserver, driver, breaker = dra_rig
    # widen the commit window so the whole burst deterministically rides
    # the ONE faulted write attempt, whatever the CI scheduler does
    driver.checkpoint_commit_window_s = 0.25
    names = [slice_device_name(c.bdf) for c in TWO_MODEL_CHIPS[:2]]
    uids = [f"ckpt-fault-{i}" for i in range(4)]
    for i, uid in enumerate(uids):
        apiserver.add_claim("ns", uid, uid, driver.driver_name,
                            [{"device": names[i % 2]}])
    claims = [drapb.Claim(namespace="ns", name=uid, uid=uid)
              for uid in uids]

    faults.arm("checkpoint.write", kind="oserror", count=1)
    resp = driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=claims), None)
    errors = {uid: resp.claims[uid].error for uid in uids}
    assert all(errors.values()), f"silent ACK under write fault: {errors}"
    assert faults.stats().get("checkpoint.write") == 1
    # rolled back everywhere: no checkpoint entries, no orphan spec files
    assert driver.prepared_claim_count() == 0
    leftovers = [f for f in os.listdir(driver.cdi_dir)
                 if "claim" in f] if os.path.isdir(driver.cdi_dir) else []
    assert leftovers == []

    # fault budget exhausted: the kubelet's retry succeeds exactly once
    resp = driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=claims), None)
    for uid in uids:
        assert resp.claims[uid].error == "", resp.claims[uid].error
    assert driver.prepared_claim_count() == 4
    import json as json_mod
    with open(driver.checkpoint_path) as f:
        # versioned envelope (dra.CHECKPOINT_VERSION): claims live under
        # the "claims" key
        assert set(json_mod.load(f)["claims"]) == set(uids)


def test_claim_burst_preadmitted_to_commit_window_before_fanout(dra_rig):
    """Deterministic regression for the commit-window race behind the
    flaky checkpoint-fault failure: _claim_task used to increment
    _attach_active only when a pool worker STARTED its claim, so a claim
    admitted in the same RPC but not yet picked up was invisible to the
    writer's commit window — an early lone claim could commit solo and
    split the burst across checkpoint writes. The whole burst must be
    charged to the gauge BEFORE fan-out: the first claim to run — forced
    here to run to completion before any sibling starts, the exact
    ordering the lazy gauge was blind to — must already see every
    admitted claim counted."""
    from tpu_device_plugin.dra import slice_device_name
    from tpu_device_plugin.kubeletapi import drapb

    host, cfg, apiserver, driver, breaker = dra_rig
    names = [slice_device_name(c.bdf) for c in TWO_MODEL_CHIPS[:2]]
    uids = [f"burst-{i}" for i in range(4)]
    for i, uid in enumerate(uids):
        apiserver.add_claim("ns", uid, uid, driver.driver_name,
                            [{"device": names[i % 2]}])

    seen = []
    real_pool = driver._prepare_pool

    class _FirstClaimAloneThenRest:
        def map(self, fn, items):
            items = list(items)

            def probe(claim):
                seen.append(driver._attach_active)
                return fn(claim)

            out = [probe(items[0])]
            out += list(real_pool.map(probe, items[1:]))
            return out

    driver._prepare_pool = _FirstClaimAloneThenRest()
    try:
        resp = driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=[
                drapb.Claim(namespace="ns", name=u, uid=u)
                for u in uids]), None)
    finally:
        driver._prepare_pool = real_pool

    for uid in uids:
        assert resp.claims[uid].error == "", resp.claims[uid].error
    assert len(seen) == len(uids)
    # the first claim runs before any sibling has started: with lazy
    # admission it saw only itself (1); pre-admission makes the whole
    # burst visible. Later claims see one slot fewer — claim 0's slot is
    # correctly released once it is durable.
    assert seen[0] == len(uids), \
        f"burst not pre-admitted to the commit window: saw {seen}"
    assert driver._attach_active == 0          # every slot released
    assert driver.prepared_claim_count() == 4


# --------------------------------------------------- broker chaos (ISSUE 11)


def test_broker_fault_mid_allocate_degrades_typed_unavailable(dra_rig):
    """faults: broker.ipc armed on the privilege seam — every claim whose
    prepare crosses the boundary while armed errors with the typed
    'broker unavailable' prefix and rolls back; when the fault clears,
    the kubelet retry prepares exactly once (checkpoint audit clean)."""
    from tpu_device_plugin.dra import slice_device_name
    from tpu_device_plugin.kubeletapi import drapb

    host, cfg, apiserver, driver, breaker = dra_rig
    names = [slice_device_name(c.bdf) for c in TWO_MODEL_CHIPS[:2]]
    uids = [f"broker-fault-{i}" for i in range(3)]
    for i, uid in enumerate(uids):
        apiserver.add_claim("ns", uid, uid, driver.driver_name,
                            [{"device": names[i % 2]}])
    claims = [drapb.Claim(namespace="ns", name=uid, uid=uid)
              for uid in uids]

    faults.arm("broker.ipc", kind="drop", count=None)
    resp = driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=claims), None)
    for uid in uids:
        assert "broker unavailable" in resp.claims[uid].error, \
            resp.claims[uid].error
    assert driver.prepared_claim_count() == 0
    faults.disarm("broker.ipc")

    # the retry after "respawn" (fault cleared) prepares exactly once
    resp = driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=claims), None)
    for uid in uids:
        assert resp.claims[uid].error == "", resp.claims[uid].error
    assert driver.prepared_claim_count() == 3


@pytest.fixture
def broker_rig(short_root):
    """dra_rig running against a REAL spawned broker process: every
    privileged read of the prepare path crosses the versioned IPC."""
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin import broker as broker_mod
    from tpu_device_plugin.discovery import discover
    from tpu_device_plugin.dra import DraDriver
    from tpu_device_plugin.kubeapi import ApiClient

    host, cfg = _make_node(short_root, TWO_MODEL_CHIPS[:2])
    proc = broker_mod.spawn_broker(cfg.broker_socket_path, root=short_root)
    client = broker_mod.SocketBrokerClient(cfg.broker_socket_path)
    prev = broker_mod.set_client(client)
    apiserver = FakeApiServer()
    api = ApiClient(apiserver.url, token_path="/nonexistent-token")
    registry, generations = discover(cfg)
    driver = DraDriver(cfg, registry, generations, node_name="broker-node",
                       api=api)
    yield host, cfg, apiserver, driver, proc, client
    driver.stop()
    apiserver.stop()
    broker_mod.set_client(prev)
    client.close()
    if proc.poll() is None:
        proc.terminate()
        proc.wait(timeout=5)


def test_broker_kill9_mid_claim_storm_respawn_claims_survive(broker_rig):
    """The acceptance scenario against a real broker process:

    1. a claim storm prepares through the spawned broker;
    2. kill -9 of the broker mid-storm → the remaining claims degrade to
       typed 'broker unavailable' errors, nothing half-prepares;
    3. respawn + handshake recovers: the kubelet retry prepares the rest
       exactly once (every claim exactly one checkpoint entry);
    4. a serving-daemon restart (rebuild from the schema-versioned
       checkpoint) loses zero claims while the broker keeps running —
       same pid, audit intact."""
    from tpu_device_plugin import broker as broker_mod
    from tpu_device_plugin.dra import DraDriver, slice_device_name
    from tpu_device_plugin.kubeletapi import drapb

    host, cfg, apiserver, driver, proc, client = broker_rig
    names = [slice_device_name(c.bdf) for c in TWO_MODEL_CHIPS[:2]]
    uids = [f"storm-{i}" for i in range(6)]
    for i, uid in enumerate(uids):
        apiserver.add_claim("ns", uid, uid, driver.driver_name,
                            [{"device": names[i % 2]}])

    def prepare(batch):
        return driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=[
                drapb.Claim(namespace="ns", name=u, uid=u)
                for u in batch]), None)

    # phase 1: half the storm lands through the live broker
    resp = prepare(uids[:3])
    for uid in uids[:3]:
        assert resp.claims[uid].error == "", resp.claims[uid].error
    broker_pid = client.stats()["broker"]["pid"]
    assert broker_pid == proc.pid

    # phase 2: kill -9 mid-storm → typed unavailable, no half-prepares
    proc.kill()
    proc.wait(timeout=5)
    resp = prepare(uids[3:])
    for uid in uids[3:]:
        assert "broker unavailable" in resp.claims[uid].error
    assert driver.prepared_claim_count() == 3

    # phase 3: respawn + handshake → the retry prepares exactly once
    proc2 = broker_mod.spawn_broker(cfg.broker_socket_path,
                                    root=short_root_of(host))
    try:
        client.reconnect()
        resp = prepare(uids[3:])
        for uid in uids[3:]:
            assert resp.claims[uid].error == "", resp.claims[uid].error
        assert driver.prepared_claim_count() == 6
        import json as json_mod
        with open(driver.checkpoint_path) as f:
            ckpt = json_mod.load(f)["claims"]
        assert set(ckpt) == set(uids)   # exactly one entry per claim

        # phase 4: serving-daemon restart — rebuild from the checkpoint
        # while the broker keeps running (same pid, ops preserved)
        ops_before = client.stats()["broker"]["ops"].get("revalidate", 0)
        driver.stop()
        driver2 = DraDriver(cfg, *discover_inventory(cfg),
                            node_name="broker-node", api=driver.api)
        try:
            assert driver2.prepared_claim_count() == 6, \
                "serving-daemon restart lost claims"
            stats = client.stats()["broker"]
            assert stats["pid"] == proc2.pid
            assert stats["ops"].get("revalidate", 0) >= ops_before
        finally:
            driver2.stop()
    finally:
        if proc2.poll() is None:
            proc2.terminate()
            proc2.wait(timeout=5)


def short_root_of(host):
    return host.root


def discover_inventory(cfg):
    from tpu_device_plugin.discovery import discover
    return discover(cfg)


def test_broker_ring_fault_storm_falls_back_to_socket_never_wrong(broker_rig):
    """Chaos for the broker.ring fault site (round 20): with the
    shared-memory response ring randomly unusable mid-storm, every hot
    read degrades to a counted socket crossing and still returns the
    exact bytes the broker would have served — the ring is a pure cache,
    never a correctness dependency — and claim prepares riding through
    the same client stay clean. After disarm the ring serves hits
    again without a reattach."""
    from tpu_device_plugin import faults
    from tpu_device_plugin.dra import slice_device_name
    from tpu_device_plugin.kubeletapi import drapb

    host, cfg, apiserver, driver, proc, client = broker_rig
    vendor = os.path.join(short_root_of(host),
                          "sys/bus/pci/devices/0000:00:04.0/vendor")
    assert client.stats()["ring_attached"] is True

    # warm: first read crosses AND publishes; the tight re-read is a
    # ring hit — no socket, no crossing.
    truth = client.read_attr("0000:00:04.0", vendor)
    assert truth == b"0x1ae0\n"
    crossings_warm = client.crossings.value
    assert client.read_attr("0000:00:04.0", vendor) == truth
    assert client.ring_hits.value >= 1
    assert client.crossings.value == crossings_warm

    rng = random.Random(SEED)
    faults.arm("broker.ring", kind="drop", count=None, probability=0.5)
    try:
        fallbacks0 = client.ring_fallbacks.value
        crossings0 = client.crossings.value
        hits0 = client.ring_hits.value
        for i in range(40):
            assert client.read_attr("0000:00:04.0", vendor) == truth
            if rng.random() < 0.2:     # prepares ride the faulted client
                uid = f"ring-chaos-{i}"
                apiserver.add_claim(
                    "ns", uid, uid, driver.driver_name,
                    [{"device": slice_device_name(
                        TWO_MODEL_CHIPS[i % 2].bdf)}])
                resp = driver.NodePrepareResources(
                    drapb.NodePrepareResourcesRequest(claims=[
                        drapb.Claim(namespace="ns", name=uid, uid=uid)]),
                    None)
                assert resp.claims[uid].error == "", resp.claims[uid].error
        forced = client.ring_fallbacks.value - fallbacks0
        assert forced > 0, "fault never forced a socket fallback"
        # every forced fallback paid a real crossing (plus the prepares')
        assert client.crossings.value - crossings0 >= forced
        assert faults.stats().get("broker.ring", 0) > 0
        # under p=0.5 the surviving half still hit the warm ring
        assert client.ring_hits.value > hits0
    finally:
        faults.disarm("broker.ring")

    # recovery: same attachment, hits resume, no crossing paid
    crossings_after = client.crossings.value
    hits_after = client.ring_hits.value
    assert client.read_attr("0000:00:04.0", vendor) == truth
    assert client.ring_hits.value == hits_after + 1
    assert client.crossings.value == crossings_after


# ------------------------------------------- watch-stream chaos (ISSUE 12)


def test_watch_stream_chaos_storm_converges_exactly_once():
    """THE watch-plane chaos contract: a watch-driven fleet under a
    seeded storm of stream breaks, stalls, duplicate deliveries and
    stale resumes (both the fabric's chaos knobs AND every
    kubeapi.watch fault site armed probabilistically) — while slices
    are flipped AND wiped behind the drivers — must converge to the
    exact healthy projection with the fabric's accepted-write audit
    exactly-once, and every reflector must end the run with a live
    (non-degraded) stream again."""
    from tpu_device_plugin.fleetsim import (FleetSim,
                                            assert_fleet_invariants)

    rng = random.Random(SEED)
    faults.seed(SEED)
    sim = FleetSim(n_nodes=4, latency_s=0.0, max_inflight=0, seed=SEED,
                   watch=True, watch_resync_s=5.0, watch_poll_s=0.2,
                   watch_timeout_s=1.0)
    try:
        boot = sim.boot_storm()
        assert boot["published_ok"] == 4
        sim.apiserver.arm_watch_chaos(break_p=0.1, dup_p=0.2,
                                      stall_s=0.002, seed=SEED)
        faults.arm("kubeapi.watch", kind="error", count=None,
                   probability=0.1)
        faults.arm("kubeapi.watch.dup", kind="drop", count=None,
                   probability=0.2)
        faults.arm("kubeapi.watch.stale", kind="drop", count=None,
                   probability=0.05)
        def chaos_bit():
            # the "chaos actually bit" proof the assertions below rely
            # on: a break (either plane) AND a duplicate delivery
            snap = sim.apiserver.snapshot()
            fired = faults.stats()
            return (fired.get("kubeapi.watch", 0)
                    + snap["watch_chaos_breaks_total"] >= 1
                    and sim.watch_totals()
                    ["watch_duplicate_deliveries_total"]
                    + snap["watch_chaos_dups_total"] >= 1)

        # storm for 6 rounds MINIMUM, then keep storming (bounded)
        # until the probabilistic chaos has provably bitten — on a
        # CPU-starved box the watch plane makes few random draws per
        # round, and stopping early would fail the bite assertions
        # below without anything being wrong
        storm_deadline = time.time() + 60
        rounds = 0
        while rounds < 6 or (not chaos_bit()
                             and time.time() < storm_deadline):
            node = rng.choice(sim.nodes)
            node.flip_storm(rng.randrange(1, 4))
            if rng.random() < 0.5:
                victim = rng.choice(sim.nodes)
                victim.driver.api.delete(
                    "/apis/resource.k8s.io/v1beta1/resourceslices/"
                    + victim.driver.slice_name())
            time.sleep(0.05)
            rounds += 1
        # let the watch plane observe and repair; settle() compresses
        # any republish-retry stragglers (its unchanged-projection
        # publishes are no-ops, never extra audited writes)
        deadline = time.time() + 20
        converged = False
        while time.time() < deadline:
            sim.settle()
            try:
                converged = sim.assert_converged()
                break
            except AssertionError:
                time.sleep(0.1)
        assert converged, "fleet never converged under watch chaos"
        faults.disarm()
        sim.apiserver.disarm_watch_chaos()
        assert_fleet_invariants(sim)
        totals = sim.watch_totals()
        assert totals["watch_events_total"] > 0
        # chaos actually bit: breaks and duplicates were survived
        fired = faults.stats()
        assert fired.get("kubeapi.watch", 0) \
            + sim.apiserver.snapshot()["watch_chaos_breaks_total"] >= 1
        assert totals["watch_duplicate_deliveries_total"] \
            + sim.apiserver.snapshot()["watch_chaos_dups_total"] >= 1
        # every reflector recovered to a live stream (bounded wait:
        # post-chaos rotations re-establish quickly)
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(n.driver._watch_live() for n in sim.nodes):
                break
            time.sleep(0.1)
        assert all(n.driver._watch_live() for n in sim.nodes), \
            sim.watch_totals()
    finally:
        faults.reset()
        sim.stop()


# --------------------------------------- snapshot-cache chaos (ISSUE 19)


def test_restart_under_snapshot_fault_converges_zero_lost_claims():
    """`discovery.snapshot` armed across a restart: the warm path's
    cache load reads as untrusted, boot degrades to the counted cold
    walk, the node converges with EVERY prepared claim intact, the
    cold walk re-seeds the cache — and with the fault exhausted the
    NEXT restart rides the snapshot again. The fast path must never
    trade durability for speed: a poisoned cache costs reads, not
    claims."""
    from tpu_device_plugin.fleetsim import FleetSim, fleet_invariants

    sim = FleetSim(n_nodes=1, devices_per_node=8, latency_s=0.0,
                   seed=SEED)
    try:
        node = sim.nodes[0]
        assert node.boot()
        uids = node.register_claims(4)
        resp = node.attach(uids)
        assert not any(resp.claims[u].error for u in uids), resp
        prepared = node.driver.prepared_claim_count()

        seeding = node.restart_with_discovery(warm=True)  # seeds cache
        assert seeding["path"] == "cold"

        faults.arm("discovery.snapshot", kind="drop", count=1)
        poisoned = node.restart_with_discovery(warm=True)
        assert poisoned["path"] == "cold", poisoned
        assert faults.stats().get("discovery.snapshot") == 1
        assert node.driver.prepared_claim_count() == prepared
        # the degraded restart still paid the FULL counted walk (no
        # half-trusted shortcut) and left a fresh cache behind
        assert poisoned["reads"] >= 8 * 5, poisoned

        healed = node.restart_with_discovery(warm=True)
        assert healed["path"] == "snapshot", healed
        assert node.driver.prepared_claim_count() == prepared
        assert healed["reads"] * 10 <= poisoned["reads"]

        # replayed prepares after all three restarts: idempotent, no
        # errors, nothing double-prepared
        replay = node.attach(uids)
        assert not any(replay.claims[u].error for u in uids), replay
        assert node.driver.prepared_claim_count() == prepared
        inv = fleet_invariants(sim, confirm=lambda: None)
        assert inv["ok"], inv["violations"]
    finally:
        sim.stop()
