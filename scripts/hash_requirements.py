#!/usr/bin/env python3
"""Rewrite a requirements file with sha256 --hash lines from a wheel dir.

Usage: python scripts/hash_requirements.py <requirements.txt> <wheel-dir>

`make hash-requirements` drives this: `pip download --no-deps` fills the
wheel dir (network needed), then every `name==version` line gains the
downloaded artifacts' hashes. Once any --hash line is present, pip enforces
hashes for the whole file at install time, so the image build gets integrity
pinning with no Dockerfile change.
"""

import hashlib
import os
import re
import sys


def main() -> int:
    if len(sys.argv) != 3:
        print(__doc__, file=sys.stderr)
        return 2
    req_path, wheel_dir = sys.argv[1], sys.argv[2]

    hashes = {}
    for fname in sorted(os.listdir(wheel_dir)):
        if not fname.endswith((".whl", ".tar.gz", ".zip")):
            continue
        dist = re.split(r"-\d", fname, maxsplit=1)[0]
        key = dist.lower().replace("_", "-")
        with open(os.path.join(wheel_dir, fname), "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()
        if digest not in hashes.setdefault(key, []):  # pure-py wheels repeat
            hashes[key].append(digest)

    out = []
    with open(req_path, encoding="utf-8") as f:
        for line in f:
            stripped = line.strip()
            m = re.match(r"^([A-Za-z0-9._-]+)==\S+", stripped)
            if not m:
                # keep comments/blank lines; drop stale continuation hashes
                if not stripped.startswith("--hash="):
                    out.append(line.rstrip("\n"))
                continue
            key = m.group(1).lower().replace("_", "-")
            # idempotent: strip any line-continuation backslash left by a
            # previous run before re-emitting the pin
            pinned = (stripped.split("#", 1)[0].split("--hash=", 1)[0]
                      .strip().rstrip("\\").strip())
            if key not in hashes:
                print(f"error: no downloaded artifact for {key}",
                      file=sys.stderr)
                return 1
            out.append(pinned + " \\")
            digests = [f"    --hash=sha256:{h}" for h in hashes[key]]
            out.extend(d + " \\" for d in digests[:-1])
            out.append(digests[-1])
    with open(req_path, "w", encoding="utf-8") as f:
        f.write("\n".join(out) + "\n")
    print(f"hashed {len(hashes)} distribution(s) into {req_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
