"""Fleet placement control plane — cluster-wide ICI slice scheduler.

PR 10's placement engine (placement.py) plans within ONE daemon's host
view; production TPU fleets place slices across thousands of hosts.
This module is the scheduler-side consumer ROADMAP item 1 names: it
merges every daemon's PUBLISHED host view — the ResourceSlices the
fleet's drivers keep converged through the PR 12 watch plane — into one
cluster placement decision. Like gpu_ext moves GPU policy out of the
fixed driver into operator-extensible programs (PAPERS.md), the
placement decision moves out of the per-host daemon into a control
plane driven by typed selector expressions over the topology attributes
the daemons publish (dra._device_entry: ICI coords, torus dims,
generation, ring/host ids).

Three planes, all reading lock-free snapshots:

1. **Selector engine.** CEL-style typed attribute expressions —
   `topology.generation == "v5e" && topology.ring_size >= 4` — compiled
   ONCE (`compile_selector`; malformed text raises SelectorError at
   compile, never at match) and evaluated over per-device attribute
   views (`device_attrs`). Pure compute over immutable inputs: no
   selector evaluation ever takes a lock. Semantics: an empty selector
   matches everything; a predicate over an unknown attribute or a
   type-mismatched comparison poisons its boolean branch to NO MATCH
   (counted, never raised to callers) — short-circuit `&&`/`||` mean an
   already-decided branch never touches the bad predicate.

2. **Slice cache + fleet views.** `SliceCache` is the scheduler-side
   informer cache: the PR 12 kubeapi.Reflector feeds it (`on_sync` /
   `on_event`, both idempotent under the at-least-once contract), the
   writer swaps an immutable snapshot under its lock, and every reader
   — selector filtering, placement planning, fragmentation accounting —
   consumes the snapshot without locking. `host_views_from_slices`
   parses published topology attributes back into placement.HostView
   grids, overlaying the scheduler's own claim ledger (a scheduler
   knows what IT placed; slices advertise capacity, not tenancy).

3. **FleetScheduler.** Cluster decisions end-to-end: selector-filtered
   views → placement.plan_slice with the POD-LEVEL host grid (cross-
   host wrap-around ICI meshes, mesh_score contiguity) → execution
   through the fleetsim multiclaim fabric — with ONE commit log
   spanning scheduler decision → per-node sub-claims → rollback,
   audited exactly-once cluster-wide (`audit`), every decision a
   flight-recorder span (`fleetplace.schedule`), and fleet-global
   fragmentation rolled up per generation (`cluster_fragmentation`)
   to drive globally-planned defrag waves applied node-by-node through
   the PR 7 migration-handoff machinery.

docs/design.md "Fleet placement control plane" documents the selector
grammar, the cross-host mesh model, and the global defrag sequence.
"""

from __future__ import annotations

import itertools
import json
import logging
import re
import threading
import time
import zlib
from dataclasses import replace
from types import MappingProxyType
from typing import (Callable, Dict, List, Mapping, Optional, Sequence,
                    Tuple)

from . import lockdep, trace
from .epoch import AtomicCounter
from .placement import HostView, volume

log = logging.getLogger(__name__)

__all__ = ["SelectorError", "CompiledSelector", "compile_selector",
           "device_attrs", "SliceCache", "host_views_from_slices",
           "cluster_fragmentation", "FragAccountant", "FleetScheduler",
           "FleetFlight", "fleet_audit"]


# ====================================================================
# selector engine
# ====================================================================


class SelectorError(ValueError):
    """A selector that cannot compile: bad token, unbalanced parens,
    dangling operator, mixed-type list literal. Raised at COMPILE time
    — a malformed expression must fail loudly when the operator writes
    it, never silently at match time."""


class _EvalMiss(Exception):
    """Internal: a predicate touched an unknown attribute or mismatched
    types. Poisons the enclosing boolean branch to no-match; counted by
    CompiledSelector.matches, never raised to callers."""

    __slots__ = ("kind",)

    def __init__(self, kind: str) -> None:
        self.kind = kind


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<lparen>\() | (?P<rparen>\)) |
      (?P<lbracket>\[) | (?P<rbracket>\]) | (?P<comma>,) |
      (?P<cmp>==|!=|<=|>=|<|>) |
      (?P<andop>&&) | (?P<orop>\|\|) | (?P<notop>!) |
      (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*') |
      (?P<int>-?\d+\b) |
      (?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)
    )""", re.VERBOSE)


def _tokenize(text: str) -> List[Tuple[str, str]]:
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None or m.end() == m.start():
            rest = text[pos:].lstrip()
            if not rest:
                break
            raise SelectorError(
                f"selector: unrecognized input at {rest[:20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind is None:      # trailing whitespace
            continue
        tokens.append((kind, m.group(kind)))
    return tokens


def _type_name(value) -> str:
    # bool before int: isinstance(True, int) holds in Python, but a
    # selector comparing a bool attribute against 1 is a type error
    if isinstance(value, bool):
        return "bool"
    if isinstance(value, int):
        return "int"
    return "string"


_CMP_OPS: Dict[str, Callable] = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}
_ORDER_OPS = {"<", "<=", ">", ">="}

_MISSING = object()


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def resolve_attr(attrs: Mapping[str, object], ident: str):
    """Selector identifier → published attribute value. `topology.` /
    `device.` prefixes address the same flat attribute map the daemon
    publishes; snake_case falls back to the camelCase the wire uses
    (`topology.ring_size` → `ringSize`). Returns _MISSING when no
    candidate resolves."""
    suffix = ident.split(".", 1)[1] \
        if ident.split(".", 1)[0] in ("topology", "device") \
        and "." in ident else ident
    for cand in (ident, suffix, _camel(suffix)):
        if cand in attrs:
            return attrs[cand]
    return _MISSING


class _Parser:
    """Recursive-descent over the token list; every production returns
    a closure. Value closures: attrs -> python value (raising _EvalMiss
    on unknown attributes). Boolean closures: attrs -> bool."""

    def __init__(self, tokens: List[Tuple[str, str]]) -> None:
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) \
            else None

    def take(self, kind: Optional[str] = None) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise SelectorError("selector: unexpected end of expression")
        if kind is not None and tok[0] != kind:
            raise SelectorError(f"selector: expected {kind}, got "
                                f"{tok[1]!r}")
        self.pos += 1
        return tok

    # ------------------------------------------------------- grammar

    def parse(self) -> Callable:
        fn = self.expr()
        if self.peek() is not None:
            raise SelectorError(
                f"selector: trailing input at {self.peek()[1]!r}")
        return fn

    def expr(self) -> Callable:
        terms = [self.and_()]
        while self.peek() and self.peek()[0] == "orop":
            self.take()
            terms.append(self.and_())
        if len(terms) == 1:
            return terms[0]

        def run_or(attrs, _terms=tuple(terms)):
            for t in _terms:
                if t(attrs):
                    return True
            return False
        return run_or

    def and_(self) -> Callable:
        terms = [self.unary()]
        while self.peek() and self.peek()[0] == "andop":
            self.take()
            terms.append(self.unary())
        if len(terms) == 1:
            return terms[0]

        def run_and(attrs, _terms=tuple(terms)):
            for t in _terms:
                if not t(attrs):
                    return False
            return True
        return run_and

    def unary(self) -> Callable:
        if self.peek() and self.peek()[0] == "notop":
            self.take()
            inner = self.unary()
            return lambda attrs: not inner(attrs)
        return self.primary()

    def primary(self) -> Callable:
        tok = self.peek()
        if tok is None:
            raise SelectorError("selector: unexpected end of expression")
        if tok[0] == "lparen":
            self.take()
            inner = self.expr()
            self.take("rparen")
            return inner
        lhs, lhs_desc = self.operand()
        nxt = self.peek()
        if nxt is not None and nxt[0] == "cmp":
            op = self.take()[1]
            rhs, _rhs_desc = self.operand()
            return self._comparison(lhs, op, rhs)
        if nxt is not None and nxt[0] == "ident" and nxt[1] == "in":
            self.take()
            members = self.list_literal()
            return self._membership(lhs, members)
        # bare operand: must evaluate to a bool attribute/literal

        def run_bare(attrs, _lhs=lhs, _desc=lhs_desc):
            value = _lhs(attrs)
            if not isinstance(value, bool):
                raise _EvalMiss("type_mismatch")
            return value
        return run_bare

    @staticmethod
    def _unquote(text: str) -> str:
        """Decode one string-literal token — shared by the operand and
        list-literal positions so the same quoted token denotes the
        same value in `==` and `in` contexts."""
        return text[1:-1].replace("\\" + text[0], text[0]) \
            .replace("\\\\", "\\")

    def operand(self) -> Tuple[Callable, str]:
        tok = self.take()
        kind, text = tok
        if kind == "string":
            value = self._unquote(text)
            return (lambda attrs, _v=value: _v), f"string {value!r}"
        if kind == "int":
            value = int(text)
            return (lambda attrs, _v=value: _v), f"int {value}"
        if kind == "ident":
            if text in ("true", "false"):
                value = text == "true"
                return (lambda attrs, _v=value: _v), f"bool {text}"
            if text == "in":
                raise SelectorError("selector: 'in' needs a left operand")

            def run_ident(attrs, _name=text):
                value = resolve_attr(attrs, _name)
                if value is _MISSING:
                    raise _EvalMiss("unknown_attribute")
                return value
            return run_ident, f"attribute {text}"
        raise SelectorError(f"selector: expected a value, got {text!r}")

    def list_literal(self) -> Tuple:
        self.take("lbracket")
        members: List = []
        while True:
            tok = self.peek()
            if tok is None:
                raise SelectorError("selector: unterminated list literal")
            if tok[0] == "rbracket":
                self.take()
                break
            if members:
                self.take("comma")
                tok = self.peek()
                if tok is not None and tok[0] == "rbracket":
                    self.take()
                    break
            kind, text = self.take()
            if kind == "string":
                members.append(self._unquote(text))
            elif kind == "int":
                members.append(int(text))
            elif kind == "ident" and text in ("true", "false"):
                members.append(text == "true")
            else:
                raise SelectorError(
                    f"selector: list literals hold literals only, got "
                    f"{text!r}")
        if members and len({_type_name(m) for m in members}) > 1:
            raise SelectorError("selector: mixed-type list literal")
        return tuple(members)

    @staticmethod
    def _comparison(lhs: Callable, op: str, rhs: Callable) -> Callable:
        fn = _CMP_OPS[op]
        ordered = op in _ORDER_OPS

        def run_cmp(attrs):
            a = lhs(attrs)
            b = rhs(attrs)
            ta, tb = _type_name(a), _type_name(b)
            if ta != tb or (ordered and ta == "bool"):
                raise _EvalMiss("type_mismatch")
            return fn(a, b)
        return run_cmp

    @staticmethod
    def _membership(lhs: Callable, members: Tuple) -> Callable:
        member_type = _type_name(members[0]) if members else None

        def run_in(attrs):
            value = lhs(attrs)
            if member_type is not None \
                    and _type_name(value) != member_type:
                raise _EvalMiss("type_mismatch")
            return value in members
        return run_in


class CompiledSelector:
    """One compiled selector: `matches(attrs)` over a per-device
    attribute view. Stateless between calls except the lock-free
    AtomicCounter stats — safe to share across scheduler threads, safe
    inside zero-lock read paths."""

    __slots__ = ("text", "_fn", "stats")

    STAT_KEYS = ("evals_total", "matches_total",
                 "unknown_attribute_total", "type_mismatch_total")

    def __init__(self, text: str, fn: Optional[Callable]) -> None:
        self.text = text
        self._fn = fn
        self.stats = {key: AtomicCounter() for key in self.STAT_KEYS}

    def matches(self, attrs: Mapping[str, object]) -> bool:
        self.stats["evals_total"].add()
        if self._fn is None:          # empty selector: match-all
            self.stats["matches_total"].add()
            return True
        try:
            ok = bool(self._fn(attrs))
        except _EvalMiss as miss:
            self.stats[f"{miss.kind}_total"].add()
            ok = False
        if ok:
            self.stats["matches_total"].add()
        return ok

    def snapshot(self) -> Dict[str, int]:
        return {key: counter.value
                for key, counter in self.stats.items()}


def compile_selector(text: str) -> CompiledSelector:
    """Compile a selector expression ONCE; evaluate many times.
    Raises SelectorError on malformed input — compile is where
    expressions fail, match never raises. An empty/whitespace selector
    compiles to match-all."""
    text = (text or "").strip()
    if not text:
        return CompiledSelector(text, None)
    return CompiledSelector(text, _Parser(_tokenize(text)).parse())


def device_attrs(entry: Mapping) -> Dict[str, object]:
    """Flatten one ResourceSlice device entry's typed attributes
    ({"string"|"int"|"bool": v}, v1beta1 "basic"-nested or v1 flat)
    into the plain {name: value} view selectors evaluate over. The
    device's published name rides along as "name"."""
    basic = entry.get("basic")
    attrs = (basic or {}).get("attributes") if isinstance(basic, Mapping) \
        else entry.get("attributes")
    out: Dict[str, object] = {}
    for name, tv in (attrs or {}).items():
        if not isinstance(tv, Mapping):
            continue
        if "string" in tv:
            out[name] = str(tv["string"])
        elif "bool" in tv:
            out[name] = bool(tv["bool"])
        elif "int" in tv:
            out[name] = int(tv["int"])
    out.setdefault("name", entry.get("name"))
    return out


# ====================================================================
# scheduler-side slice cache (the PR 12 Reflector's consumer)
# ====================================================================


class SliceCache:
    """Informer cache over published ResourceSlices, fed by a
    kubeapi.Reflector (`on_sync` for LIST states, `on_event` for watch
    events — both idempotent, surviving the at-least-once delivery
    contract). The writer (reflector thread) mutates its private dict
    under `_lock` and marks it dirty; `snapshot()` rebuilds an
    IMMUTABLE MappingProxyType copy only when something changed since
    the last read, so a 16k-commit watch storm costs O(events) writer
    work, not O(events x fleet) snapshot copies. Readers that hit a
    clean snapshot never lock — fleet accounting and selector
    evaluation run against one frozen cluster state.

    The cache also feeds a FragAccountant (ISSUE 17): every sync/event
    is forwarded on the same writer thread, so the incremental per-node
    placement state converges in lockstep with the raw snapshot."""

    def __init__(self, pod_dims: Optional[Tuple[int, ...]] = None,
                 accountant: Optional["FragAccountant"] = None) -> None:
        self._lock = lockdep.instrument(
            "fleetplace.SliceCache._lock", threading.Lock())
        self._by_name: Dict[str, dict] = {}
        self._snap: Mapping[str, dict] = MappingProxyType({})
        self._dirty = False
        self.syncs = AtomicCounter()
        self.events = AtomicCounter()
        self.accountant = accountant if accountant is not None \
            else FragAccountant(pod_dims=pod_dims)

    def on_sync(self, items: Sequence[dict]) -> None:
        fresh = {}
        for obj in items or ():
            name = ((obj.get("metadata") or {}).get("name"))
            # real apiserver LIST items omit per-item kind (only the
            # List envelope carries one) — skip an item only when a
            # kind IS present and names something else
            if name and obj.get("kind") in (None, "ResourceSlice"):
                fresh[name] = obj
        with self._lock:
            self._by_name = fresh
            self._snap = MappingProxyType(dict(fresh))
            self._dirty = False
        self.accountant.on_sync(fresh)
        # count the sync only once BOTH planes converged: wait_synced
        # is the scheduler's boot barrier, and a sync counted before
        # the accountant finished ingesting would let the first wave
        # plan against a partially-built view set (seen at 4096 nodes
        # as a whole wave of phantom "unplaceable" decisions)
        self.syncs.add()

    def on_event(self, evt: dict) -> None:
        obj = evt.get("object") or {}
        name = (obj.get("metadata") or {}).get("name")
        if not name:
            return
        with self._lock:
            if evt.get("type") == "DELETED":
                self._by_name.pop(name, None)
            else:
                self._by_name[name] = obj
            self._dirty = True
        self.accountant.on_event(evt)
        self.events.add()      # counted only once fully applied

    def snapshot(self) -> Mapping[str, dict]:
        """Lock-free on the hot path: one attribute read of an
        immutable mapping, with a locked O(fleet) rebuild only when
        events landed since the last read (storms coalesce into one
        copy per reader visit)."""
        if self._dirty:
            with self._lock:
                if self._dirty:
                    self._snap = MappingProxyType(dict(self._by_name))
                    self._dirty = False
        return self._snap


_AXES = "xyz"


def _axis_attrs(attrs: Mapping[str, object], prefix: str
                ) -> Optional[Tuple[int, ...]]:
    """("iciX","iciY"[,"iciZ"]) / ("torusX",..) / ("hostX",..) →
    coordinate tuple, None when the leading axis is absent."""
    out: List[int] = []
    for axis in _AXES:
        value = attrs.get(f"{prefix}{axis.upper()}")
        if not isinstance(value, int) or isinstance(value, bool):
            break
        out.append(value)
    return tuple(out) if out else None


def host_views_from_slices(
    slices: Mapping[str, dict],
    claims: Mapping[str, Tuple[Tuple[str, str, Tuple[str, ...]], ...]],
) -> Tuple[Dict[str, List[HostView]],
           Dict[Tuple[str, str], Dict[str, Dict[str, object]]]]:
    """Published ResourceSlices + the scheduler's claim ledger → the
    cluster's placement views.

    The ledger maps uid -> ((sub_uid, node, raws), ...): each shard
    carries its NODE-LEVEL claim identity (`<uid>-<node>` at placement
    time, stable across defrag migrations), and the views' claims maps
    are keyed by those sub-uids — the ids the node drivers' checkpoints
    actually hold — so a defrag advisory computed over these views
    names claims the handoff machinery can really unprepare.

    Returns (views_by_generation, attrs_index): one HostView per
    (node, generation) grouped by generation name, plus the per-device
    attribute views ((node, generation) -> bdf -> attrs) selector
    filtering evaluates. Pure compute over the immutable cache
    snapshot: devices without ICI coords or torus dims (partitions,
    pre-topology daemons) are skipped — a scheduler cannot place a mesh
    on chips whose topology it cannot see. Departed chips never appear
    (the daemon prunes them from its slice), so scheduler-side views
    carry no departed holes; per-daemon /status keeps that accounting.
    """
    grids: Dict[Tuple[str, str], dict] = {}
    attrs_index: Dict[Tuple[str, str], Dict[str, Dict[str, object]]] = {}
    # keyed (node, raw): BDFs repeat across hosts — every node
    # enumerates 0000:00:04.0 — so a bare-BDF key would mark one
    # claim's chips busy fleet-wide
    claimed: Dict[Tuple[str, str], str] = {}
    for _uid, shards in claims.items():
        for sub_uid, node, raws in shards:
            for raw in raws:
                claimed[(node, raw)] = sub_uid
    for obj in slices.values():
        for key, grid, attrs_by_bdf in _parse_slice_grids(obj):
            g = grids.get(key)
            if g is None:
                grids[key] = {"dims": grid["dims"],
                              "coords": dict(grid["coords"]),
                              "names": dict(grid["names"]),
                              "consumed": dict(grid["consumed"]),
                              "host_coords": grid["host_coords"]}
            else:
                g["coords"].update(grid["coords"])
                g["names"].update(grid["names"])
                g["consumed"].update(grid["consumed"])
            attrs_index.setdefault(key, {}).update(attrs_by_bdf)
    views: Dict[str, List[HostView]] = {}
    for (node, generation), g in sorted(grids.items()):
        views.setdefault(generation, []).append(_grid_view(
            node, generation, g, claimed))
    return views, attrs_index


def _parse_slice_grids(obj: Mapping) -> List[tuple]:
    """One published ResourceSlice → [((node, generation), grid,
    attrs_by_bdf)]: the per-slice half of host_views_from_slices,
    shared with the incremental FragAccountant so a delta apply parses
    exactly what a full rebuild would. `grid["consumed"]` carries the
    fabric's CAS placement overlay (spec.consumed, ISSUE 17):
    {bdf: owning multiclaim uid} for chips committed cluster-wide."""
    spec = obj.get("spec") or {}
    node = spec.get("nodeName")
    if not node:
        return []
    consumed = spec.get("consumed") or {}
    grids: Dict[Tuple[str, str], dict] = {}
    attrs_out: Dict[Tuple[str, str], Dict[str, Dict[str, object]]] = {}
    for entry in spec.get("devices") or ():
        attrs = device_attrs(entry)
        generation = attrs.get("generation")
        bdf = attrs.get("bdf")
        coords = _axis_attrs(attrs, "ici")
        dims = _axis_attrs(attrs, "torus")
        if not generation or not bdf or coords is None or dims is None:
            continue
        if len(coords) != len(dims):
            continue
        key = (node, str(generation))
        g = grids.setdefault(key, {
            "dims": dims, "coords": {}, "names": {}, "consumed": {},
            "host_coords": _axis_attrs(attrs, "host")})
        g["coords"][bdf] = coords
        g["names"][bdf] = str(attrs.get("name"))
        if bdf in consumed:
            g["consumed"][bdf] = str(consumed[bdf])
        attrs_out.setdefault(key, {})[bdf] = attrs
    return [(key, grids[key], attrs_out[key]) for key in sorted(grids)]


def _grid_view(node: str, generation: str, grid: Mapping,
               claimed: Mapping[Tuple[str, str], str]) -> HostView:
    """Assemble one HostView from a parsed grid plus the scheduler's
    OWN claim ledger overlay. Busy chips come from two planes: the
    fabric's consumed overlay (cluster-wide committed truth — includes
    every peer scheduler's placements) and the local ledger (covers the
    commit-to-watch-event window for this scheduler's claims). Where
    both know a chip, the ledger's sub-uid wins — it is the id the node
    driver's checkpoint actually holds, the one defrag can unprepare."""
    busy: Dict[str, str] = dict(grid["consumed"])
    for bdf in grid["coords"]:
        sub_uid = claimed.get((node, bdf))
        if sub_uid is not None:
            busy[bdf] = sub_uid
    claim_raws: Dict[str, List[str]] = {}
    for bdf in sorted(busy):
        claim_raws.setdefault(busy[bdf], []).append(bdf)
    return HostView(
        node=node, dims=grid["dims"],
        coords=dict(grid["coords"]), names=dict(grid["names"]),
        free=frozenset(b for b in grid["coords"] if b not in busy),
        departed=frozenset(),
        claims={uid: tuple(raws) for uid, raws in claim_raws.items()},
        host_coords=grid["host_coords"])


def _view_attrs(generation: str, view: HostView, raw: str
                ) -> Dict[str, object]:
    """Synthesize the published attribute view for one chip of a
    driver-side HostView — the same fields dra._device_entry puts on
    the wire, so selector semantics cannot drift between the watch-fed
    and the direct-views scheduler modes."""
    dims = view.dims
    out: Dict[str, object] = {
        "generation": generation,
        "bdf": raw,
        "name": view.names.get(raw, raw),
        "ringSize": max(dims),
        "hostId": view.node,
    }
    coords = view.coords.get(raw)
    if coords is not None:
        for axis, coord in zip(_AXES, coords):
            out[f"ici{axis.upper()}"] = coord
        ring_axis = dims.index(max(dims))
        ring = [str(c) for i, c in enumerate(coords) if i != ring_axis]
        out["ringId"] = "/".join([view.node, generation] + ring)
    for axis, d in zip(_AXES, dims):
        out[f"torus{axis.upper()}"] = d
    if view.host_coords is not None:
        for axis, coord in zip(_AXES, view.host_coords):
            out[f"host{axis.upper()}"] = coord
    return out


# ====================================================================
# fleet-global fragmentation accounting
# ====================================================================


def _largest_free_mesh(views: Sequence[HostView],
                       pod_dims: Optional[Tuple[int, ...]]) -> int:
    """Chips in the largest wrap-aware host-grid window made entirely
    of FULLY-FREE hosts — the biggest cross-host slice placeable right
    now. 0 when the pod grid is unmodeled or fewer than two hosts are
    fully free."""
    from . import placement
    if pod_dims is None:
        return 0
    free_hosts = [v for v in views
                  if v.host_coords is not None
                  and len(v.host_coords) == len(pod_dims)
                  and len(v.free_coords()) == volume(v.dims)
                  and not v.departed]
    if len(free_hosts) < 2:
        return 0
    by_dims: Dict[Tuple[int, ...], List[HostView]] = {}
    for v in free_hosts:
        by_dims.setdefault(v.dims, []).append(v)
    best = 0
    for dims, hosts in by_dims.items():
        host_vol = volume(dims)
        slots = {v.host_coords for v in hosts}
        # windows scanned largest-volume-first so the first hit wins
        shapes = sorted(
            itertools.product(*[range(1, p + 1) for p in pod_dims]),
            key=volume, reverse=True)
        for counts in shapes:
            n = volume(counts)
            # n >= 2: a (1,1) window is a single host, not a mesh —
            # counting it would report cross-host capacity that does
            # not exist (largest_free_box already covers it)
            if n < 2 or n * host_vol <= best or n > len(slots):
                continue
            if placement._mesh_window(counts, hosts, pod_dims) is not None:
                best = n * host_vol
                break
    return best


def cluster_fragmentation(
    views_by_gen: Mapping[str, Sequence[HostView]],
    pod_dims: Optional[Tuple[int, ...]] = None,
) -> Dict[str, dict]:
    """Many hosts' fragmentation records rolled into one cluster curve
    per generation — the record the bench's fragmentation-over-churn
    curves and the defrag planner read. Pure compute over immutable
    views (lock-free by construction):

      hosts/chips/free        cluster totals
      fully_free_hosts        whole tori available for cross-host tiling
      largest_free_box        best single-host contiguous placement
      largest_free_mesh       best cross-host wrap-window placement
      fragmentation           1 - largest_placeable/free (0.0 = one
                              placement reaches every free chip)
      mean_host_fragmentation per-host scores averaged (the per-daemon
                              records' rollup)
    """
    from . import placement
    out: Dict[str, dict] = {}
    for generation, views in sorted(views_by_gen.items()):
        records = [placement.fragmentation(v) for v in views]
        free = sum(r["free"] for r in records)
        largest_box = max((r["largest_free_box"] for r in records),
                          default=0)
        largest_mesh = _largest_free_mesh(views, pod_dims)
        largest = max(largest_box, largest_mesh)
        out[generation] = {
            "hosts": len(views),
            "chips": sum(r["chips"] for r in records),
            "free": free,
            "departed": sum(r["departed"] for r in records),
            "fully_free_hosts": sum(
                1 for v in views
                if len(v.free_coords()) == volume(v.dims)
                and not v.departed),
            "largest_free_box": largest_box,
            "largest_free_mesh": largest_mesh,
            "fragmentation": 0.0 if free == 0
            else round(1.0 - largest / free, 4),
            "mean_host_fragmentation": round(
                sum(r["fragmentation"] for r in records)
                / max(1, len(records)), 4),
        }
    return out


# ====================================================================
# incremental fragmentation accounting (ISSUE 17)
# ====================================================================


class FragAccountant:
    """Per-node cached placement state, updated per WATCH EVENT instead
    of reparsed per decision (ISSUE 17): the SliceCache forwards every
    sync/event on its writer thread, and the accountant keeps — per
    (node, generation) — the parsed grid, the HostView, the per-host
    fragmentation record, and a per-generation FragAggregate rollup.
    A single slice flip costs one slice reparse + one aggregate delta
    (O(request), counted by `frag_delta_applies_total`); a full
    recompute happens only when a 410-compaction relist actually
    changed a slice (`frag_full_recomputes_total`), and relisted
    slices whose resourceVersion / pool generation / placement
    generation are UNCHANGED are skipped entirely
    (`relist_unchanged_skips_total` — the ISSUE 17 bugfix).

    Concurrency: all bookkeeping mutates under `_lock` (the reflector
    writer thread, plus schedulers feeding back commit deltas via
    `apply_placement`). Readers NEVER lock: the published surfaces
    (`views_by_generation`, `attrs_index`, `observed_generations`,
    `fragmentation`) are plain dicts mutated copy-on-KEY-change —
    value stores swap in place (GIL-atomic, resize-free), key inserts/
    deletes replace the whole dict — so the zero-lock read-path gates
    keep pinning 0. The cross-host mesh term is computed LAZILY by
    readers and memoized on a writer-bumped epoch (only fully-free-host
    membership changes invalidate it): a writer-side mesh recompute per
    event would be O(fully_free_hosts x window shapes), exactly the
    fleet-proportional cost this class exists to remove."""

    STAT_KEYS = ("frag_delta_applies_total", "frag_full_recomputes_total",
                 "relist_unchanged_skips_total", "slice_reparses_total")

    def __init__(self, pod_dims: Optional[Tuple[int, ...]] = None) -> None:
        self.pod_dims = tuple(pod_dims) if pod_dims else None
        self._lock = lockdep.instrument(
            "fleetplace.FragAccountant._lock", threading.Lock())
        self.stats = {key: AtomicCounter() for key in self.STAT_KEYS}
        # writer-private bookkeeping (under _lock)
        self._keys: Dict[str, tuple] = {}      # name -> (rv, gen, pgen)
        self._entries: Dict[str, tuple] = {}   # name -> parsed grids
        self._sources: Dict[tuple, set] = {}   # (node, gen) -> {name}
        self._records: Dict[tuple, dict] = {}  # (node, gen) -> frag rec
        self._fully: Dict[tuple, bool] = {}    # (node, gen) -> fully free
        self._aggs: Dict[str, object] = {}     # gen -> FragAggregate
        self._node_slices: Dict[str, set] = {}
        self._slice_nodes: Dict[str, str] = {}
        self._slice_pgens: Dict[str, int] = {}
        # published read surfaces (lock-free readers; see class doc)
        self._views: Dict[str, Dict[str, HostView]] = {}
        self._attrs: Dict[Tuple[str, str], Dict[str, dict]] = {}
        self._gens: Dict[str, int] = {}        # node -> placement gen
        self._frag: Dict[str, dict] = {}       # gen -> rollup(0) record
        self._mesh_epoch: Dict[str, int] = {}
        self._mesh_memo: Dict[str, tuple] = {}  # gen -> (epoch, chips)
        # monotonic mutation stamp: schedulers memoize their ledger
        # overlay on it (a GIL-atomic int read)
        self.version = 0

    @staticmethod
    def _slice_key(obj: Mapping) -> tuple:
        """The skip-detection identity: a slice whose resourceVersion,
        pool generation AND placement generation all match the cached
        copy cannot change any derived state."""
        meta = obj.get("metadata") or {}
        pool = (obj.get("spec") or {}).get("pool") or {}
        return (meta.get("resourceVersion"), pool.get("generation"),
                pool.get("placementGeneration", 0))

    # ------------------------------------------------- writer side

    def on_sync(self, fresh: Mapping[str, dict]) -> None:
        """Full LIST state (initial sync or 410-compaction relist):
        vanished slices drop, changed slices fully recompute, and
        generation-identical slices SKIP — counted, so the regression
        test can prove a relist did not reparse the unchanged fleet."""
        with self._lock:
            for name in [n for n in self._keys if n not in fresh]:
                self._apply_slice_locked(name, None)
            for name, obj in fresh.items():
                if self._keys.get(name) == self._slice_key(obj):
                    self.stats["relist_unchanged_skips_total"].add()
                    continue
                self.stats["slice_reparses_total"].add()
                self.stats["frag_full_recomputes_total"].add()
                self._apply_slice_locked(name, obj)
            self._publish_frag_locked()

    def on_event(self, evt: Mapping) -> None:
        """One watch event -> one slice reparse -> O(1) aggregate
        deltas. Duplicate deliveries (the at-least-once contract) hit
        the same unchanged-identity skip as relists."""
        obj = evt.get("object") or {}
        name = (obj.get("metadata") or {}).get("name")
        if not name:
            return
        with self._lock:
            if evt.get("type") == "DELETED":
                if name in self._keys or name in self._entries:
                    n = self._apply_slice_locked(name, None)
                    self._count_deltas(n)
                    self._publish_frag_locked()
                return
            if self._keys.get(name) == self._slice_key(obj):
                self.stats["relist_unchanged_skips_total"].add()
                return
            self.stats["slice_reparses_total"].add()
            n = self._apply_slice_locked(name, obj)
            self._count_deltas(n)
            self._publish_frag_locked()

    def apply_placement(self, slices_delta) -> int:
        """Commit feedback: the fabric's CAS commit returns the slices
        it restamped ({name, node, resource_version, generation,
        placement_generation, consumed}); folding them in immediately
        closes the commit-to-watch-event window — and stamps the
        post-commit identity, so the watch event that follows is an
        unchanged-identity skip (idempotent in either arrival order)."""
        applied = 0
        with self._lock:
            for rec in slices_delta or ():
                name = rec.get("name")
                if not name or name not in self._entries:
                    continue
                key3 = (rec.get("resource_version"),
                        rec.get("generation"),
                        rec.get("placement_generation") or 0)
                if self._keys.get(name) == key3:
                    continue
                consumed = rec.get("consumed") or {}
                patched = []
                for ekey, grid, attrs in self._entries[name]:
                    g = dict(grid)
                    g["consumed"] = {
                        b: str(consumed[b])
                        for b in g["coords"] if b in consumed}
                    patched.append((ekey, g, attrs))
                applied += self._store_entries_locked(
                    name, tuple(patched), key3,
                    int(rec.get("placement_generation") or 0),
                    rec.get("node"))
            if applied:
                self._publish_frag_locked()
        self._count_deltas(applied)
        return applied

    def _count_deltas(self, n: int) -> None:
        for _ in range(n):
            self.stats["frag_delta_applies_total"].add()

    def _apply_slice_locked(self, name: str,
                            obj: Optional[Mapping]) -> int:
        if obj is None:
            return self._store_entries_locked(name, (), None, 0, None)
        spec = obj.get("spec") or {}
        pool = spec.get("pool") or {}
        return self._store_entries_locked(
            name, tuple(_parse_slice_grids(obj)), self._slice_key(obj),
            int(pool.get("placementGeneration") or 0),
            spec.get("nodeName"))

    def _store_entries_locked(self, name: str, new_entries: tuple,
                              key3, pgen: int,
                              node: Optional[str]) -> int:
        old_entries = self._entries.pop(name, ())
        old_keys = {k for k, _g, _a in old_entries}
        new_keys = {k for k, _g, _a in new_entries}
        if new_entries:
            self._entries[name] = new_entries
            self._keys[name] = key3
        else:
            self._keys.pop(name, None)
        for key in old_keys - new_keys:
            srcs = self._sources.get(key)
            if srcs:
                srcs.discard(name)
                if not srcs:
                    del self._sources[key]
        for key in new_keys - old_keys:
            self._sources.setdefault(key, set()).add(name)
        touched = old_keys | new_keys
        for key in sorted(touched):
            self._rebuild_key_locked(key)
        self._update_node_gen_locked(name, node, pgen,
                                     bool(new_entries))
        self.version += 1
        return len(touched)

    def _rebuild_key_locked(self, key: tuple) -> None:
        from . import placement
        node, generation = key
        # merge every contributing slice's grid for this key (several
        # slices can feed one (node, generation) — the same merge
        # host_views_from_slices does)
        grid = None
        attrs: Dict[str, dict] = {}
        for name in sorted(self._sources.get(key) or ()):
            for ekey, egrid, eattrs in self._entries.get(name, ()):
                if ekey != key:
                    continue
                if grid is None:
                    grid = {"dims": egrid["dims"],
                            "coords": dict(egrid["coords"]),
                            "names": dict(egrid["names"]),
                            "consumed": dict(egrid["consumed"]),
                            "host_coords": egrid["host_coords"]}
                else:
                    grid["coords"].update(egrid["coords"])
                    grid["names"].update(egrid["names"])
                    grid["consumed"].update(egrid["consumed"])
                attrs.update(eattrs)
        old_rec = self._records.pop(key, None)
        old_fully = self._fully.pop(key, False)
        agg = self._aggs.get(generation)
        if old_rec is not None and agg is not None:
            agg.remove(old_rec, old_fully)
        if grid is None:
            if agg is not None and agg.hosts == 0:
                del self._aggs[generation]
            self._publish_view_locked(generation, node, None)
            self._publish_attrs_locked(key, None)
            if old_fully:
                self._bump_mesh_locked(generation)
            return
        # base view: the fabric's consumed overlay only — scheduler
        # ledgers are per-scheduler and overlay downstream
        view = _grid_view(node, generation, grid, {})
        rec = placement.fragmentation(view)
        fully = (not view.departed
                 and len(view.free) == volume(view.dims))
        self._records[key] = rec
        self._fully[key] = fully
        if agg is None:
            agg = self._aggs[generation] = placement.FragAggregate()
        agg.add(rec, fully)
        self._publish_view_locked(generation, node, view)
        self._publish_attrs_locked(key, attrs)
        if fully or old_fully:
            self._bump_mesh_locked(generation)

    def _update_node_gen_locked(self, name: str, node: Optional[str],
                                pgen: int, present: bool) -> None:
        old_node = self._slice_nodes.get(name)
        if present and node:
            self._slice_nodes[name] = node
            self._slice_pgens[name] = pgen or 0
            self._node_slices.setdefault(node, set()).add(name)
        else:
            node = old_node
            self._slice_nodes.pop(name, None)
            self._slice_pgens.pop(name, None)
            if old_node:
                group = self._node_slices.get(old_node)
                if group:
                    group.discard(name)
                    if not group:
                        del self._node_slices[old_node]
        for n in {x for x in (node, old_node) if x}:
            names = self._node_slices.get(n)
            if names:
                self._publish_gen_locked(n, max(
                    self._slice_pgens.get(m, 0) for m in names))
            else:
                self._publish_gen_locked(n, None)

    # published-surface writes: value swap in place, dict copy on any
    # key-set change (readers iterate these dicts lock-free)

    def _publish_view_locked(self, generation: str, node: str,
                             view: Optional[HostView]) -> None:
        views = self._views
        inner = views.get(generation)
        if view is None:
            if not inner or node not in inner:
                return
            fresh_inner = dict(inner)
            del fresh_inner[node]
            fresh = dict(views)
            if fresh_inner:
                fresh[generation] = fresh_inner
            else:
                del fresh[generation]
            self._views = fresh
        elif inner is not None and node in inner:
            inner[node] = view
        else:
            fresh_inner = dict(inner or {})
            fresh_inner[node] = view
            fresh = dict(views)
            fresh[generation] = fresh_inner
            self._views = fresh

    def _publish_attrs_locked(self, key: tuple,
                              attrs: Optional[dict]) -> None:
        cur = self._attrs
        if attrs is None:
            if key in cur:
                fresh = dict(cur)
                del fresh[key]
                self._attrs = fresh
        elif key in cur:
            cur[key] = attrs
        else:
            fresh = dict(cur)
            fresh[key] = attrs
            self._attrs = fresh

    def _publish_gen_locked(self, node: str,
                            gen: Optional[int]) -> None:
        gens = self._gens
        if gen is None:
            if node in gens:
                fresh = dict(gens)
                del fresh[node]
                self._gens = fresh
        elif node in gens:
            gens[node] = gen
        else:
            fresh = dict(gens)
            fresh[node] = gen
            self._gens = fresh

    def _publish_frag_locked(self) -> None:
        self._frag = {gen: agg.rollup()
                      for gen, agg in self._aggs.items()}

    def _bump_mesh_locked(self, generation: str) -> None:
        fresh = dict(self._mesh_epoch)
        fresh[generation] = fresh.get(generation, 0) + 1
        self._mesh_epoch = fresh

    # ------------------------------------------------- reader side

    def views_by_generation(self) -> Mapping[str, Mapping[str, HostView]]:
        """{generation: {node: HostView}} — the fabric-truth base views
        (consumed overlay applied, no scheduler ledger). Lock-free."""
        return self._views

    def attrs_index(self) -> Mapping[Tuple[str, str], Dict[str, dict]]:
        return self._attrs

    def observed_generations(self) -> Mapping[str, int]:
        """{node: placement generation} as last seen from the watch
        plane — the CAS observation a scheduler commits against."""
        return self._gens

    def fragmentation(self) -> Dict[str, dict]:
        """The cluster_fragmentation record shape from the maintained
        aggregates — O(generations) plus a lazily-memoized mesh scan
        (recomputed only when fully-free-host membership changed).
        Zero locks: safe inside the fleetplace.frag read-path gate."""
        out: Dict[str, dict] = {}
        frag = self._frag
        views = self._views
        for generation in sorted(frag):
            rec = dict(frag[generation])
            mesh = self._mesh_for(generation, views.get(generation))
            rec["largest_free_mesh"] = mesh
            largest = max(rec["largest_free_box"], mesh)
            free = rec["free"]
            rec["fragmentation"] = (0.0 if free == 0
                                    else round(1.0 - largest / free, 4))
            out[generation] = rec
        return out

    def _mesh_for(self, generation: str, inner) -> int:
        if self.pod_dims is None or not inner:
            return 0
        epoch = self._mesh_epoch.get(generation, 0)
        memo = self._mesh_memo.get(generation)
        if memo is not None and memo[0] == epoch:
            return memo[1]
        mesh = _largest_free_mesh(list(inner.values()), self.pod_dims)
        # racing reader stores are benign: both computed from the same
        # or a newer epoch's views, and the epoch tag keeps them honest
        self._mesh_memo[generation] = (epoch, mesh)
        return mesh

    def snapshot(self) -> dict:
        out = {key: c.value for key, c in self.stats.items()}
        out["tracked_slices"] = len(self._keys)
        return out


# ====================================================================
# fleet flight collector (the cross-node trace waterfall, ISSUE 15)
# ====================================================================


class FleetFlight:
    """Scheduler-side flight collector: merges per-node ``/debug/flight``
    rings into ONE cross-node, cross-process waterfall for a trace id —
    the ``/debug/fleet/trace?trace=`` body.

    Sources are named fetch callables taking a query dict ({"trace":
    id}) and returning the /debug/flight JSON shape ({"spans": [...]}).
    ``add_http_source`` pulls a real daemon's endpoint over HTTP (the
    production deployment shape); fleetsim builds in-process sources of
    the SAME shape (FleetSim.fleet_flight) — one per node, filtered by
    the ``node`` attribute its driver stamps on every RPC span. A
    source that fails to answer degrades to a per-source error note
    (an incident view must render the nodes that DID answer).

    Merging dedupes by the records' process-unique identity
    ((thread, seq, ts, op) — per-node sources backed by one shared
    in-process recorder overlap by construction), labels every record
    with its node (the span's own ``node`` attr wins over the source
    name), and returns the records time-ordered: the waterfall."""

    def __init__(self) -> None:
        self._sources: Dict[str, Callable[[dict], dict]] = {}

    def add_source(self, name: str,
                   fetch: Callable[[dict], dict]) -> None:
        self._sources[name] = fetch

    def add_http_source(self, name: str, base_url: str,
                        timeout_s: float = 5.0) -> None:
        """Pull `name`'s flight ring from its status endpoint
        (`<base_url>/debug/flight?trace=...`) — the real-deployment
        source shape."""
        import urllib.parse
        import urllib.request

        base = base_url.rstrip("/")

        def fetch(query: dict) -> dict:
            qs = urllib.parse.urlencode(
                {k: v for k, v in query.items() if v is not None})
            with urllib.request.urlopen(
                    f"{base}/debug/flight?{qs}", timeout=timeout_s) as r:
                return json.loads(r.read())
        self.add_source(name, fetch)

    def add_local_source(self, name: str = "local") -> None:
        """THIS process's recorder as a source (the single-daemon
        deployment: /debug/fleet/trace serves the local ring until an
        operator registers the fleet's endpoints)."""
        self.add_source(
            name, lambda query: {"spans": trace.snapshot(
                trace=query.get("trace"))})

    def sources(self) -> List[str]:
        return sorted(self._sources)

    def trace(self, trace_id: str, limit: Optional[int] = None) -> dict:
        """The merged waterfall for one trace id: every source's
        matching records (own trace_id OR span-link match — the
        migration-handoff joins), deduped, node-labeled, time-ordered.
        `limit` keeps the newest N after the merge."""
        merged: List[dict] = []
        seen: set = set()
        errors: Dict[str, str] = {}
        for name, fetch in sorted(self._sources.items()):
            try:
                body = fetch({"trace": trace_id})
            except Exception as exc:
                errors[name] = str(exc)
                continue
            for rec in body.get("spans") or ():
                key = (rec.get("thread"), rec.get("seq"),
                       rec.get("ts"), rec.get("op"))
                if key in seen:
                    continue
                seen.add(key)
                rec = dict(rec)
                rec["node"] = (rec.get("attrs") or {}).get("node") or name
                merged.append(rec)
        merged.sort(key=lambda r: (r.get("ts", 0), r.get("seq", 0)))
        if limit is not None and limit >= 0:
            merged = merged[len(merged) - min(limit, len(merged)):]
        # nodes/ops summarize the RETURNED page (post-limit), so a
        # limited body is internally consistent — never a node with
        # zero spans in the waterfall it headlines
        return {
            "trace": trace_id,
            "spans": merged,
            "nodes": sorted({r["node"] for r in merged}),
            "ops": sorted({r["op"] for r in merged}),
            "sources": len(self._sources),
            "source_errors": errors,
        }


# ====================================================================
# the scheduler
# ====================================================================


class _WaveIndex:
    """Working free-capacity index for ONE decision wave: candidate
    hosts bucketed by free-chip count, working copies updated as the
    wave reserves capacity claim-by-claim. A request probes a bounded
    number of single-host candidates best-fit-first (decision cost
    scales with the request), falling back to the full fleet planner
    only for shapes a single host cannot satisfy — the rare path at
    storm scale."""

    PROBES = 8

    def __init__(self, views: Sequence[HostView]) -> None:
        self._views: List[HostView] = list(views)
        self._node_idx: Dict[str, List[int]] = {}
        self._buckets: Dict[int, Dict[int, None]] = {}
        for i, v in enumerate(self._views):
            self._node_idx.setdefault(v.node, []).append(i)
            if v.free:
                self._buckets.setdefault(len(v.free), {})[i] = None

    def plan(self, shape, best_effort: bool,
             pod_dims: Optional[Tuple[int, ...]]):
        from . import placement
        need = volume(shape)
        tried = 0
        for count in sorted(c for c in self._buckets if c >= need):
            for i in list(self._buckets[count]):
                plan = placement.plan_slice(shape, [self._views[i]],
                                            pod_dims=pod_dims)
                if plan is not None:
                    return plan
                tried += 1
                if tried >= self.PROBES:
                    break
            if tried >= self.PROBES:
                break
        return placement.plan_slice(shape, self._views,
                                    best_effort=best_effort,
                                    pod_dims=pod_dims)

    def reserve(self, plan) -> None:
        for node, raws in plan.shards:
            taken = frozenset(raws)
            for i in self._node_idx.get(node, ()):
                view = self._views[i]
                if not (taken & view.free):
                    continue
                old = len(view.free)
                view = replace(view, free=view.free - taken)
                self._views[i] = view
                bucket = self._buckets.get(old)
                if bucket is not None:
                    bucket.pop(i, None)
                    if not bucket:
                        del self._buckets[old]
                if view.free:
                    self._buckets.setdefault(
                        len(view.free), {})[i] = None


class FleetScheduler:
    """Cluster-wide slice scheduler over the published topology.

    Views come from the reflector-fed SliceCache (production shape) or
    a `views_source` callable returning {generation: [HostView]}
    (tests/benches without a watch plane). Decisions execute through an
    `executor` — fleetsim.FleetSim is the reference implementation
    (`execute_plan` / `release_plan` / `apply_defrag`), carrying the
    fabric's cross-node multiclaim records — and EVERY lifecycle step
    lands in one commit log: decision → per-node sub-claims → rollback/
    commit, audited exactly-once by `audit()`. All reads (selector
    filtering, views, fragmentation) are lock-free snapshot reads
    bracketed by lockdep read paths, pinned at zero lock acquisitions
    by tests/test_fleetplace.py."""

    def __init__(self, executor=None,
                 cache: Optional[SliceCache] = None,
                 reflector=None,
                 views_source: Optional[Callable[[], Mapping[
                     str, Sequence[HostView]]]] = None,
                 pod_dims: Optional[Tuple[int, ...]] = None,
                 shard_index: int = 0, shard_count: int = 1,
                 partition: bool = False,
                 wave_max: int = 64, wave_window_s: float = 0.0,
                 replan_max: int = 3,
                 conflict_wait_s: float = 2.0) -> None:
        if cache is None and views_source is None:
            raise ValueError("FleetScheduler needs a SliceCache or a "
                             "views_source")
        self.executor = executor
        self.cache = cache
        self.reflector = reflector
        self._views_source = views_source
        self.pod_dims = tuple(pod_dims) if pod_dims else None
        # sharded-fleet identity (ISSUE 17): N schedulers share one
        # fabric; `partition` additionally narrows THIS instance's
        # offered capacity to its node band so a partitioned fleet
        # converges with ~zero CAS conflicts, while partition=False
        # exercises the full optimistic-concurrency conflict path
        self.shard_index = int(shard_index)
        self.shard_count = max(1, int(shard_count))
        self.partition = bool(partition)
        self.wave_max = max(1, int(wave_max))
        self.wave_window_s = float(wave_window_s)
        self.replan_max = max(0, int(replan_max))
        self.conflict_wait_s = float(conflict_wait_s)
        self._obs_ok: Optional[bool] = None
        self._defrag_fb_ok: Optional[bool] = None
        self._pending: List[dict] = []
        self._pending_lock = lockdep.instrument(
            "fleetplace.FleetScheduler._pending_lock", threading.Lock())
        # claim ledger: uid -> ((sub_uid, node, raws), ...) — each
        # shard carries its node-level claim identity, minted at
        # placement (`<uid>-<node>`) and KEPT across defrag migrations
        # (the node checkpoints know the claim by that id wherever it
        # lives now). Copy-on-write swaps keep readers lock-free (the
        # GIL makes the attribute store atomic).
        self._claims: Dict[str, Tuple] = {}
        # identity-memoized cluster views: both the cache snapshot and
        # the ledger are swapped wholesale (never mutated), so reusing
        # the parse while both references are unchanged is exact —
        # steady-state reads stop re-parsing 2048 device entries per
        # decision at 256 nodes
        self._views_memo: Optional[Tuple] = None
        self._claims_lock = lockdep.instrument(
            "fleetplace.FleetScheduler._claims_lock", threading.Lock())
        # THE commit log: (kind, uid, detail) tuples, append-only.
        # list.append is GIL-atomic; audit() reads a C-atomic copy.
        self._log: List[Tuple[str, str, object]] = []
        self._selectors: Dict[str, CompiledSelector] = {}
        self._selector_lock = lockdep.instrument(
            "fleetplace.FleetScheduler._selector_lock", threading.Lock())
        self.stats = {key: AtomicCounter() for key in (
            "decisions_total", "placed_total", "unplaceable_total",
            "rollbacks_total", "releases_total", "defrag_waves_total",
            "defrag_moves_total", "selector_compile_errors_total",
            "bias_applied_total", "bias_cleared_total",
            "drains_planned_total", "decision_waves_total",
            "commit_conflicts_total", "replans_total")}
        # remediation seam: nodes the self-heal plane is steering new
        # placements away from (exemplar->node attribution pinned a
        # host). Copy-on-write frozenset — the zero-lock decision read
        # path reads the reference GIL-atomically; writes (rare, one
        # per remediation action) serialize on _bias_lock.
        self._avoid_nodes: frozenset = frozenset()
        self._bias_lock = lockdep.instrument(
            "fleetplace.FleetScheduler._bias_lock", threading.Lock())

    # ------------------------------------------------------- control

    def start(self) -> None:
        if self.reflector is not None:
            self.reflector.start()

    def stop(self) -> None:
        if self.reflector is not None:
            self.reflector.stop()

    def wait_synced(self, timeout_s: float = 10.0,
                    min_slices: int = 0) -> bool:
        """Block until the reflector's first LIST seeded the cache (and
        at least `min_slices` slices are visible) — the scheduler's
        boot barrier. True on sync, False on timeout."""
        if self.cache is None:
            return True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.cache.syncs.value > 0 \
                    and len(self.cache.snapshot()) >= min_slices:
                return True
            time.sleep(0.02)
        return False

    # ------------------------------------------------- views + selectors

    def selector(self, text: str) -> CompiledSelector:
        """Compile-once cache: one CompiledSelector per expression text,
        its stats accumulating across decisions. Compile failures count
        and re-raise (SelectorError)."""
        text = (text or "").strip()
        compiled = self._selectors.get(text)    # lock-free hit
        if compiled is not None:
            return compiled
        try:
            compiled = compile_selector(text)
        except SelectorError:
            self.stats["selector_compile_errors_total"].add()
            raise
        with self._selector_lock:
            compiled = self._selectors.setdefault(text, compiled)
        return compiled

    def views_by_generation(self) -> Tuple[
            Dict[str, List[HostView]],
            Dict[Tuple[str, str], Dict[str, Dict[str, object]]]]:
        """The merged cluster view: every daemon's published host view
        + the scheduler's own ledger. Lock-free snapshot reads. In
        views_source mode the attribute index is SYNTHESIZED from the
        views with the same fields the daemon publishes, so selectors
        behave identically with or without a watch plane."""
        if self.cache is not None:
            # incremental path (ISSUE 17): the accountant maintained
            # the base views per WATCH EVENT; only the ledger overlay
            # is (re)applied here, memoized on the accountant's
            # mutation stamp + the ledger identity — a decision never
            # reparses the fleet
            acct = self.cache.accountant
            version = acct.version
            claims = self._claims
            memo = self._views_memo
            if memo is not None and memo[0] == version \
                    and memo[1] is claims:
                return memo[2], memo[3]
            views = self._overlay_ledger(acct.views_by_generation(),
                                         claims)
            idx = acct.attrs_index()
            self._views_memo = (version, claims, views, idx)
            return views, idx
        views = {gen: list(vs)
                 for gen, vs in self._views_source().items()}
        attrs_index: Dict[Tuple[str, str],
                          Dict[str, Dict[str, object]]] = {}
        for gen, vs in views.items():
            for view in vs:
                attrs_index[(view.node, gen)] = {
                    raw: _view_attrs(gen, view, raw)
                    for raw in view.coords}
        return views, attrs_index

    def _overlay_ledger(self, base: Mapping[str, Mapping[str, HostView]],
                        claims: Mapping[str, Tuple]
                        ) -> Dict[str, List[HostView]]:
        """Stamp THIS scheduler's ledger onto the accountant's base
        views. The base busy set is the fabric's consumed overlay
        (parent multiclaim uids — every peer's commits included); where
        the ledger also knows a chip, its SUB-uid wins: that is the id
        the node checkpoint holds, the one defrag can unprepare. Views
        without ledger chips pass through untouched (shared with the
        accountant's published dicts — never mutated)."""
        by_node: Dict[str, Dict[str, str]] = {}
        for _uid, shards in claims.items():
            for sub_uid, node, raws in shards:
                dest = by_node.setdefault(node, {})
                for raw in raws:
                    dest[raw] = sub_uid
        out: Dict[str, List[HostView]] = {}
        for generation in sorted(base):
            inner = base[generation]
            views: List[HostView] = []
            for node in sorted(inner):
                view = inner[node]
                ledger = by_node.get(node)
                if ledger:
                    view = self._overlay_view(view, ledger)
                views.append(view)
            out[generation] = views
        return out

    @staticmethod
    def _overlay_view(view: HostView,
                      ledger: Mapping[str, str]) -> HostView:
        busy: Dict[str, str] = {}
        for uid, raws in view.claims.items():
            for raw in raws:
                busy[raw] = uid
        changed = False
        for raw, sub_uid in ledger.items():
            if raw in view.coords and busy.get(raw) != sub_uid:
                busy[raw] = sub_uid
                changed = True
        if not changed:
            return view
        claim_raws: Dict[str, List[str]] = {}
        for raw in sorted(busy):
            claim_raws.setdefault(busy[raw], []).append(raw)
        return replace(
            view,
            free=frozenset(r for r in view.coords if r not in busy),
            claims={uid: tuple(raws)
                    for uid, raws in claim_raws.items()})

    def _owns_node(self, view: HostView) -> bool:
        """Partition membership for `partition=True` fleets: host-grid
        row bands when the pod grid is modeled (keeps each shard's
        nodes ICI-adjacent, so in-shard cross-host meshes survive),
        stable hashing otherwise."""
        if self.shard_count <= 1:
            return True
        hc = view.host_coords
        if hc and self.pod_dims:
            band = max(1, self.pod_dims[0] // self.shard_count)
            return min(hc[0] // band,
                       self.shard_count - 1) == self.shard_index
        return (zlib.crc32(view.node.encode())
                % self.shard_count) == self.shard_index

    @staticmethod
    def _filter_views(views_by_gen: Mapping[str, Sequence[HostView]],
                      attrs_index, compiled: CompiledSelector
                      ) -> Dict[str, List[HostView]]:
        """Per-generation selector filtering: each view's FREE set
        narrows to the chips whose published attributes match; a view
        left with no matching free chip still participates as occupancy
        (its claims can still block boxes) but offers nothing."""
        out: Dict[str, List[HostView]] = {}
        for generation, views in views_by_gen.items():
            filtered: List[HostView] = []
            for view in views:
                index = attrs_index.get((view.node, generation))
                if compiled._fn is None or index is None:
                    filtered.append(view)
                    continue
                keep = frozenset(
                    raw for raw in view.free
                    if compiled.matches(index.get(raw, {})))
                if keep != view.free:
                    view = replace(view, free=keep)
                filtered.append(view)
            out[generation] = filtered
        return out

    def eligible_views(self, selector_text: str = ""
                       ) -> Tuple[List[HostView], CompiledSelector]:
        """Selector-filtered cluster views, flattened across
        generations. Runs inside the `fleetplace.select` read-path
        bracket — zero registered locks, counted."""
        compiled = self.selector(selector_text)
        with lockdep.read_path("fleetplace.select"):
            views_by_gen, attrs_index = self.views_by_generation()
            filtered = self._filter_views(views_by_gen, attrs_index,
                                          compiled)
            avoid = self._avoid_nodes          # GIL-atomic ref read
            shard = self.partition and self.shard_count > 1
            out = []
            for views in filtered.values():
                for v in views:
                    if v.free and (v.node in avoid
                                   or (shard
                                       and not self._owns_node(v))):
                        # biased-away or out-of-shard host: still
                        # occupancy (its claims keep blocking boxes)
                        # but offers no capacity
                        v = replace(v, free=frozenset())
                    out.append(v)
            return out, compiled

    # ---------------------------------------------------- decisions

    def _note(self, kind: str, uid: str, detail=None) -> None:
        self._log.append((kind, uid, detail))

    def schedule(self, shape, uid: str, selector: str = "",
                 best_effort: bool = False,
                 fail_node: Optional[str] = None) -> dict:
        """One cluster placement decision end-to-end: selector-filtered
        views → plan (cross-host mesh aware) → optimistic CAS commit
        through the multiclaim fabric. A conflicting commit (a peer
        scheduler consumed a planned chip first) is a clean counted
        abort: the fabric refused atomically, the executor unwound the
        prepares, and the decision REPLANS against the caught-up cache
        — plan → conflict-abort → replan → commit all on ONE trace id
        (the /debug/fleet/trace waterfall of a contended claim)."""
        from . import placement
        shape = placement.parse_shape(shape)
        self.stats["decisions_total"].add()
        t0 = time.monotonic()
        with trace.span("fleetplace.schedule", claim_uid=uid,
                        shape="x".join(str(d) for d in shape),
                        selector=selector or ""):
            # the decision's trace id is THE fleet trace handle: shard
            # prepares, broker crossings and later migration handoffs
            # all join it, and every schedule() result returns it so a
            # caller can open /debug/fleet/trace?trace= directly
            ctx = trace.current_context()
            trace_id = ctx["trace_id"] if ctx else None
            attempt = 0
            while True:
                if attempt == 0:
                    result = self._attempt_once(
                        shape, uid, selector, best_effort, fail_node,
                        trace_id)
                else:
                    with trace.span("fleetplace.replan",
                                    claim_uid=uid, attempt=attempt):
                        result = self._attempt_once(
                            shape, uid, selector, best_effort,
                            fail_node, trace_id)
                if result.get("conflict"):
                    self.stats["commit_conflicts_total"].add()
                    trace.event(
                        "fleetplace.conflict_abort", claim_uid=uid,
                        attempt=attempt,
                        nodes=",".join(sorted(
                            result.get("conflicts") or ())))
                    if attempt < self.replan_max:
                        attempt += 1
                        self.stats["replans_total"].add()
                        self._await_catchup(
                            result.get("placement_gens") or {},
                            result.get("conflicts") or ())
                        continue
                break
            ms = (time.monotonic() - t0) * 1e3
            result.setdefault("latency_ms", round(ms, 3))
            trace.observe("tdp_fleet_decision_ms", ms,
                          exemplar=trace_id)
            return result

    def _attempt_once(self, shape, uid: str, selector: str,
                      best_effort: bool, fail_node: Optional[str],
                      trace_id: Optional[str]) -> dict:
        """One plan→execute attempt of a decision (the body schedule()
        replans on CAS conflict). Every attempt logs a fresh `decided`
        entry — the audit's prepared-set tracking resets with it, so a
        conflict-unwound attempt followed by a replan stays clean."""
        from . import placement
        views, _compiled = self.eligible_views(selector)
        plan = placement.plan_slice(shape, views,
                                    best_effort=best_effort,
                                    pod_dims=self.pod_dims)
        self._note("decided", uid, {
            "shape": list(shape), "selector": selector or "",
            "shards": None if plan is None
            else [[n, list(r)] for n, r in plan.shards]})
        if plan is None:
            self.stats["unplaceable_total"].add()
            self._note("unplaceable", uid, None)
            trace.event("fleetplace.unplaceable", claim_uid=uid)
            return {"uid": uid, "placed": False,
                    "reason": "unplaceable", "trace_id": trace_id}
        if self.executor is None:
            # plan-only mode (dry runs / what-if): the decision is
            # logged as advisory, never committed
            self._note("advisory", uid, None)
            return {"uid": uid, "placed": True, "advisory": True,
                    "trace_id": trace_id,
                    "score": plan.score, "hosts": plan.hosts,
                    "shards": [(n, list(r)) for n, r in plan.shards]}
        observed = self._observed_for(plan)
        if observed is None or not self._observed_supported():
            result = self.executor.execute_plan(
                plan, uid, fail_node=fail_node, observer=self._note)
        else:
            result = self.executor.execute_plan(
                plan, uid, fail_node=fail_node, observer=self._note,
                observed=observed)
        result.setdefault("trace_id", trace_id)
        if result.get("placed"):
            self._commit_ledger(uid, plan.shards)
            self.stats["placed_total"].add()
            self._apply_commit_feedback(result)
            trace.event("fleetplace.commit", claim_uid=uid)
        elif not result.get("conflict"):
            self.stats["rollbacks_total"].add()
        return result

    def _commit_ledger(self, uid: str, shards) -> None:
        with self._claims_lock:
            fresh = dict(self._claims)
            fresh[uid] = tuple(
                (f"{uid}-{node}", node, tuple(raws))
                for node, raws in shards)
            self._claims = fresh

    def _observed_for(self, plan) -> Optional[Dict[str, int]]:
        """The CAS observation: per planned node, the placement
        generation this scheduler's cache last saw. None in
        views_source mode — no watch plane, no concurrent peers."""
        if self.cache is None:
            return None
        gens = self.cache.accountant.observed_generations()
        return {node: gens.get(node, 0) for node, _raws in plan.shards}

    def _observed_supported(self) -> bool:
        """Does the attached executor's execute_plan take `observed`?
        Probed once (test doubles predate the CAS protocol)."""
        flag = self._obs_ok
        if flag is None:
            import inspect
            try:
                flag = "observed" in inspect.signature(
                    self.executor.execute_plan).parameters
            except (TypeError, ValueError):
                flag = False
            self._obs_ok = flag
        return flag

    def _defrag_feedback_ok(self) -> bool:
        """Does the executor's apply_defrag hand back restamp deltas
        (deltas_out)? Probed once, like _observed_supported."""
        flag = self._defrag_fb_ok
        if flag is None:
            import inspect
            try:
                flag = "deltas_out" in inspect.signature(
                    self.executor.apply_defrag).parameters
            except (TypeError, ValueError, AttributeError):
                flag = False
            self._defrag_fb_ok = flag
        return flag

    def _apply_commit_feedback(self, result: Mapping) -> None:
        """Fold the commit's restamped-slice deltas into the accountant
        immediately: the cache converges without waiting on the watch
        round-trip, and the later MODIFIED event lands as an
        unchanged-identity skip."""
        placement_rec = result.get("placement")
        if not placement_rec or self.cache is None:
            return
        self.cache.accountant.apply_placement(
            placement_rec.get("slices") or ())

    def _await_catchup(self, target_gens: Mapping[str, int],
                       nodes) -> None:
        """Block (bounded) until the watch plane delivered the peer
        commit that beat us: replanning before the conflicted nodes'
        views catch up to the generations the fabric reported would
        re-pick the same chips and conflict again."""
        if self.cache is None:
            return
        wanted = set(nodes)
        want = {n: g for n, g in (target_gens or {}).items()
                if not wanted or n in wanted}
        if not want:
            return
        acct = self.cache.accountant
        deadline = time.monotonic() + self.conflict_wait_s
        while time.monotonic() < deadline:
            gens = acct.observed_generations()
            if all(gens.get(n, 0) >= g for n, g in want.items()):
                return
            time.sleep(0.005)

    # ------------------------------------------- decision waves (r19)

    def submit(self, shape, uid: str, selector: str = "",
               best_effort: bool = False) -> int:
        """Queue a claim for the next decision wave. Returns the queue
        depth; `pump()` fires the wave by the group-commit rules."""
        from . import placement
        req = {"shape": placement.parse_shape(shape), "uid": uid,
               "selector": (selector or ""),
               "best_effort": bool(best_effort),
               "t0": time.monotonic()}
        with self._pending_lock:
            self._pending.append(req)
            return len(self._pending)

    def pump(self, force: bool = False) -> List[dict]:
        """Fire a wave when the PR 4 group-commit rules say so: a full
        wave (`wave_max`), an expired wave window, or a LONE claim —
        which commits immediately, never waiting for company that may
        not come."""
        with self._pending_lock:
            if not self._pending:
                return []
            age = time.monotonic() - self._pending[0]["t0"]
            if not (force or len(self._pending) == 1
                    or len(self._pending) >= self.wave_max
                    or age >= self.wave_window_s):
                return []
            batch = self._pending[:self.wave_max]
            self._pending = self._pending[self.wave_max:]
        return self.schedule_wave(batch)

    def drain(self) -> List[dict]:
        """Flush the queue through forced waves (harness teardown)."""
        out: List[dict] = []
        while True:
            fired = self.pump(force=True)
            if not fired:
                return out
            out.extend(fired)

    def schedule_wave(self, requests,
                      best_effort: bool = False) -> List[dict]:
        """One batched decision wave over a claim storm: ONE snapshot
        acquisition, ONE volume-sorted planning pass against a working
        free-capacity index (decision cost scales with the request —
        the accountant keeps the views, the index narrows candidates),
        and ONE batched fabric commit round for the whole wave — the
        PR 4 group-commit shape lifted to the scheduler tier. Requests
        are (shape, uid) pairs or submit()-shaped dicts. CAS conflicts
        replan in bounded follow-up rounds; every claim's result
        carries its decision latency and trace id."""
        from . import placement
        reqs: List[dict] = []
        for r in requests:
            if isinstance(r, Mapping):
                reqs.append({
                    "shape": placement.parse_shape(r["shape"]),
                    "uid": r["uid"],
                    "selector": (r.get("selector") or ""),
                    "best_effort": bool(r.get("best_effort",
                                              best_effort)),
                    "t0": r.get("t0")})
            else:
                shape, uid = r
                reqs.append({"shape": placement.parse_shape(shape),
                             "uid": uid, "selector": "",
                             "best_effort": best_effort, "t0": None})
        if not reqs:
            return []
        wave_start = time.monotonic()
        for req in reqs:
            if req["t0"] is None:
                req["t0"] = wave_start
            self.stats["decisions_total"].add()
        self.stats["decision_waves_total"].add()
        wave_id = self.stats["decision_waves_total"].value
        results: Dict[str, dict] = {}
        pending = reqs
        attempt = 0
        with trace.span("fleetplace.wave", wave=wave_id,
                        claims=len(reqs), shard=self.shard_index):
            while pending:
                batch = self._plan_wave(pending, wave_id, attempt,
                                        results)
                if not batch:
                    break
                outcomes = self._execute_batch(batch)
                conflicted: List[Tuple[dict, dict]] = []
                for item in batch:
                    uid = item["uid"]
                    res = outcomes.get(uid) or {
                        "uid": uid, "placed": False,
                        "reason": "no_result"}
                    res.setdefault("trace_id",
                                   item["req"].get("trace_id"))
                    if res.get("placed"):
                        if not res.get("advisory"):
                            self._commit_ledger(
                                uid, item["plan"].shards)
                            self.stats["placed_total"].add()
                            self._apply_commit_feedback(res)
                            trace.event("fleetplace.commit",
                                        claim_uid=uid,
                                        link=item["req"].get("lctx"))
                        results[uid] = res
                    elif res.get("conflict"):
                        self.stats["commit_conflicts_total"].add()
                        trace.event(
                            "fleetplace.conflict_abort",
                            claim_uid=uid, attempt=attempt,
                            link=item["req"].get("lctx"),
                            nodes=",".join(sorted(
                                res.get("conflicts") or ())))
                        conflicted.append((item, res))
                    else:
                        self.stats["rollbacks_total"].add()
                        results[uid] = res
                if not conflicted:
                    break
                if attempt >= self.replan_max:
                    for item, res in conflicted:
                        results[item["uid"]] = res
                    break
                attempt += 1
                targets: Dict[str, int] = {}
                for item, res in conflicted:
                    self.stats["replans_total"].add()
                    for n, g in (res.get("placement_gens")
                                 or {}).items():
                        targets[n] = max(targets.get(n, 0), g)
                self._await_catchup(targets, ())
                pending = [item["req"] for item, _res in conflicted]
        out: List[dict] = []
        for req in reqs:
            res = results.get(req["uid"]) or {
                "uid": req["uid"], "placed": False,
                "reason": "unplanned"}
            ms = (time.monotonic() - req["t0"]) * 1e3
            res.setdefault("latency_ms", round(ms, 3))
            trace.observe("tdp_fleet_decision_ms", ms,
                          exemplar=res.get("trace_id"))
            out.append(res)
        return out

    def _plan_wave(self, pending: List[dict], wave_id: int,
                   attempt: int, results: Dict[str, dict]
                   ) -> List[dict]:
        """The wave's single sorted planning pass. Per selector group:
        one eligible_views snapshot, one _WaveIndex, claims planned
        largest-first (big meshes get first pick of contiguity) with
        in-wave free-capacity reservations. Observed generations are
        PRE-BUMPED per in-wave placement on the same node: the fabric
        applies the batch in order, bumping once per commit, so a later
        same-node claim's CAS observation anticipates the earlier one's
        commit instead of conflicting with its own wave."""
        from . import placement
        batch: List[dict] = []
        groups: Dict[str, List[dict]] = {}
        for req in pending:
            groups.setdefault(req["selector"], []).append(req)
        base_gens = None
        if self.cache is not None:
            base_gens = dict(
                self.cache.accountant.observed_generations())
        wave_bumps: Dict[str, int] = {}
        for selector in sorted(groups):
            views, _compiled = self.eligible_views(selector)
            index = _WaveIndex(views)
            for req in sorted(groups[selector], key=lambda r: (
                    -volume(r["shape"]), r["uid"])):
                uid = req["uid"]
                op = ("fleetplace.replan" if attempt
                      else "fleetplace.schedule")
                with trace.span(op, claim_uid=uid, wave=wave_id,
                                attempt=attempt,
                                link=req.get("lctx"),
                                shape="x".join(
                                    str(d) for d in req["shape"])):
                    if req.get("trace_id") is None:
                        ctx = trace.current_context()
                        req["trace_id"] = (ctx or {}).get("trace_id")
                        req["lctx"] = trace.propagate()
                    plan = index.plan(req["shape"],
                                      req["best_effort"],
                                      self.pod_dims)
                    self._note("decided", uid, {
                        "shape": list(req["shape"]),
                        "selector": req["selector"],
                        "shards": None if plan is None else
                        [[n, list(r)] for n, r in plan.shards]})
                    if plan is None:
                        self.stats["unplaceable_total"].add()
                        self._note("unplaceable", uid, None)
                        trace.event("fleetplace.unplaceable",
                                    claim_uid=uid)
                        results[uid] = {
                            "uid": uid, "placed": False,
                            "reason": "unplaceable",
                            "trace_id": req["trace_id"]}
                        continue
                    index.reserve(plan)
                    observed = None
                    if base_gens is not None:
                        observed = {}
                        for node, _raws in plan.shards:
                            observed[node] = (
                                base_gens.get(node, 0)
                                + wave_bumps.get(node, 0))
                        for node, _raws in plan.shards:
                            wave_bumps[node] = \
                                wave_bumps.get(node, 0) + 1
                    batch.append({"plan": plan, "uid": uid,
                                  "observed": observed,
                                  "traceparent": req.get("lctx"),
                                  "req": req})
        return batch

    def _execute_batch(self, batch: List[dict]) -> Dict[str, dict]:
        """The wave's single commit round: one executor.execute_wave
        call (one fabric crossing for every ready claim). Falls back
        to per-claim execute_plan for executors that predate waves;
        no executor at all means every plan is advisory."""
        if self.executor is None:
            out = {}
            for item in batch:
                uid, plan = item["uid"], item["plan"]
                self._note("advisory", uid, None)
                out[uid] = {"uid": uid, "placed": True,
                            "advisory": True,
                            "trace_id": item["req"].get("trace_id"),
                            "score": plan.score, "hosts": plan.hosts,
                            "shards": [(n, list(r))
                                       for n, r in plan.shards]}
            return out
        wave_exec = getattr(self.executor, "execute_wave", None)
        if wave_exec is not None:
            items = [{"plan": item["plan"], "uid": item["uid"],
                      "observed": item["observed"],
                      "traceparent": item["traceparent"]}
                     for item in batch]
            return wave_exec(items, observer=self._note)
        out = {}
        for item in batch:
            if item["observed"] is None \
                    or not self._observed_supported():
                res = self.executor.execute_plan(
                    item["plan"], item["uid"], observer=self._note)
            else:
                res = self.executor.execute_plan(
                    item["plan"], item["uid"], observer=self._note,
                    observed=item["observed"])
            out[item["uid"]] = res
        return out

    def release(self, uid: str) -> bool:
        """Release a committed decision's sub-claims node-by-node (the
        tenant went away). Each shard is released by its LEDGER
        identity (sub_uid, current node) — correct even after a defrag
        wave moved the claim to a different host. Logged; the ledger
        swap keeps readers lock-free."""
        shards = self._claims.get(uid)
        if shards is None:
            return False
        with trace.span("fleetplace.release", claim_uid=uid):
            if self.executor is not None:
                deltas = self.executor.release_subclaims(
                    [(sub_uid, node) for sub_uid, node, _raws in shards])
                # same contract as commit feedback: fold the release's
                # restamp deltas in now, so the freed chips are offered
                # before the watch round-trip delivers them
                if deltas and self.cache is not None:
                    self.cache.accountant.apply_placement(deltas)
            with self._claims_lock:
                fresh = dict(self._claims)
                fresh.pop(uid, None)
                self._claims = fresh
            self._note("released", uid, None)
            self.stats["releases_total"].add()
        return True

    # --------------------------------------- remediation seams (PR 16)

    def bias_away(self, node: str, reason: str = "") -> bool:
        """Steer NEW placements off `node`: its free chips stop being
        offered while its existing claims keep participating as
        occupancy. Idempotent; logged and counted. The remediation
        engine applies this when exemplar->node attribution keeps
        surfacing one host under a burning SLO, and clears it on
        recovery (clear_bias)."""
        with self._bias_lock:
            if node in self._avoid_nodes:
                return False
            self._avoid_nodes = self._avoid_nodes | {node}
        self.stats["bias_applied_total"].add()
        self._note("bias_applied", node, {"reason": reason})
        trace.event("fleetplace.bias_applied", node=node,
                    reason=reason)
        return True

    def clear_bias(self, node: str) -> bool:
        """Rollback of bias_away: the node offers capacity again."""
        with self._bias_lock:
            if node not in self._avoid_nodes:
                return False
            self._avoid_nodes = self._avoid_nodes - {node}
        self.stats["bias_cleared_total"].add()
        self._note("bias_cleared", node, None)
        trace.event("fleetplace.bias_cleared", node=node)
        return True

    def biased_nodes(self) -> Tuple[str, ...]:
        return tuple(sorted(self._avoid_nodes))

    def plan_drain(self, node: str,
                   generation: Optional[str] = None) -> dict:
        """Plan draining every scheduler-placed claim shard off `node`
        through the SAME handoff path a defrag wave uses: the returned
        proposal feeds apply_defrag_wave unchanged (unprepare → durable
        handoff record → re-point fabric claim → import at the
        destination, ledger re-pointed move-by-move).

        Destinations are chosen most-free-first within the node's own
        generation, capacity reserved move-by-move; a shard with no
        destination is advised with target_node None (apply skips it —
        a partial drain is honest, not silent)."""
        views_by_gen, _ = self.views_by_generation()
        if generation is None:
            for gen, views in views_by_gen.items():
                if any(v.node == node for v in views):
                    generation = gen
                    break
        views = views_by_gen.get(generation) or []
        source = next((v for v in views if v.node == node), None)
        migrations: List[dict] = []
        if source is not None:
            targets = sorted(
                (v for v in views
                 if v.node != node and v.node not in self._avoid_nodes),
                key=lambda v: (-len(v.free), v.node))
            reserved: Dict[str, set] = {}
            for uid in sorted(source.claims):
                raws = sorted(source.claims[uid])
                mig = {"claim": uid, "source_node": node,
                       "devices": raws,
                       "target_node": None, "target_devices": None}
                for tv in targets:
                    avail = sorted(tv.free - reserved.get(tv.node,
                                                          set()))
                    if len(avail) >= len(raws):
                        picked = avail[:len(raws)]
                        reserved.setdefault(tv.node,
                                            set()).update(picked)
                        mig["target_node"] = tv.node
                        mig["target_devices"] = picked
                        break
                migrations.append(mig)
        self.stats["drains_planned_total"].add()
        resolved = sum(1 for m in migrations
                       if m["target_node"] is not None)
        self._note("drain_planned", node, {
            "generation": generation, "moves": len(migrations),
            "resolved": resolved})
        return {"node": node, "generation": generation,
                "migrations": migrations,
                "moves": len(migrations), "resolved": resolved}

    # ------------------------------------------------- fragmentation

    def fragmentation(self) -> Dict[str, dict]:
        """Fleet-global fragmentation rollup (cluster curves), read
        lock-free inside the `fleetplace.frag` bracket."""
        with lockdep.read_path("fleetplace.frag"):
            views_by_gen, _ = self.views_by_generation()
            return cluster_fragmentation(views_by_gen,
                                         pod_dims=self.pod_dims)

    def plan_defrag_wave(self, shape, generation: Optional[str] = None,
                         selector: str = "") -> dict:
        """Plan one globally-coordinated defrag wave: the cluster-wide
        advisory (placement.propose_defrag over EVERY host's view, so
        migration targets resolve across the fleet) plus the rollup
        curves before the wave. Raises ValueError (typed, HTTP-400
        shaped) when the named generation has no host view."""
        from . import placement
        shape = placement.parse_shape(shape)
        views_by_gen, attrs_index = self.views_by_generation()
        if generation is None and len(views_by_gen) == 1:
            generation = next(iter(views_by_gen))
        views = views_by_gen.get(generation)
        if not views:
            raise ValueError(
                f"unknown generation {generation!r}; have "
                f"{sorted(views_by_gen)}")
        if selector:
            # filter WITHIN the named generation only: a node serving
            # several generations must not leak its other tori into
            # this advisory as free capacity
            views = self._filter_views(
                {generation: views}, attrs_index,
                self.selector(selector))[generation]
        proposal = placement.propose_defrag(shape, views)
        proposal["generation"] = generation
        proposal["cluster_fragmentation"] = cluster_fragmentation(
            {generation: views}, pod_dims=self.pod_dims)[generation]
        return proposal

    def apply_defrag_wave(self, proposal: dict) -> dict:
        """Apply a planned wave NODE-BY-NODE through the PR 7 handoff
        machinery: migrations grouped by source node, each group one
        executor.apply_defrag call (unprepare → durable handoff record
        → re-point fabric claim → import + validate at destination),
        every move logged and spanned. Returns the wave report."""
        if self.executor is None:
            raise RuntimeError("no executor attached")
        migrations = [m for m in proposal.get("migrations", ())
                      if m.get("target_node") is not None]
        by_source: Dict[str, List[dict]] = {}
        for mig in migrations:
            by_source.setdefault(mig["source_node"], []).append(mig)
        # counted at wave START so a retried wave after a mid-apply
        # failure gets a fresh id in the log
        self.stats["defrag_waves_total"].add()
        wave_id = f"wave-{self.stats['defrag_waves_total'].value}"
        moves = 0
        with trace.span("fleetplace.defrag.wave", wave=wave_id):
            self._note("defrag_wave", wave_id,
                       {"moves_planned": len(migrations)})
            for node in sorted(by_source):
                group = by_source[node]
                with trace.span("fleetplace.defrag.node", node=node,
                                moves=len(group)):
                    # one executor call PER migration: the ledger
                    # re-point and the log entry land immediately after
                    # each completed move, so a failure mid-group
                    # leaves every already-moved claim's ledger shard
                    # naming its REAL new home (a later release then
                    # unprepares the right node)
                    for mig in group:
                        feedback: List[dict] = []
                        if self._defrag_feedback_ok():
                            applied = self.executor.apply_defrag(
                                {"migrations": [mig]},
                                deltas_out=feedback)
                        else:
                            applied = self.executor.apply_defrag(
                                {"migrations": [mig]})
                        if feedback and self.cache is not None:
                            # move feedback = commit feedback: the
                            # freed source chips and the re-owned
                            # target chips land in the views now, not
                            # a watch round-trip later
                            self.cache.accountant.apply_placement(
                                feedback)
                        moves += applied
                        self._migrate_ledger(mig)
                        self._note("defrag_move", mig["claim"], {
                            "wave": wave_id, "source": node,
                            "target": mig["target_node"]})
                        self.stats["defrag_moves_total"].add()
        return {"wave": wave_id, "moves_planned": len(migrations),
                "moves_applied": moves}

    def _migrate_ledger(self, mig: dict) -> None:
        """Re-point a migrated claim's ledger shard at its new home.
        The advisory names the NODE-LEVEL claim id (the views' claims
        maps are sub-uid-keyed), so resolve it back to its ledger
        parent; the sub-uid itself is KEPT — the destination driver
        imported the handoff under that id, and a later release must
        unprepare by it. A migration of a claim the scheduler never
        placed (a direct/foreign tenant) is a no-op here — the drivers'
        own state is ground truth for those."""
        sub_uid = mig["claim"]
        # resolve AND rebuild under the ledger lock like every other
        # writer: a racing release() popping the parent between a
        # lock-free lookup and the swap would be resurrected by the
        # stale re-insert (permanently busy chips, failing releases)
        with self._claims_lock:
            parent = None
            for uid, shards in self._claims.items():
                if any(s == sub_uid for s, _n, _r in shards):
                    parent = uid
                    break
            if parent is None:
                return
            fresh_shards = tuple(
                (s, mig["target_node"],
                 tuple(mig.get("target_devices") or ()))
                if s == sub_uid else (s, node, raws)
                for s, node, raws in self._claims[parent])
            fresh = dict(self._claims)
            fresh[parent] = fresh_shards
            self._claims = fresh

    # ----------------------------------------------------- the audit

    def audit(self, fabric_audit: Optional[dict] = None) -> dict:
        """Exactly-once over THE commit log — one log spanning scheduler
        decision → per-node sub-claims → rollback/commit, cluster-wide:

          - every uid's first entry is its decision;
          - at most ONE commit per uid, and nothing after it;
          - every abort is clean: each sub-claim prepared since the
            latest decision was rolled back first.

        `fabric_audit` (FleetApiServer.multiclaim_audit()) cross-checks
        the fabric's view: the sets of committed uids must agree — a
        commit only one side knows is a lost or replayed claim."""
        entries = list(self._log)          # C-atomic copy
        by_uid: Dict[str, List[Tuple[str, object]]] = {}
        for kind, uid, detail in entries:
            if kind in ("defrag_wave", "bias_applied", "bias_cleared",
                        "drain_planned"):
                continue
            by_uid.setdefault(uid, []).append((kind, detail))
        duplicated: List[str] = []
        undecided: List[str] = []
        dirty_aborts: List[str] = []
        post_commit: List[str] = []
        committed: List[str] = []
        for uid, seq in sorted(by_uid.items()):
            kinds = [k for k, _d in seq]
            if kinds and kinds[0] not in ("decided", "defrag_move",
                                          "released"):
                undecided.append(uid)
            n_commit = kinds.count("committed")
            if n_commit > 1:
                duplicated.append(uid)
            if n_commit:
                committed.append(uid)
                # a committed claim may later be released or migrated
                # by a defrag wave; anything else after its commit is
                # a replayed decision
                after = kinds[kinds.index("committed") + 1:]
                if any(k not in ("released", "defrag_move")
                       for k in after):
                    post_commit.append(uid)
            prepared: set = set()
            for kind, detail in seq:
                if kind == "decided":
                    prepared = set()
                elif kind == "shard_prepared":
                    prepared.add(detail)
                elif kind == "shard_rolled_back":
                    prepared.discard(detail)
                elif kind == "aborted" and prepared:
                    dirty_aborts.append(uid)
                    break
        out = {
            "decisions_audited": len(by_uid),
            "committed": sorted(committed),
            "duplicated_commits": sorted(duplicated),
            "undecided_commits": sorted(undecided),
            "dirty_aborts": sorted(dirty_aborts),
            "entries_after_commit": sorted(post_commit),
            "exactly_once": not (duplicated or undecided or dirty_aborts
                                 or post_commit),
        }
        if fabric_audit is not None:
            fabric_committed = set(fabric_audit.get("committed") or ())
            ours = set(committed)
            out["fabric_agrees"] = (
                fabric_audit.get("exactly_once", False)
                and fabric_committed == ours)
            out["fabric_only"] = sorted(fabric_committed - ours)
            out["scheduler_only"] = sorted(ours - fabric_committed)
            out["exactly_once"] = (out["exactly_once"]
                                   and out["fabric_agrees"])
        return out

    def snapshot(self) -> dict:
        """Lock-free stats read: AtomicCounter sums + ledger/log sizes
        (GIL-atomic len reads)."""
        out = {key: counter.value for key, counter in self.stats.items()}
        out["biased_nodes"] = list(self.biased_nodes())
        out["claims"] = len(self._claims)
        out["log_entries"] = len(self._log)
        out["selectors_compiled"] = len(self._selectors)
        out["shard_index"] = self.shard_index
        out["shard_count"] = self.shard_count
        out["pending_claims"] = len(self._pending)
        if self.reflector is not None:
            out["reflector"] = self.reflector.snapshot()
        if self.cache is not None:
            out["cache_slices"] = len(self.cache.snapshot())
            out["cache_syncs"] = self.cache.syncs.value
            out["cache_events"] = self.cache.events.value
            # the accountant's counters flatten into the scheduler's
            # surface: one /status "fleet" section, one drift row
            out.update(self.cache.accountant.snapshot())
        return out


# ====================================================================
# the fleet-level audit (N schedulers, one fabric — ISSUE 17)
# ====================================================================


def fleet_audit(schedulers: Sequence[FleetScheduler],
                fabric_audit: Optional[dict] = None,
                placement_audit: Optional[dict] = None,
                checkpoint_audit: Optional[dict] = None) -> dict:
    """Exactly-once across ALL schedulers on one fabric: each
    scheduler's own log must audit clean, no claim uid may commit on
    more than one scheduler, and the UNION of scheduler commits must
    equal the fabric's committed set (per-scheduler fabric
    cross-checks would flag every peer's commit as foreign — the
    fleet-level set comparison is the honest one). The optional
    placement / checkpoint audits fold in the other two legs of the
    ISSUE 17 triple audit: multiclaim commit log, per-slice
    write-generation + placement log, node checkpoints."""
    per = [s.audit() for s in schedulers]
    committed_by: Dict[str, List[int]] = {}
    for i, audit in enumerate(per):
        for uid in audit["committed"]:
            committed_by.setdefault(uid, []).append(i)
    cross_dup = sorted(u for u, owners in committed_by.items()
                       if len(owners) > 1)
    ok = all(a["exactly_once"] for a in per) and not cross_dup
    out: Dict[str, object] = {
        "schedulers": len(per),
        "per_scheduler": per,
        "committed_total": len(committed_by),
        "cross_scheduler_duplicates": cross_dup,
    }
    if fabric_audit is not None:
        fabric_committed = set(fabric_audit.get("committed") or ())
        ours = set(committed_by)
        out["fabric_agrees"] = (
            fabric_audit.get("exactly_once", False)
            and fabric_committed == ours)
        out["fabric_only"] = sorted(fabric_committed - ours)
        out["scheduler_only"] = sorted(ours - fabric_committed)
        ok = ok and bool(out["fabric_agrees"])
    if placement_audit is not None:
        out["placement_exactly_once"] = bool(
            placement_audit.get("exactly_once", False))
        ok = ok and bool(out["placement_exactly_once"])
    if checkpoint_audit is not None:
        out["checkpoint_exactly_once"] = bool(
            checkpoint_audit.get("exactly_once", False))
        ok = ok and bool(out["checkpoint_exactly_once"])
    out["exactly_once"] = ok
    return out
