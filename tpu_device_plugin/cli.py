"""CLI entrypoint: flag parsing → Config → PluginManager until SIGTERM.

Analogue of cmd/main.go:33-35, with the small flag surface SURVEY.md §5
recommends (the reference has zero flags; every knob is a compile-time var).
Defaults match production paths; every flag exists so the same binary runs
against fixture trees.
"""

from __future__ import annotations

import argparse
import logging
import math
import os
import signal
import threading
from concurrent import futures
from dataclasses import replace

from .config import Config
from .lifecycle import PluginManager


def _parse_host_coords(text) -> "tuple[int, ...] | None":
    """'x,y[,z]' → pod-grid coordinate tuple. A malformed value fails
    LOUDLY (like the typo'd $TDP_BROKER contract): silently dropping it
    would leave this host invisible to cross-host mesh planning with no
    operator signal."""
    if text is None or str(text).strip() == "":
        return None
    try:
        coords = tuple(int(p) for p in str(text).split(","))
    except ValueError:
        raise SystemExit(
            f"--host-coords/$TDP_HOST_COORDS {text!r} is not 'x,y[,z]' "
            f"(comma-separated integers)") from None
    if not coords or any(c < 0 for c in coords):
        raise SystemExit(
            f"--host-coords/$TDP_HOST_COORDS {text!r} must be "
            f"non-negative integers")
    return coords


def build_config(argv=None) -> "tuple[Config, argparse.Namespace]":
    parser = argparse.ArgumentParser(
        prog="tpu-device-plugin",
        description="KubeVirt device plugin advertising Google Cloud TPUs "
                    "for VFIO passthrough into VMs.",
    )
    cfg = Config()
    parser.add_argument("--root", default=None,
                        help="re-root every sysfs/devfs/kubelet path under "
                             "this directory (fixture/testing mode)")
    parser.add_argument("--pci-base-path", default=cfg.pci_base_path)
    parser.add_argument("--mdev-base-path", default=cfg.mdev_base_path)
    parser.add_argument("--accel-class-path", default=cfg.accel_class_path)
    parser.add_argument("--pci-ids-path", default=cfg.pci_ids_path)
    # default=None sentinel: "explicitly passed" must be detectable so an
    # explicit value (even one equal to the default) survives --root
    parser.add_argument("--device-plugin-path", default=None,
                        help=f"kubelet device-plugin socket dir (default: "
                             f"{cfg.device_plugin_path})")
    parser.add_argument("--resource-namespace", default=cfg.resource_namespace)
    parser.add_argument("--vfio-drivers", default=",".join(cfg.vfio_drivers),
                        help="comma-separated driver names accepted as VFIO "
                             "bindings (the reference accepts a second "
                             "variant driver the same way, "
                             "device_plugin.go:75-78)")
    parser.add_argument("--generation-map", default=None,
                        help="JSON overriding the device-id → generation table")
    parser.add_argument("--topology-file", default=None,
                        help="JSON mapping BDF → ICI torus coordinates")
    parser.add_argument("--host-coords",
                        default=os.environ.get("TDP_HOST_COORDS"),
                        help="this host's slot on the pod-level host "
                             "grid, 'x,y[,z]' — published as hostX/hostY"
                             "[/hostZ] ResourceSlice attributes for the "
                             "fleet placement control plane "
                             "($TDP_HOST_COORDS)")
    parser.add_argument("--partition-config", default=None,
                        help="JSON declaring logical vTPU partitions")
    parser.add_argument("--max-partitions-per-chip", type=int,
                        default=cfg.max_partitions_per_chip,
                        help="cap advertised accel-backed logical partitions "
                             "per parent chip (0 = no extra cap); bounds the "
                             "blast radius of unisolated chip sharing (see "
                             "docs/design.md, vTPU trust boundary)")
    parser.add_argument("--partition-node-permissions",
                        choices=("r", "rw"),
                        default=cfg.partition_node_permissions,
                        help="device-node permissions VMIs get for "
                             "accel-backed logical partitions")
    parser.add_argument("--native-lib", default=None,
                        help="path to libtpuhealth.so")
    # default=None sentinel so the env var ($TDP_BROKER) can supply the
    # mode when the flag is absent, with the SAME validation either way
    parser.add_argument("--broker", choices=("inproc", "spawn"),
                        default=None,
                        help="privilege separation mode (broker.py): "
                             "'inproc' runs privileged operations in this "
                             "process through the audited seam; 'spawn' "
                             "starts a separate privileged broker process "
                             "and crosses a versioned IPC per operation "
                             f"(default {cfg.broker_mode}; env TDP_BROKER)")
    parser.add_argument("--broker-socket", default=None,
                        help="unix socket for the broker IPC (default: "
                             f"{cfg.broker_socket_path}; re-rooted under "
                             "--root). With --broker spawn and an EXISTING "
                             "broker on this socket, the daemon connects "
                             "and handshakes instead of spawning — the "
                             "serving-daemon-restart path")
    parser.add_argument("--broker-handshake-timeout", type=float,
                        default=10.0,
                        help="seconds to wait for the spawned broker to "
                             "bind its socket and answer the version "
                             "handshake before aborting startup")
    parser.add_argument("--broker-protocol", choices=("auto", "1", "2"),
                        default=None,
                        help="broker IPC framing to OFFER at the hello "
                             "handshake: 2 negotiates the compact binary "
                             "frames + response ring (round 20), 1 forces "
                             "JSON framing (rollback / mixed-version "
                             "debugging), auto offers the newest (default "
                             "auto; env TDP_BROKER_PROTOCOL)")
    parser.add_argument("--policy-dir", default=None,
                        help="directory of sandboxed operator policy "
                             "modules (*.py; policy.py hooks: "
                             "score_allocation, health_verdict, admit). "
                             "A module that fails to load aborts startup")
    parser.add_argument("--policy-hook-deadline-ms", type=float,
                        default=cfg.policy_hook_deadline_ms,
                        help="wall-clock budget per policy hook call; "
                             "later results are discarded (builtin "
                             "behavior) and charged to the hook's "
                             "circuit breaker")
    parser.add_argument("--cdi-spec-dir", default=None,
                        help="write CDI specs here (e.g. /var/run/cdi) and "
                             "return CDIDevice names from Allocate")
    parser.add_argument("--health-poll-seconds", type=float,
                        default=cfg.health_poll_s)
    parser.add_argument("--health-probe-workers", type=int,
                        default=cfg.health_probe_workers,
                        help="worker pool size for the shared health hub's "
                             "deduped per-chip liveness probes")
    parser.add_argument("--health-probe-deadline-seconds", type=float,
                        default=cfg.health_probe_deadline_s,
                        help="wall-clock budget for one probe cycle; a "
                             "probe that has not answered by then is "
                             "scored dead (counted on /metrics) instead "
                             "of delaying every other chip's verdict")
    # default=None sentinel so the env var ($TDP_PREPARE_WORKERS) can supply
    # the value when the flag is absent, with the SAME validation either way
    parser.add_argument("--prepare-workers", type=int, default=None,
                        help="worker pool size for fanning out a multi-claim "
                             "DRA NodePrepareResources/NodeUnprepareResources "
                             "(same-UID kubelet retries still serialize on a "
                             f"per-claim lock; default {cfg.prepare_workers}; "
                             "env TDP_PREPARE_WORKERS)")
    parser.add_argument("--rediscovery-seconds", type=float,
                        default=cfg.rediscovery_interval_s,
                        help="0 disables periodic re-discovery")
    # default=None sentinel so the env var ($TDP_LW_DEBOUNCE_MS) can supply
    # the value when the flag is absent, with the SAME validation either way
    parser.add_argument("--lw-debounce-ms", type=float, default=None,
                        help="coalesce ListAndWatch health re-sends within "
                             "this window (ms; 0 = send per flip; default "
                             f"{cfg.lw_debounce_s * 1000:g}; env "
                             "TDP_LW_DEBOUNCE_MS)")
    parser.add_argument("--full-rescan", action="store_true",
                        help="disable dirty-set incremental rediscovery: "
                             "every rediscovery tick walks all of sysfs "
                             "(env TDP_FULL_RESCAN=1)")
    parser.add_argument("--shared-scan-ttl", type=float,
                        default=cfg.shared_scan_ttl_s,
                        help="cache the shared-device (EGM-analogue) sysfs "
                             "scan for this many seconds inside Allocate "
                             "(0 = rescan every RPC, reference behavior)")
    parser.add_argument("--publish-pace-max", type=float,
                        default=cfg.publish_pace_max_s,
                        help="ceiling (seconds) for the adaptive "
                             "ResourceSlice publish admission window "
                             "(kubeapi.PublishPacer): 429/slow-RTT feedback "
                             "grows the jittered window up to this; 0 "
                             "disables pacing entirely")
    parser.add_argument("--publish-pace-base", type=float,
                        default=cfg.publish_pace_base_s,
                        help="resting admission window (seconds) for "
                             "ResourceSlice publishes; the default 0 adds "
                             "no latency until the apiserver pushes back")
    parser.add_argument("--diagnostics-ttl", type=float,
                        default=cfg.diagnostics_ttl_s,
                        help="cache the per-device PCI diagnostics reads "
                             "on /status for this many seconds (0 = read "
                             "live every scrape; at 4096 devices a scrape "
                             "costs 2 sysfs reads per device uncached)")
    parser.add_argument("--label-node", action="store_true",
                        help="publish per-node TPU facts (generation, chip "
                             "count, torus dims) as node labels via the API "
                             "server (needs NODE_NAME + patch-nodes RBAC)")
    parser.add_argument("--node-name", default=None,
                        help="this node's name (default: $NODE_NAME)")
    parser.add_argument("--api-server", default=None,
                        help="API server URL override (default: in-cluster)")
    parser.add_argument("--feature-file", default=None,
                        help="also/instead write facts as an NFD local "
                             "feature file (key=value lines)")
    parser.add_argument("--dra", action="store_true",
                        help="ALSO serve the DRA (Dynamic Resource "
                             "Allocation) driver: publish this node's chips "
                             "and partitions as a ResourceSlice and answer "
                             "NodePrepareResources with per-claim CDI specs. "
                             "Runs alongside the device-plugin API so a "
                             "cluster can migrate gradually (needs "
                             "resourceslices + resourceclaims RBAC)")
    parser.add_argument("--dra-plugins-path", default=None,
                        help=f"kubelet plugins dir for the DRA service "
                             f"socket (default: {cfg.dra_plugins_path})")
    parser.add_argument("--dra-registry-path", default=None,
                        help=f"kubelet plugin-registration watch dir "
                             f"(default: {cfg.dra_registry_path})")
    parser.add_argument("--no-slice-watch", action="store_true",
                        help="disable the watch-driven slice reconciler "
                             "(kubeapi.Reflector) and keep the pre-watch "
                             "read/repair behavior; with the watch on, a "
                             "slice wiped or mutated behind the driver is "
                             "observed as an event and repaired through "
                             "the guarded-write path, and an apiserver "
                             "without watch support degrades to paced "
                             "relist polling automatically")
    parser.add_argument("--slice-watch-resync", type=float, default=300.0,
                        help="watch reconciler resync interval in seconds "
                             "(the periodic relist that backstops missed "
                             "events; default 300)")
    parser.add_argument("--status-port", type=int, default=0,
                        help="serve /healthz and /status on this port "
                             "(0 disables)")
    parser.add_argument("--status-host", default="0.0.0.0",
                        help="bind address for the status endpoint (the "
                             "default serves kubelet httpGet probes on the "
                             "pod IP)")
    parser.add_argument("--slo-config", default=None,
                        help="JSON file of SLO objectives overriding the "
                             "shipped defaults (slo.py; env $TDP_SLO_CONFIG;"
                             " docs/observability.md 'SLO objective "
                             "config') — malformed config fails boot "
                             "loudly, it never silently monitors nothing")
    parser.add_argument("--no-remediation", action="store_true",
                        help="disable the SLO-closed-loop remediation "
                             "engine (remediation.py): with it on (the "
                             "default), a latched SLO breach backs the "
                             "publish pacer off and sheds admission above "
                             "a token rate — every action policy-gated "
                             "(remediate hook), audited, trace-linked, "
                             "and rolled back on recovery")
    parser.add_argument("--discover-only", action="store_true",
                        help="run discovery once, print the inventory as "
                             "JSON, and exit (ops/debug; no kubelet contact)")
    parser.add_argument("--log-json", action="store_true",
                        help="emit one JSON object per log line (fleet log "
                             "pipelines; env TDP_LOG_JSON=1)")
    parser.add_argument("-v", "--verbose", action="count", default=0)
    args = parser.parse_args(argv)
    if args.max_partitions_per_chip < 0:
        parser.error("--max-partitions-per-chip must be >= 0 "
                     "(0 = no extra cap); negative values would silently "
                     "disable the cap")
    if not args.full_rescan:
        env_full = os.environ.get("TDP_FULL_RESCAN")
        if env_full is not None:
            val = env_full.strip().lower()
            if val in ("1", "true", "yes", "on"):
                args.full_rescan = True
            elif val not in ("", "0", "false", "no", "off"):
                # fail loudly like the other env knobs: a typo'd truthy
                # value silently keeping incremental mode is the worst case
                parser.error(f"$TDP_FULL_RESCAN={env_full!r} is not a "
                             "boolean (use 1/0, true/false, yes/no, on/off)")
    if args.lw_debounce_ms is None:
        env_debounce = os.environ.get("TDP_LW_DEBOUNCE_MS")
        if env_debounce is not None:
            try:
                args.lw_debounce_ms = float(env_debounce)
            except ValueError:
                parser.error(f"$TDP_LW_DEBOUNCE_MS={env_debounce!r} is not "
                             "a number")
        else:
            args.lw_debounce_ms = cfg.lw_debounce_s * 1000.0
    # reject bad windows HERE, not deep in a plugin thread mid-flap: a NaN
    # window would make every condvar timeout comparison silently false
    if math.isnan(args.lw_debounce_ms) or math.isinf(args.lw_debounce_ms) \
            or args.lw_debounce_ms < 0:
        parser.error("--lw-debounce-ms must be a finite number >= 0, got "
                     f"{args.lw_debounce_ms!r}")
    if args.prepare_workers is None:
        env_workers = os.environ.get("TDP_PREPARE_WORKERS")
        if env_workers is not None:
            try:
                args.prepare_workers = int(env_workers)
            except ValueError:
                parser.error(f"$TDP_PREPARE_WORKERS={env_workers!r} is not "
                             "an integer")
        else:
            args.prepare_workers = cfg.prepare_workers
    if args.prepare_workers < 1:
        parser.error("--prepare-workers must be >= 1, got "
                     f"{args.prepare_workers}")
    # same fail-loud rule for the health-hub knobs: a 0-worker pool can run
    # no probe at all and a non-finite deadline silently disables timeouts
    if args.health_probe_workers < 1:
        parser.error("--health-probe-workers must be >= 1, got "
                     f"{args.health_probe_workers}")
    if math.isnan(args.health_probe_deadline_seconds) \
            or math.isinf(args.health_probe_deadline_seconds) \
            or args.health_probe_deadline_seconds <= 0:
        parser.error("--health-probe-deadline-seconds must be a finite "
                     f"number > 0, got {args.health_probe_deadline_seconds!r}")
    # fail-loud pacing/diagnostics knobs: a NaN window defeats every
    # monotonic-deadline comparison silently, a negative one is nonsense
    for name, value in (("--publish-pace-base", args.publish_pace_base),
                        ("--publish-pace-max", args.publish_pace_max),
                        ("--diagnostics-ttl", args.diagnostics_ttl)):
        if math.isnan(value) or math.isinf(value) or value < 0:
            parser.error(f"{name} must be a finite number >= 0, "
                         f"got {value!r}")
    if args.broker is None:
        env_broker = os.environ.get("TDP_BROKER")
        if env_broker is not None and env_broker.strip():
            mode = env_broker.strip().lower()
            if mode not in ("inproc", "spawn"):
                # fail loudly like the other env knobs: a typo'd mode
                # silently keeping in-process privileges is the worst case
                parser.error(f"$TDP_BROKER={env_broker!r} is not a broker "
                             "mode (use inproc or spawn)")
            args.broker = mode
        else:
            args.broker = cfg.broker_mode
    if args.broker_protocol is None:
        env_proto = os.environ.get("TDP_BROKER_PROTOCOL")
        if env_proto is not None and env_proto.strip():
            proto = env_proto.strip().lower()
            if proto not in ("auto", "1", "2"):
                # same fail-loud contract as $TDP_BROKER: a typo'd
                # protocol silently negotiating the wrong framing is
                # exactly the confusion the flag exists to remove
                parser.error(f"$TDP_BROKER_PROTOCOL={env_proto!r} is not "
                             "a broker protocol (use auto, 1 or 2)")
            args.broker_protocol = proto
        else:
            args.broker_protocol = "auto"
    if math.isnan(args.policy_hook_deadline_ms) \
            or math.isinf(args.policy_hook_deadline_ms) \
            or args.policy_hook_deadline_ms <= 0:
        parser.error("--policy-hook-deadline-ms must be a finite number "
                     f"> 0, got {args.policy_hook_deadline_ms!r}")
    if args.broker_handshake_timeout <= 0 \
            or math.isnan(args.broker_handshake_timeout) \
            or math.isinf(args.broker_handshake_timeout):
        parser.error("--broker-handshake-timeout must be a finite number "
                     f"> 0, got {args.broker_handshake_timeout!r}")
    if args.publish_pace_base > args.publish_pace_max:
        # base > max is silently inconsistent: decay clamps the window
        # to base while adaptation clamps to max — reject it loudly
        # (this also keeps "--publish-pace-max 0 disables pacing" true:
        # it forces base 0 too)
        parser.error(f"--publish-pace-base ({args.publish_pace_base}) "
                     f"must be <= --publish-pace-max "
                     f"({args.publish_pace_max})")

    level = logging.DEBUG if args.verbose else logging.INFO
    # Structured logging (log.py): key=value records by default, JSON
    # under --log-json / $TDP_LOG_JSON=1 — either way each line carries
    # the active trace span's context (claim_uid/bdf/resource), so log
    # lines and /debug/flight traces correlate by construction.
    json_mode = args.log_json or os.environ.get(
        "TDP_LOG_JSON", "").strip().lower() in ("1", "true", "yes", "on")
    from .log import configure as configure_logging
    configure_logging(level=level, json_mode=json_mode)
    dpp = (args.device_plugin_path if args.device_plugin_path is not None
           else cfg.device_plugin_path)
    cfg = replace(
        cfg,
        pci_base_path=args.pci_base_path,
        mdev_base_path=args.mdev_base_path,
        accel_class_path=args.accel_class_path,
        pci_ids_path=args.pci_ids_path,
        device_plugin_path=dpp,
        kubelet_socket=dpp.rstrip("/") + "/kubelet.sock",
        resource_namespace=args.resource_namespace,
        vfio_drivers=tuple(
            d.strip() for d in args.vfio_drivers.split(",") if d.strip()),
        generation_map_path=args.generation_map,
        topology_hints_path=args.topology_file,
        host_coords=_parse_host_coords(args.host_coords),
        partition_config_path=args.partition_config,
        max_partitions_per_chip=args.max_partitions_per_chip,
        partition_node_permissions=args.partition_node_permissions,
        native_lib_path=args.native_lib,
        cdi_spec_dir=args.cdi_spec_dir,
        health_poll_s=args.health_poll_seconds,
        health_probe_workers=args.health_probe_workers,
        health_probe_deadline_s=args.health_probe_deadline_seconds,
        prepare_workers=args.prepare_workers,
        rediscovery_interval_s=args.rediscovery_seconds,
        shared_scan_ttl_s=args.shared_scan_ttl,
        lw_debounce_s=args.lw_debounce_ms / 1000.0,
        incremental_rediscovery=not args.full_rescan,
        publish_pace_base_s=args.publish_pace_base,
        publish_pace_max_s=args.publish_pace_max,
        diagnostics_ttl_s=args.diagnostics_ttl,
        broker_mode=args.broker,
        policy_dir=args.policy_dir,
        policy_hook_deadline_ms=args.policy_hook_deadline_ms,
    )
    if args.root:
        cfg = cfg.with_root(args.root)
        if args.device_plugin_path is not None:
            # An explicit --device-plugin-path wins over --root's re-rooting:
            # the kind e2e runs fixture sysfs/devfs under --root while
            # registering with the REAL kubelet socket dir.
            cfg = replace(
                cfg,
                device_plugin_path=args.device_plugin_path,
                kubelet_socket=(args.device_plugin_path.rstrip("/")
                                + "/kubelet.sock"),
            )
    # explicit DRA paths win over --root's re-rooting, same rule as above
    if args.dra_plugins_path is not None:
        cfg = replace(cfg, dra_plugins_path=args.dra_plugins_path)
    if args.dra_registry_path is not None:
        cfg = replace(cfg, dra_registry_path=args.dra_registry_path)
    # explicit broker socket wins over --root's re-rooting, same rule
    if args.broker_socket is not None:
        cfg = replace(cfg, broker_socket_path=args.broker_socket)
    return cfg, args


def dump_inventory(cfg) -> str:
    """One-shot discovery → JSON (the --discover-only surface)."""
    import dataclasses
    import json

    from .discovery import discover
    from .labeler import node_facts

    registry, generations = discover(cfg)
    # discover() already warned per unmatched id; surface them in the JSON
    # so scripted invocations (CI smoke, fleet audits) can assert on it.
    return json.dumps({
        "unmatched_device_ids": sorted(m for m in registry.devices_by_model
                                       if m not in generations),
        "devices": {
            model: [dataclasses.asdict(d) for d in devs]
            for model, devs in registry.devices_by_model.items()
        },
        "partitions": {
            t: [dataclasses.asdict(p) for p in ps]
            for t, ps in registry.partitions_by_type.items()
        },
        "iommu_groups": {g: [d.bdf for d in ds]
                         for g, ds in registry.iommu_map.items()},
        "node_facts": node_facts(cfg, registry, generations),
    }, indent=2, sort_keys=True)


def main(argv=None) -> int:
    cfg, args = build_config(argv)
    # chaos/soak runs arm named fault points from $TDP_FAULTS (see
    # faults.py for the grammar and docs/fault-injection.md for the sites);
    # unset, this is one getenv and every fault point stays a no-op
    from . import faults
    if faults.configure_from_env():
        logging.getLogger(__name__).warning(
            "FAULT INJECTION ARMED from $TDP_FAULTS: %s",
            sorted(faults.armed_sites()))
    # Flight recorder (trace.py): always-on span rings; an unhandled
    # exception in any thread dumps them to a JSON file for post-incident
    # analysis ($TDP_TRACE_DUMP_PATH overrides the location)
    from . import trace
    trace.install_crash_hook()
    # SLO plane (slo.py): the process-global engine gets the operator's
    # objectives (--slo-config / $TDP_SLO_CONFIG; defaults otherwise)
    # and registers its burn-rate state as the "slo" section of every
    # crash/SIGHUP flight dump. SLOConfigError propagates — a malformed
    # objective must fail boot, not silently monitor nothing.
    from . import slo
    slo_spec = args.slo_config or os.environ.get("TDP_SLO_CONFIG")
    if slo_spec:
        slo.set_engine(slo.SLOEngine(slo.load_objectives(slo_spec)))
    slo.get_engine().attach_to_dumps()
    if args.discover_only:
        print(dump_inventory(cfg))
        return 0
    # Privilege separation (broker.py): in spawn mode the global broker
    # seam is pointed at a separate privileged process BEFORE anything
    # builds planners or health shims. An existing broker on the socket
    # (serving-daemon restart — the broker survived us) is connected to
    # and version-handshaked; otherwise one is spawned. In-process mode
    # leaves the lazy audited in-process seam in place.
    from . import broker as broker_mod
    broker_proc = None
    # Privilege separation (broker.py): in spawn mode the global broker
    # seam is pointed at a separate privileged process. An existing
    # broker on the socket (serving-daemon restart -- the broker survived
    # us) is connected to and version-handshaked; otherwise one is
    # spawned. In-process mode installs the audited in-process seam
    # explicitly so the configured native lib reaches probes routed
    # through it (the lazy default client has no cfg to read).
    #
    # Parallel boot pipeline: the spawn fork/exec + socket dial + version
    # handshake is pure wall time that neither policy-module loading nor
    # the DRA driver's checkpoint restore depends on -- it runs on a boot
    # worker thread, overlapped with both, and is joined at the barrier
    # below before the first consumer that crosses the seam (the
    # PluginManager ctor builds its health shim through it; discovery
    # crosses it in spawn mode).
    broker_boot: dict = {}

    def _boot_broker() -> None:
        try:
            if cfg.broker_mode == "spawn":
                logger = logging.getLogger(__name__)
                from . import brokeripc
                offer = (brokeripc.PROTOCOL_VERSION
                         if args.broker_protocol == "auto"
                         else int(args.broker_protocol))
                try:
                    client = broker_mod.SocketBrokerClient(
                        cfg.broker_socket_path,
                        connect_timeout_s=args.broker_handshake_timeout,
                        protocol_version=offer)
                    logger.info("connected to existing broker on %s (daemon "
                                "restart path; protocol v%d)",
                                cfg.broker_socket_path,
                                client.negotiated_version)
                except broker_mod.BrokerUnavailable:
                    if broker_mod.socket_live(cfg.broker_socket_path):
                        # something IS listening but would not complete the
                        # handshake (a wedged broker): spawning a duplicate
                        # would unlink the live broker's socket and orphan
                        # its held device fds -- refuse startup loudly and
                        # let the operator deal with the stuck process
                        raise
                    broker_boot["proc"] = broker_mod.spawn_broker(
                        cfg.broker_socket_path, root=cfg.root_path,
                        native_lib_path=cfg.native_lib_path,
                        timeout_s=args.broker_handshake_timeout)
                    client = broker_mod.SocketBrokerClient(
                        cfg.broker_socket_path,
                        connect_timeout_s=args.broker_handshake_timeout,
                        protocol_version=offer)
                    logger.info("spawned privileged broker pid=%d on %s "
                                "(protocol v%d)", broker_boot["proc"].pid,
                                cfg.broker_socket_path,
                                client.negotiated_version)
                broker_mod.set_client(client)
            else:
                broker_mod.set_client(
                    broker_mod.InProcessBroker(cfg.native_lib_path))
        except BaseException as exc:   # published; re-raised at the barrier
            broker_boot["error"] = exc

    boot_pool = futures.ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="boot-broker")
    boot_pool.submit(_boot_broker)
    try:
        # --- overlapped with the broker handshake: nothing in this
        # stretch crosses the privilege seam ---
        # Operator policy hooks (policy.py): fail-loud loading -- a broken
        # policy module must refuse startup, not silently run without it
        policy_engine = None
        if cfg.policy_dir:
            from .policy import PolicyEngine
            policy_engine = PolicyEngine(
                hook_deadline_ms=cfg.policy_hook_deadline_ms)
            n_modules = policy_engine.load_dir(cfg.policy_dir)
            logging.getLogger(__name__).info(
                "policy engine: %d module(s) loaded from %s",
                n_modules, cfg.policy_dir)
        stop = threading.Event()

        def handle(signum, frame):
            logging.getLogger(__name__).info("signal %d; shutting down", signum)
            stop.set()

        signal.signal(signal.SIGTERM, handle)
        signal.signal(signal.SIGINT, handle)
        inventory_sinks = []
        if args.label_node or args.feature_file:
            from .labeler import NodeLabeler, node_facts
            labeler = NodeLabeler(node_name=args.node_name,
                                  api_server=args.api_server,
                                  feature_file=args.feature_file,
                                  require_api=args.label_node,
                                  label_prefix=cfg.resource_namespace)
            inventory_sinks.append(lambda reg, gens: labeler.publish(
                node_facts(cfg, reg, gens)))
        # SLO-closed-loop remediation (remediation.py): subscribes to the
        # engine above; breach → pacer backoff + typed admission shed,
        # recovery → rollback. Every action runs the policy remediate gate.
        # Off with --no-remediation; without a DRA driver the pacer knob is
        # simply absent and only the admission throttle can arm.
        remediation_engine = None
        if not args.no_remediation:
            from .remediation import RemediationEngine
            remediation_engine = RemediationEngine(policy=policy_engine)
            slo.get_engine().subscribe(remediation_engine.on_transition)
        dra_driver = None
        health_listener = None
        if args.dra:
            from .dra import DraDriver
            from .kubeapi import ApiClient, in_cluster_server
            from .registry import Registry
            server_url = args.api_server or in_cluster_server()
            api = ApiClient(server_url) if server_url else None
            dra_driver = DraDriver(cfg, Registry(), {}, node_name=args.node_name,
                                   api=api, policy=policy_engine,
                                   remediation=remediation_engine)
            if remediation_engine is not None:
                # the knob the self-heal plane turns on a burning publish/
                # attach SLO — wired here because the pacer is born with the
                # driver, after the engine
                remediation_engine.pacer = dra_driver.pacer

            def dra_sink(reg, gens, _d=dra_driver):
                _d.set_inventory(reg, gens)
                ok = _d.publish_resource_slices()
                # sockets come up only AFTER the first discovery has filled the
                # inventory: the kubelet may call NodePrepareResources the
                # moment the registration socket appears, and an empty
                # inventory would fail claims that are perfectly preparable
                if not _d.serving:
                    _d.start()
                return ok
            inventory_sinks.append(dra_sink)
            # the plugin servers' ANDed health verdict prunes dead devices from
            # the published ResourceSlice on the same transition that flips
            # them Unhealthy on ListAndWatch (no second health watcher)
            health_listener = dra_driver.apply_health
        on_inventory = None
        if inventory_sinks:
            def on_inventory(reg, gens):
                ok = True
                for sink in inventory_sinks:
                    ok = sink(reg, gens) and ok
                return ok
        # barrier: everything past here may cross the privilege seam
        boot_pool.shutdown(wait=True)
        if "error" in broker_boot:
            raise broker_boot["error"]
        broker_proc = broker_boot.get("proc")
    except Exception:
        # a boot failure BEFORE the barrier resolves (handshake timeout,
        # broken policy module, checkpoint restore error) must not
        # orphan a root-privileged child the worker thread spawned
        boot_pool.shutdown(wait=True)
        proc = broker_boot.get("proc")
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()
        raise
    manager = PluginManager(cfg, on_inventory=on_inventory,
                            health_listener=health_listener,
                            policy_engine=policy_engine,
                            remediation_engine=remediation_engine)
    if dra_driver is not None:
        # the DRA driver rides the manager's shared health plane for its
        # registration-socket watch (kubelet-restart recovery) — same hub,
        # same single inotify fd as the plugin servers
        dra_driver.attach_health_hub(manager.health_hub)
        # lifecycle FSM wiring (lifecycle_fsm.py): prepares mark devices
        # allocated; a hot-unplugged device with prepared claims orphans
        # them in the checkpoint and leaves the published ResourceSlice
        dra_driver.attach_lifecycle(manager.device_lifecycle)
        # watch-driven slice convergence (ISSUE 12): the reflector
        # replaces the read/repair churn; degradation to paced relist
        # polling is the reflector's own ladder, never a hang
        if not args.no_slice_watch and dra_driver.api is not None:
            dra_driver.start_watch_reconciler(
                resync_interval_s=args.slice_watch_resync)

    def handle_drain(signum, frame):
        # flag-set only: drain() takes locks the interrupted main thread
        # may hold; the manager run loop applies the request next tick
        manager.request_drain(signum == signal.SIGUSR1)

    def handle_dump(signum, frame):
        # flag-set only, like drain: trace.dump() logs + writes a file (a
        # reentrant-stream hazard if the signal lands mid-write); the run
        # loop dumps within ~1s. A DEDICATED signal — overloading the
        # undrain signal would silently undrain a maintenance-drained
        # node exactly when an operator asks for a post-incident dump.
        manager.request_flight_dump()

    # SIGUSR1 = drain (all devices administratively Unhealthy; kubelet stops
    # placing new VMIs), SIGUSR2 = undrain, SIGHUP = flight-recorder dump
    # (the on-demand post-incident artifact; harmless if delivered
    # spuriously by a closing terminal)
    signal.signal(signal.SIGUSR1, handle_drain)
    signal.signal(signal.SIGUSR2, handle_drain)
    signal.signal(signal.SIGHUP, handle_dump)
    status = None
    if args.status_port:
        from .status import StatusServer
        status = StatusServer(manager, args.status_port, host=args.status_host,
                              dra_driver=dra_driver)
        status.start()
    if remediation_engine is not None:
        # background tick: queued SLO transitions become knob turns off
        # the scrape thread (the subscriber callback only queues)
        remediation_engine.start()
    try:
        manager.run(stop)
    finally:
        if remediation_engine is not None:
            remediation_engine.stop()
        if dra_driver is not None:
            dra_driver.stop()
        if status is not None:
            status.stop()
        if broker_proc is not None:
            # WE spawned this broker: reap it on a clean daemon shutdown
            # (a broker we merely connected to belongs to whoever started
            # it and outlives us — the privilege-separation design)
            broker_proc.terminate()
            try:
                broker_proc.wait(timeout=5)
            except Exception:
                broker_proc.kill()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
