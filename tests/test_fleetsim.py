"""Fleet-scale simulation harness tests (ISSUE 9).

Small-N deterministic versions of the storms bench.py --fleet runs at
{16,64,256}: coordinated boot, mass attach, health-flip coalescing,
rolling drain/upgrade — each asserting the counted fleet contracts
(exactly-once slice generations, zero lost claims, convergence) rather
than wall-clock. The 64-node chaos soak is @pytest.mark.slow and gated
on TDP_CHAOS_SOAK=1 (`make fleet-soak`, lockdep-enabled).
"""

import os
import time
import threading

import pytest

from tpu_device_plugin import faults
from tpu_device_plugin.fleetsim import FleetApiServer, FleetSim
from tpu_device_plugin.kubeapi import ApiClient, ApiError, PublishPacer


@pytest.fixture()
def fleet():
    sims = []

    def build(**kw):
        kw.setdefault("n_nodes", 4)
        kw.setdefault("devices_per_node", 4)
        kw.setdefault("latency_s", 0.002)
        kw.setdefault("seed", 3)
        sim = FleetSim(**kw)
        sims.append(sim)
        return sim

    yield build
    for sim in sims:
        sim.stop()


# ------------------------------------------------------------ fabric


def test_fabric_serves_the_dra_surface_and_audits_writes():
    srv = FleetApiServer()
    try:
        client = ApiClient(srv.url, token_path="/nonexistent")
        group = client.get_json("/apis/resource.k8s.io")
        assert group["versions"][0]["version"] == "v1beta1"
        node = client.get_json("/api/v1/nodes/n1")
        assert node["metadata"]["uid"] == "uid-n1"
        obj = {"metadata": {"name": "s1"},
               "spec": {"pool": {"generation": 1}, "devices": []}}
        created = client.post_json(
            "/apis/resource.k8s.io/v1beta1/resourceslices", obj)
        # duplicate create = 409, like a real apiserver (exactly-once)
        with pytest.raises(ApiError) as exc:
            client.post_json(
                "/apis/resource.k8s.io/v1beta1/resourceslices", obj)
        assert exc.value.code == 409
        # guarded PUT honors resourceVersion
        created["spec"]["pool"]["generation"] = 2
        client.put_json(
            "/apis/resource.k8s.io/v1beta1/resourceslices/s1", created)
        stale = dict(created, metadata={"name": "s1",
                                        "resourceVersion": "0"})
        with pytest.raises(ApiError) as exc:
            client.put_json(
                "/apis/resource.k8s.io/v1beta1/resourceslices/s1", stale)
        assert exc.value.code == 409
        audit = srv.exactly_once_audit()
        assert audit["exactly_once"], audit
        assert audit["slices_audited"] == 1
    finally:
        srv.stop()


def test_fabric_throttles_beyond_capacity_and_client_retries_gets():
    srv = FleetApiServer(latency_s=0.4, max_inflight=1)
    try:
        client = ApiClient(srv.url, token_path="/nonexistent")
        blocker = threading.Thread(
            target=lambda: client.get_json("/api/v1/nodes/slow"),
            daemon=True)
        blocker.start()
        # wait until the blocker actually OCCUPIES the single admission
        # slot, so the probe below deterministically draws a 429 first
        deadline = time.monotonic() + 5
        while srv._admitted < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv._admitted >= 1
        # the blocked slot forces 429s; the client's bounded in-call GET
        # retry (jittered, client-wide backoff) absorbs most of the
        # window, and the outer loop models the caller retrying a GET
        # whose in-call budget expired while the slot was still held —
        # the budget is deliberately bounded, so exhausting it under a
        # 400 ms hold is legitimate behavior, not a failure
        out = ApiClient(srv.url, token_path="/nonexistent")
        node = None
        for _ in range(5):
            try:
                node = out.get_json("/api/v1/nodes/n2")
                break
            except ApiError as exc:
                assert exc.code == 429, exc
        assert node is not None and node["metadata"]["name"] == "n2"
        assert out.throttled_total.value >= 1
        assert out.thread_throttled_count() >= 1
        blocker.join(timeout=5)
        assert srv.snapshot()["throttled_total"] >= 1
    finally:
        srv.stop()


def test_fabric_load_dependent_latency_degrades_with_inflight():
    """congestion_k: service time scales 1 + inflight/k — concurrent
    requests are measurably slower than a lone one (the herd makes
    itself slow; what the pacing bench's peak-in-flight cells model)."""
    srv = FleetApiServer(latency_s=0.05, congestion_k=1)
    try:
        lone = ApiClient(srv.url, token_path="/nonexistent")
        t0 = time.monotonic()
        lone.get_json("/api/v1/nodes/a")
        lone_wall = time.monotonic() - t0

        clients = [ApiClient(srv.url, token_path="/nonexistent")
                   for _ in range(4)]
        walls = []

        def hit(c):
            t0 = time.monotonic()
            c.get_json("/api/v1/nodes/b")
            walls.append(time.monotonic() - t0)

        threads = [threading.Thread(target=hit, args=(c,), daemon=True)
                   for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # the slowest concurrent request saw >= 2 in flight: its service
        # time is at least ~2x the lone request's base
        assert max(walls) > lone_wall * 1.5, (lone_wall, walls)
    finally:
        srv.stop()


# --------------------------------------------------------- pacing unit


def test_pacer_coalesces_concurrent_publishers():
    """Publishers arriving during a wave's admission wait ride that wave:
    5 concurrent requests -> 1 publish_fn call, every caller sees the
    wave's result."""
    calls = []
    release = threading.Event()

    def publish():
        calls.append(threading.get_ident())
        return True

    pacer = PublishPacer(base_window_s=0.3)
    results = []

    def caller():
        release.wait(5)
        results.append(pacer.run(publish))

    threads = [threading.Thread(target=caller, daemon=True)
               for _ in range(5)]
    for t in threads:
        t.start()
    release.set()
    for t in threads:
        t.join(timeout=10)
    assert len(calls) == 1, calls
    assert results == [True] * 5
    snap = pacer.snapshot()
    assert snap["publish_waves_total"] == 1
    assert snap["publishes_coalesced_total"] == 4


def test_pacer_zero_window_adds_no_delay_and_adapts_on_throttle():
    class FakeApi:
        def __init__(self):
            self.last_code = None
            self.last_rtt_s = 0.001

        def reset_thread_error(self):
            self.last_code = None

        def thread_last_error_code(self):
            return self.last_code

    api = FakeApi()
    pacer = PublishPacer(api=api, base_window_s=0.0, max_window_s=2.0)
    assert pacer.run(lambda: True) is True
    assert pacer.snapshot()["window_ms"] == 0      # uncongested: no pacing
    assert pacer.snapshot()["pacing_delays_total"] == 0

    # a throttled failure (the wave's final request answered 429) grows
    # the window and re-admits; success through the grown window decays
    outcomes = [False, True]

    def publish():
        ok = outcomes.pop(0)
        api.last_code = None if ok else 429
        return ok

    assert pacer.run(publish) is True
    snap = pacer.snapshot()
    assert snap["publish_throttled_total"] == 1
    assert snap["pacing_delays_total"] >= 1        # the re-admission wait
    assert outcomes == []


def test_pacer_non_throttle_failure_with_earlier_throttled_get():
    """A wave whose internal GET drew a (retried-away) 429 but whose
    final request failed 5xx is NOT throttled: it returns to the
    caller's republish machinery immediately instead of re-admitting."""
    class FakeApi:
        def __init__(self):
            self.last_code = None
            self.last_rtt_s = 0.001

        def reset_thread_error(self):
            self.last_code = None

        def thread_last_error_code(self):
            return self.last_code

    api = FakeApi()
    pacer = PublishPacer(api=api, base_window_s=0.0, max_window_s=2.0)
    calls = []

    def publish():
        calls.append(1)
        api.last_code = 500     # the request that made the wave give up
        return False

    assert pacer.run(publish) is False
    assert len(calls) == 1
    assert pacer.snapshot()["publish_throttled_total"] == 0


def test_pacer_non_throttle_failure_returns_immediately():
    pacer = PublishPacer(base_window_s=0.0)
    calls = []

    def publish():
        calls.append(1)
        return False

    assert pacer.run(publish) is False
    assert len(calls) == 1     # no blind retry: the caller's machinery owns it


# ------------------------------------------------------------- storms


def test_boot_storm_publishes_every_node_exactly_once(fleet):
    sim = fleet(n_nodes=4)
    boot = sim.boot_storm()
    assert boot["published_ok"] == 4
    assert boot["exactly_once"], boot["audit"]
    assert boot["apiserver"]["slices"] == 4
    # one accepted write per node at boot: no duplicated POSTs
    assert boot["apiserver"]["accepted_writes"] == 4
    assert sim.assert_converged()


def test_boot_storm_converges_through_a_throttling_fabric(fleet):
    """A capped fabric 429s the herd; the adaptive windows + in-pacer
    re-admission land every node's slice exactly once. A node may
    legitimately exhaust its in-call retry budget under extreme
    throttling (production hands off to the republish timer); settle()
    compresses that timer, after which convergence and the exactly-once
    write audit must hold unconditionally."""
    sim = fleet(n_nodes=6, latency_s=0.05, max_inflight=2, pace=True)
    boot = sim.boot_storm()
    assert boot["published_ok"] >= 4     # the storm mostly lands in-call
    sim.settle()
    assert sim.assert_converged()
    audit = sim.apiserver.exactly_once_audit()
    assert audit["exactly_once"], audit
    assert audit["slices_audited"] == 6


def test_attach_storm_prepares_every_claim(fleet):
    sim = fleet(n_nodes=4)
    sim.boot_storm()
    attach = sim.attach_storm(4)
    assert attach["errors"] == []
    assert attach["prepared_total"] == 16
    # group commit held fleet-wide: commits well under one per claim
    assert attach["checkpoint_commits"] < 16


def test_flip_wave_coalesces_and_lands_final_state(fleet):
    sim = fleet(n_nodes=4, latency_s=0.02, max_inflight=2)
    sim.boot_storm()
    flip = sim.flip_wave(6)
    assert flip["converged"]
    assert flip["exactly_once"]
    # the fabric never saw one write per flip: pacing + effective-flip
    # publishing bound the wave count below the raw flip count
    assert flip["accepted_writes"] < 4 * 7


def test_drain_upgrade_wave_preserves_claims(fleet):
    sim = fleet(n_nodes=4)
    sim.boot_storm()
    sim.attach_storm(2)
    wave = sim.drain_upgrade_wave(2)
    assert wave["waves"] == 2
    assert wave["converged"]
    assert wave["exactly_once"]
    assert wave["prepared_total"] == 8     # every claim survived upgrade


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("TDP_CHAOS_SOAK") != "1",
                    reason="soak: set TDP_CHAOS_SOAK=1 (make fleet-soak)")
def test_fleet_soak_64_node_boot_storm_with_chaos():
    """`make fleet-soak`: a 64-node boot storm + flip wave + attach storm
    + rolling upgrade with the chaos registry armed (publish refusals and
    apiserver transport faults firing mid-storm), under TDP_LOCKDEP=1
    (the make target bakes it in). Every fleet contract must hold
    through the faults."""
    faults.reset()
    faults.arm("dra.publish", kind="drop", count=8)
    faults.arm("kubeapi.request", kind="error", count=8)
    try:
        sim = FleetSim(n_nodes=64, devices_per_node=4, latency_s=0.02,
                       max_inflight=8, pace=True, seed=1337)
        try:
            boot = sim.boot_storm()
            # armed dra.publish faults fail some first publishes; the
            # nodes' own retry (pacer returns False -> storm result
            # False) is out of scope here — republish and convergence
            # are: re-drive the failed nodes once, then audit
            for node in sim.nodes:
                name = node.driver.slice_name()
                with sim.apiserver._lock:
                    missing = name not in sim.apiserver.slices
                if missing:
                    assert node.driver.publish_resource_slices()
            assert sim.assert_converged()
            flip = sim.flip_wave(4)
            assert flip["converged"] and flip["exactly_once"]
            attach = sim.attach_storm(4)
            assert attach["errors"] == []
            assert attach["prepared_total"] == 256
            wave = sim.drain_upgrade_wave(16)
            assert wave["converged"] and wave["exactly_once"]
            assert wave["prepared_total"] == 256
            assert boot["exactly_once"]
        finally:
            sim.stop()
    finally:
        faults.reset()
