"""DRA driver concurrency stress: prepares + health churn + inventory swaps.

tests/test_stress.py pressure-tests the classic plugin servers; this suite
does the same for the DRA driver, whose shared mutable state (checkpoint,
prune set, sticky name records, publish lock, republish timer) is touched
from gRPC workers, the plugin servers' health listener, the PluginManager's
rediscovery callback, and a retry timer thread. Invariants asserted after
the storm: no exceptions or deadlocks, a prepared claim always resolves to
the same devices, the final slice reflects the final health state, and the
checkpoint drains to empty.
"""
import os
import random
import threading
import time

import pytest

from tests.fakehost import FakeChip, FakeHost
from tests.test_dra import FakeApiServer
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover
from tpu_device_plugin.dra import DraDriver, slice_device_name
from tpu_device_plugin.kubeapi import ApiClient
from tpu_device_plugin.kubeletapi import drapb

N_CHIPS = 4


@pytest.fixture
def rig(short_root):
    host = FakeHost(short_root)
    for i in range(N_CHIPS):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i), numa_node=i // 2))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    apiserver = FakeApiServer()
    registry, generations = discover(cfg)
    api = ApiClient(apiserver.url, token_path="/nonexistent-token")
    driver = DraDriver(cfg, registry, generations, node_name="node-a",
                       api=api)
    yield host, cfg, driver, apiserver, registry, generations
    driver.stop()
    apiserver.stop()


def test_prepare_health_swap_storm(rig):
    host, cfg, driver, apiserver, registry, generations = rig
    bdfs = [f"0000:00:{4 + i:02x}.0" for i in range(N_CHIPS)]
    names = [slice_device_name(b) for b in bdfs]
    assert driver.publish_resource_slices()
    stop = threading.Event()
    errors = []

    def record(exc):
        errors.append(repr(exc))

    def prepare_worker(seed):
        """Fresh claim per iteration: prepare must yield exactly the
        claim's devices, then unprepare must drop the checkpoint entry."""
        rng = random.Random(seed)
        n = 0
        while not stop.is_set():
            n += 1
            uid = f"storm-{seed}-{n}"
            picked = rng.sample(names, 2)
            apiserver.add_claim("ns", f"c{seed}-{n}", uid,
                                driver.driver_name,
                                [{"device": x} for x in picked])
            claim = drapb.Claim(namespace="ns", name=f"c{seed}-{n}",
                                uid=uid)
            try:
                resp = driver.NodePrepareResources(
                    drapb.NodePrepareResourcesRequest(claims=[claim]), None)
                out = resp.claims[uid]
                if out.error:
                    record(AssertionError(f"prepare failed: {out.error}"))
                elif sorted(d.device_name for d in out.devices) \
                        != sorted(picked):
                    record(AssertionError(
                        f"prepare returned wrong devices for {picked}: "
                        f"{[d.device_name for d in out.devices]}"))
                driver.NodeUnprepareResources(
                    drapb.NodeUnprepareResourcesRequest(claims=[claim]),
                    None)
            except Exception as exc:
                record(exc)

    def health_worker():
        rng = random.Random(7)
        while not stop.is_set():
            bdf = rng.choice(bdfs)
            try:
                driver.apply_health({bdf: rng.random() < 0.5})
            except Exception as exc:
                record(exc)
            time.sleep(0.005)

    def swap_worker():
        while not stop.is_set():
            try:
                driver.set_inventory(registry, generations)
            except Exception as exc:
                record(exc)
            time.sleep(0.02)

    def publish_worker():
        while not stop.is_set():
            try:
                driver.publish_resource_slices()
            except Exception as exc:
                record(exc)
            time.sleep(0.01)

    threads = ([threading.Thread(target=prepare_worker, args=(i,),
                                 daemon=True) for i in range(4)]
               + [threading.Thread(target=health_worker, daemon=True),
                  threading.Thread(target=swap_worker, daemon=True),
                  threading.Thread(target=publish_worker, daemon=True)])
    for t in threads:
        t.start()
    time.sleep(3)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "worker deadlocked"
    assert not errors, errors[:3]

    # names never re-pointed: every published name still maps to its bdf
    with driver._lock:
        assert {n: driver._raw_id(k, o)
                for n, (k, g, o) in driver._by_name.items()} == \
            dict(zip(names, bdfs))
    # converge: all healthy again -> final slice carries all devices
    driver.apply_health({b: True for b in bdfs})
    assert driver.publish_resource_slices()
    obj = next(iter(apiserver.slices.values()))
    assert sorted(d["name"] for d in obj["spec"]["devices"]) == \
        sorted(names)
    # checkpoint drained (every prepared claim was unprepared)
    with driver._lock:
        assert driver._checkpoint == {}
    # no orphaned per-claim CDI spec files
    leftovers = [f for f in os.listdir(driver.cdi_dir)
                 if "claim" in f] if os.path.isdir(driver.cdi_dir) else []
    assert leftovers == []
