"""Kubelet devicemanager simulator: the kubelet SIDE of the plugin protocol.

FakeKubelet (fakehost.py) only records Register calls; every other suite
drives the plugin's RPCs directly. This harness instead behaves like the
kubelet's devicemanager does (upstream semantics:
pkg/kubelet/cm/devicemanager, consumed by the reference through the same
v1beta1 contract its vendored api.proto locks):

  - serves `Registration` on kubelet.sock and VALIDATES the request
    (version, resource-name form, endpoint socket exists),
  - on Register, DIALS BACK the plugin's endpoint, fetches
    GetDevicePluginOptions, and holds a long-lived ListAndWatch stream in a
    background thread, maintaining the per-resource healthy/unhealthy device
    view that backs node allocatable,
  - admits pods devicemanager-style: pick from healthy unallocated devices
    (registration order), consult GetPreferredAllocation when the plugin's
    options advertise it (validating the response is a subset of the offered
    pool at the requested size), then Allocate — marking devices in use only
    on success, so a failed Allocate leaves the pool untouched,
  - handles RE-registration of the same resource by replacing the old
    endpoint state (the kubelet does this when a plugin restarts).

This is still an in-repo stand-in, not a real kubelet — the kind-based
nightly job (.github/workflows/e2e.yml + scripts/e2e_kind.sh) covers that;
this harness is the strongest conformance check that runs with no cluster.
"""

from __future__ import annotations

import os
import threading
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.kubeletapi import pb


class ConformanceError(AssertionError):
    """A plugin behavior that a real kubelet would reject."""


class _Endpoint:
    """One registered plugin: options + live device view from ListAndWatch."""

    def __init__(self, resource: str, channel, stub, options):
        self.resource = resource
        self.channel = channel
        self.stub = stub
        self.options = options
        self.devices: Dict[str, str] = {}   # id -> Healthy/Unhealthy
        self.in_use: set = set()
        self.updates = 0
        self.stream_error: Optional[Exception] = None
        self._thread: Optional[threading.Thread] = None
        self._stream = None

    def close(self):
        if self._stream is not None:
            self._stream.cancel()
        self.channel.close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class DeviceManagerSim:
    """See module docstring. Thread-safe; one instance per fake node."""

    def __init__(self, device_plugin_dir: str):
        self.dir = device_plugin_dir
        self.cond = threading.Condition()
        self.endpoints: Dict[str, _Endpoint] = {}
        self.rejections: List[str] = []
        outer = self

        class Reg(api.RegistrationServicer):
            def Register(self, request, context):
                try:
                    outer._register(request)
                except ConformanceError as exc:
                    outer.rejections.append(str(exc))
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
                return pb.Empty()

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        api.add_registration_servicer(self._server, Reg())
        sock = os.path.join(device_plugin_dir, "kubelet.sock")
        self._server.add_insecure_port(f"unix://{sock}")
        self._server.start()

    # ------------------------------------------------------------ registration

    def _register(self, request) -> None:
        if request.version != api.API_VERSION:
            raise ConformanceError(
                f"unsupported API version {request.version!r}")
        if "/" not in request.resource_name:
            raise ConformanceError(
                f"resource name {request.resource_name!r} lacks a namespace")
        endpoint_path = os.path.join(self.dir, request.endpoint)
        if not os.path.exists(endpoint_path):
            raise ConformanceError(
                f"endpoint socket {endpoint_path} does not exist")

        channel = grpc.insecure_channel(f"unix://{endpoint_path}")
        stub = api.DevicePluginStub(channel)
        options = stub.GetDevicePluginOptions(pb.Empty(), timeout=5)
        ep = _Endpoint(request.resource_name, channel, stub, options)

        with self.cond:
            old = self.endpoints.pop(request.resource_name, None)
            self.endpoints[request.resource_name] = ep
            self.cond.notify_all()
        if old is not None:
            old.close()   # kubelet replaces a re-registering plugin's endpoint

        ep._stream = stub.ListAndWatch(pb.Empty())

        def watch():
            try:
                for msg in ep._stream:
                    with self.cond:
                        ep.devices = {d.ID: d.health for d in msg.devices}
                        ep.updates += 1
                        self.cond.notify_all()
            except grpc.RpcError as exc:
                if exc.code() != grpc.StatusCode.CANCELLED:
                    ep.stream_error = exc

        ep._thread = threading.Thread(target=watch, daemon=True,
                                      name=f"law-{request.resource_name}")
        ep._thread.start()

    # ------------------------------------------------------------ node state

    def wait_for_resource(self, resource: str, timeout: float = 15) -> bool:
        with self.cond:
            return self.cond.wait_for(
                lambda: resource in self.endpoints
                and self.endpoints[resource].updates > 0,
                timeout=timeout)

    def wait_for_allocatable(self, resource: str, n: int,
                             timeout: float = 15) -> bool:
        with self.cond:
            return self.cond.wait_for(
                lambda: self.allocatable(resource) == n, timeout=timeout)

    def allocatable(self, resource: str) -> int:
        """Healthy device count = what the node would advertise."""
        ep = self.endpoints.get(resource)
        if ep is None:
            return 0
        return sum(1 for h in ep.devices.values() if h == api.HEALTHY)

    # ------------------------------------------------------------ admission

    def admit_pod(self, resource: str, n: int) -> Tuple[List[str], object]:
        """Devicemanager admission: returns (device_ids, AllocateResponse).

        Raises ConformanceError on any plugin response a kubelet would
        reject, grpc.RpcError if the plugin errors the RPC (pod stays
        Pending; pool untouched).

        The lock is held across pick → GetPreferredAllocation → Allocate →
        commit, like the real devicemanager's admission lock: concurrent
        admissions serialize rather than double-booking devices. (Holding it
        blocks ListAndWatch view updates for the RPC's duration — the
        devicemanager has the same property.)
        """
        with self.cond:
            ep = self.endpoints.get(resource)
            if ep is None:
                raise ConformanceError(f"no plugin for {resource}")
            free = [i for i, h in ep.devices.items()
                    if h == api.HEALTHY and i not in ep.in_use]
            if len(free) < n:
                raise ConformanceError(
                    f"insufficient {resource}: want {n}, have {len(free)}")
            picked = free[:n]
            if ep.options.get_preferred_allocation_available:
                pref = ep.stub.GetPreferredAllocation(
                    pb.PreferredAllocationRequest(container_requests=[
                        pb.ContainerPreferredAllocationRequest(
                            available_deviceIDs=free,
                            must_include_deviceIDs=[],
                            allocation_size=n)]),
                    timeout=5)
                got = list(pref.container_responses[0].deviceIDs)
                if len(got) != n:
                    raise ConformanceError(
                        f"GetPreferredAllocation returned {len(got)} ids, "
                        f"requested {n}")
                if not set(got) <= set(free):
                    raise ConformanceError(
                        f"GetPreferredAllocation returned ids outside the "
                        f"offered pool: {sorted(set(got) - set(free))}")
                picked = got
            resp = ep.stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=picked)]),
                timeout=5)
            if len(resp.container_responses) != 1:
                raise ConformanceError(
                    f"Allocate returned {len(resp.container_responses)} "
                    f"container responses for 1 request")
            for spec in resp.container_responses[0].devices:
                if not spec.host_path or not spec.container_path:
                    raise ConformanceError(
                        f"DeviceSpec with empty path: {spec}")
                if not os.path.exists(spec.host_path):
                    raise ConformanceError(
                        f"DeviceSpec host path missing: {spec.host_path}")
            ep.in_use.update(picked)
        return picked, resp

    def release_pod(self, resource: str, device_ids: List[str]) -> None:
        with self.cond:
            ep = self.endpoints.get(resource)
            if ep is not None:
                ep.in_use.difference_update(device_ids)
                self.cond.notify_all()

    def stop(self) -> None:
        self._server.stop(0)
        for ep in list(self.endpoints.values()):
            ep.close()
