"""Status endpoint: /healthz gating and /status content."""

import json
import os
import threading
import urllib.request
from concurrent import futures

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.lifecycle import PluginManager
from tpu_device_plugin.status import StatusServer


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture
def rig(short_root):
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = grpc.server(futures.ThreadPoolExecutor(max_workers=2))

    class Reg(api.RegistrationServicer):
        def Register(self, request, context):
            return pb.Empty()

    api.add_registration_servicer(kubelet, Reg())
    kubelet.add_insecure_port(f"unix://{cfg.kubelet_socket}")
    kubelet.start()
    manager = PluginManager(cfg)
    status = StatusServer(manager, port=0)
    status.start()
    yield host, manager, status
    status.stop()
    manager.stop()
    kubelet.stop(0)


def test_healthz_tracks_manager_state(rig):
    host, manager, status = rig
    code, _ = _get(status.port, "/healthz")
    assert code == 503  # nothing serving yet
    manager.start()
    code, body = _get(status.port, "/healthz")
    assert (code, body) == (200, b"ok")
    manager.stop()
    code, _ = _get(status.port, "/healthz")
    assert code == 503


def test_status_payload(rig):
    host, manager, status = rig
    manager.start()
    code, body = _get(status.port, "/status")
    assert code == 200
    payload = json.loads(body)
    assert payload["pending"] == []
    (plugin,) = payload["plugins"]
    assert plugin["resource"] == "cloud-tpus.google.com/v4"
    assert plugin["serving"] is True
    assert plugin["devices"] == {"0000:00:04.0": "Healthy"}
    assert plugin["restarts"] == 0


def test_unknown_path_404(rig):
    host, manager, status = rig
    code, _ = _get(status.port, "/nope")
    assert code == 404
