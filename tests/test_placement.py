"""Slice placement engine tests (ISSUE 10).

Unit coverage of placement.py (shape algebra, scoring, fragmentation,
single/multi-host planning, defrag advisories) plus the daemon
integration: DRA fragmentation gauges recomputed per epoch publish,
/debug/defrag over real HTTP, the placement counters on /status +
/metrics, and the preferred-allocation scoring surface. The fleetsim
end-to-end scenarios (multi-host claims, rollback, defrag application
via migration handoff) live in tests/test_fleetsim.py.
"""

import json
import os
import urllib.request

import pytest

from tests.fakehost import FakeChip, FakeHost
from tests.test_dra import FakeApiServer
from tpu_device_plugin import placement
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.dra import DraDriver
from tpu_device_plugin.kubeapi import ApiClient
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.placement import HostView
from tpu_device_plugin.server import TpuDevicePlugin


def view(node="n0", dims=(2, 4), occupied=(), departed=(), claims=None,
         missing=()):
    """A hand-built HostView: chips at every torus coordinate except
    `missing`; `occupied` coords are claim-held (one claim per coord
    unless `claims` maps uid -> [coords]), `departed` coords are holes."""
    import itertools
    coords = {}
    names = {}
    for c in itertools.product(*[range(d) for d in dims]):
        if c in set(missing):
            continue
        raw = "c" + "-".join(str(x) for x in c)
        coords[raw] = c
        names[raw] = raw
    raw_at = {c: r for r, c in coords.items()}
    claim_map = {}
    if claims:
        claim_map = {uid: tuple(raw_at[c] for c in cs)
                     for uid, cs in claims.items()}
    else:
        for i, c in enumerate(occupied):
            claim_map[f"claim-{i}"] = (raw_at[c],)
    held = {r for raws in claim_map.values() for r in raws}
    dep = frozenset(raw_at[c] for c in departed)
    free = frozenset(r for r in coords
                     if r not in held and r not in dep)
    return HostView(node=node, dims=dims, coords=coords, names=names,
                    free=free, departed=dep, claims=claim_map)


# ------------------------------------------------------------ shape algebra


def test_parse_shape_forms():
    assert placement.parse_shape("2x2x1") == (2, 2, 1)
    assert placement.parse_shape("4") == (4,)
    assert placement.parse_shape([2, 2]) == (2, 2)


@pytest.mark.parametrize("bad", ["", "0x2", "-1", "2xa", [0]])
def test_parse_shape_rejects_malformed(bad):
    with pytest.raises(ValueError):
        placement.parse_shape(bad)


@pytest.mark.parametrize("bad", [
    "0x2", "-1", "2x-3", [0], [2, -1],          # zero/negative axes
    "4294967296x2", str(1 << 40),               # per-axis overflow
    "1024x1024", [256, 256, 256],               # volume overflow
    "2xa", "x", [2.5], None, object(),          # non-integer shapes
])
def test_parse_shape_rejects_degenerate_with_typed_error(bad):
    """ISSUE 14 regression: zero/negative/overflow dimensions raise the
    TYPED ShapeError (a ValueError, so /debug/defrag's 400 mapping
    holds) instead of planning degenerate boxes — a 2^32-axis shape
    must die at parse, not in _boxes' interval table."""
    with pytest.raises(placement.ShapeError):
        placement.parse_shape(bad)


def test_parse_shape_accepts_bounds():
    assert placement.parse_shape("1024") == (1024,)
    assert placement.parse_shape([256, 256]) == (256, 256)


def test_orientations_pad_and_permute():
    assert placement.orientations((4,), 2) == ((1, 4), (4, 1))
    # trailing 1-axes collapse: 2x2x1 on a 2D torus is just 2x2
    assert placement.orientations((2, 2, 1), 2) == ((2, 2),)
    # more >1 axes than the torus has: impossible
    assert placement.orientations((2, 2, 2), 2) == ()


def test_selection_score_box_vs_stragglers():
    assert placement.selection_score((2, 4), [(0, 0), (0, 1)]) == 1.0
    assert placement.selection_score(
        (2, 4), [(0, 0), (0, 1), (1, 0), (1, 1)]) == 1.0
    # opposite corners: covering box is the whole 2x4 -> 2/8
    assert placement.selection_score((2, 4), [(0, 0), (1, 3)]) == 0.25
    assert placement.selection_score(None, [(0, 0)]) == 0.0
    assert placement.selection_score((2, 4), [(0, 0), None]) == 0.0


# ------------------------------------------------------------ fragmentation


def test_fragmentation_whole_host_free_is_zero():
    rec = placement.fragmentation(view())
    assert rec == {"chips": 8, "free": 8, "departed": 0,
                   "largest_free_box": 8, "fragmentation": 0.0}


def test_fragmentation_scattered_free_scores_high():
    # free: (0,0),(1,1),(0,2),(1,3) — checkerboard, no two adjacent
    v = view(occupied=[(0, 1), (1, 0), (0, 3), (1, 2)])
    rec = placement.fragmentation(v)
    assert rec["free"] == 4
    assert rec["largest_free_box"] == 1
    assert rec["fragmentation"] == 0.75


def test_departed_hole_counts_toward_fragmentation():
    """ISSUE 10 satellite: a gone chip's slot splits boxes (raising the
    score) without adding free capacity."""
    baseline = placement.fragmentation(view(occupied=[(0, 1)]))
    departed = placement.fragmentation(view(departed=[(0, 1)]))
    # same geometry, same free count either way; the hole fragments
    assert departed["free"] == baseline["free"] == 7
    assert departed["departed"] == 1
    assert departed["largest_free_box"] == baseline["largest_free_box"] == 4
    assert departed["fragmentation"] == baseline["fragmentation"] > 0


def test_fragmentation_full_host_is_zero_not_divzero():
    v = view(occupied=[(x, y) for x in range(2) for y in range(4)])
    rec = placement.fragmentation(v)
    assert rec["free"] == 0 and rec["fragmentation"] == 0.0


# ------------------------------------------------------------- plan_slice


def test_single_host_box_any_orientation():
    plan = placement.plan_slice((4,), [view()])
    assert plan is not None and plan.score == 1.0 and plan.hosts == 1
    (_node, raws), = plan.shards
    coords = [view().coords[r] for r in raws]
    assert placement.selection_score((2, 4), coords) == 1.0


def test_plan_prefers_best_fit_host():
    """Two hosts can fit a 2x2; the one whose remaining free space stays
    LEAST fragmented wins (best-fit, not first-fit)."""
    tight = view(node="tight", occupied=[(0, 2), (0, 3), (1, 2), (1, 3)])
    empty = view(node="empty")
    plan = placement.plan_slice((2, 2), [empty, tight])
    assert plan.shards[0][0] == "tight"   # placing there leaves 0 free
    plan2 = placement.plan_slice((2, 2), [empty])
    assert plan2.shards[0][0] == "empty"


def test_plan_multi_host_requires_full_tori():
    """4x4 over 2x4 hosts = two FULLY-free tori; a host with one claim
    cannot join the tiling (cross-host ICI joins whole blocks)."""
    a, b, c = view(node="a"), view(node="b"), view(node="c",
                                                   occupied=[(0, 0)])
    plan = placement.plan_slice((4, 4), [a, b, c])
    assert plan is not None and plan.hosts == 2 and plan.score == 1.0
    assert {s[0] for s in plan.shards} == {"a", "b"}
    assert placement.plan_slice((4, 4), [a, c]) is None
    # shape that does not factor over the host torus: no tiling
    assert placement.plan_slice((3, 4), [a, b, c]) is None


def test_plan_best_effort_scatters_with_honest_score():
    v = view(occupied=[(0, 1), (1, 0), (0, 3), (1, 2)])  # checkerboard
    assert placement.plan_slice((2, 2), [v]) is None
    plan = placement.plan_slice((2, 2), [v], best_effort=True)
    assert plan is not None and 0 < plan.score < 1.0


def test_plan_unplaceable_returns_none():
    v = view(occupied=[(x, y) for x in range(2) for y in range(4)])
    assert placement.plan_slice((2, 2), [v], best_effort=True) is None


# ---------------------------------------------------------------- defrag


def test_defrag_picks_minimal_blocker_box():
    """Box (0,0)-(1,1) is blocked by ONE claim; (0,2)-(1,3) by two.
    The advisory must evict exactly the one."""
    v = view(claims={"one": [(0, 0)],
                     "two-a": [(0, 2)], "two-b": [(1, 3)]})
    prop = placement.propose_defrag((2, 2), [v])
    assert not prop["placeable"] and prop["satisfiable"]
    assert prop["moves"] == 1
    assert prop["migrations"][0]["claim"] == "one"
    # destination stays outside the target box
    target = set(prop["target"]["devices"])
    assert not target & set(prop["migrations"][0]["target_devices"])


def test_defrag_excludes_departed_boxes_and_destinations():
    """ISSUE 10 satellite: a departed hole disqualifies every box that
    contains it (no silicon to migrate onto) and is never a destination."""
    # hole at (0,0); claims block the right half lightly
    v = view(departed=[(0, 0)], claims={"c": [(0, 2)]})
    prop = placement.propose_defrag((2, 2), [v])
    assert not prop["placeable"] and prop["satisfiable"]
    hole_name = "c0-0"
    assert hole_name not in prop["target"]["devices"]
    for mig in prop["migrations"]:
        assert hole_name not in (mig["target_devices"] or ())


def test_defrag_migrates_multi_chip_claim_to_scattered_slots():
    """Regression: a multi-chip blocking claim whose destination has no
    contiguous box of its size must still get a (scattered) target —
    this used to crash with UnboundLocalError in _destination."""
    v = view(claims={"pair": [(0, 0), (0, 1)], "s1": [(1, 2)],
                     "s2": [(0, 3)]})
    prop = placement.propose_defrag((2, 2), [v])
    assert not prop["placeable"] and prop["satisfiable"]
    assert prop["moves"] == 1
    mig = prop["migrations"][0]
    assert mig["claim"] == "pair" and len(mig["target_devices"]) == 2
    assert not set(mig["target_devices"]) & set(prop["target"]["devices"])


def test_defrag_unsatisfiable_when_capacity_short():
    v = view(claims={"big": [(0, 0), (0, 1), (0, 2), (0, 3), (1, 0)]},
             departed=[(1, 1)])
    # free = 2 < 4 wanted
    prop = placement.propose_defrag((2, 2), [v])
    assert not prop["satisfiable"]


def test_defrag_placeable_short_circuits():
    prop = placement.propose_defrag((2, 2), [view()])
    assert prop["placeable"] and prop["moves"] == 0


def test_defrag_cross_host_destination():
    """Blockers move to ANOTHER host when the local one has no room."""
    full = view(node="a", occupied=[(0, 0), (0, 2), (0, 3), (1, 0),
                                    (1, 2), (1, 3)])
    spare = view(node="b", occupied=[(0, 1), (1, 0), (0, 3), (1, 2)])
    prop = placement.propose_defrag((2, 2), [full, spare])
    assert not prop["placeable"] and prop["satisfiable"]
    assert any(m["target_node"] == "b" for m in prop["migrations"])


# ------------------------------------------------- daemon integration


@pytest.fixture()
def rig(short_root):
    """8-chip v5e host + DRA driver against a fake apiserver."""
    host = FakeHost(short_root)
    for i in range(8):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i), numa_node=i // 4))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    apiserver = FakeApiServer()
    registry, generations = discover_passthrough(cfg)
    driver = DraDriver(cfg, registry, generations, node_name="n",
                       api=ApiClient(apiserver.url,
                                     token_path="/nonexistent"))
    yield cfg, registry, generations, driver, apiserver
    driver.stop()
    apiserver.stop()


def _prepare(driver, apiserver, uid, names):
    from tpu_device_plugin.kubeletapi import drapb
    apiserver.add_claim("ns", uid, uid, driver.driver_name,
                        [{"device": nm} for nm in names])
    resp = driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=[
            drapb.Claim(namespace="ns", name=uid, uid=uid)]), None)
    assert resp.claims[uid].error == "", resp.claims[uid].error


def test_driver_fragmentation_recomputes_on_claims_and_health(rig):
    _cfg, _registry, _generations, driver, apiserver = rig
    frag0 = driver.fragmentation_stats()["v5e"]
    assert frag0["free"] == 8 and frag0["fragmentation"] == 0.0
    recomputes0 = driver.placement_stats["frag_recomputes_total"]
    # claim one chip -> free drops, recompute counted
    v = driver.host_views()["v5e"]
    raw_at = {c: r for r, c in v.coords.items()}
    _prepare(driver, apiserver, "u1", [v.names[raw_at[(0, 1)]]])
    frag1 = driver.fragmentation_stats()["v5e"]
    assert frag1["free"] == 7 and frag1["fragmentation"] > 0
    assert driver.placement_stats["frag_recomputes_total"] > recomputes0
    # health flip publishes an epoch AND refreshes fragmentation
    driver.apply_health({raw_at[(1, 2)]: False})
    frag2 = driver.fragmentation_stats()["v5e"]
    assert frag2["free"] == 6


def test_driver_host_view_claims_and_propose(rig):
    _cfg, _registry, _generations, driver, apiserver = rig
    v = driver.host_views()["v5e"]
    raw_at = {c: r for r, c in v.coords.items()}
    # checkerboard the host so no 2x2 box survives
    for i, c in enumerate([(0, 1), (1, 0), (0, 3), (1, 2)]):
        _prepare(driver, apiserver, f"u{i}", [v.names[raw_at[c]]])
    v2 = driver.host_views()["v5e"]
    assert len(v2.claims) == 4 and len(v2.free) == 4
    prop = driver.propose_defrag("2x2")
    assert not prop["placeable"] and prop["satisfiable"]
    assert prop["generation"] == "v5e"
    assert prop["moves"] >= 1
    assert driver.placement_stats["defrag_proposals_total"] == 1
    with pytest.raises(ValueError):
        driver.propose_defrag("2x2", generation="nope")


def test_status_and_metrics_surface_fragmentation(rig, short_root):
    from tpu_device_plugin.lifecycle import PluginManager
    from tpu_device_plugin.status import StatusServer
    cfg, registry, _generations, driver, _apiserver = rig
    manager = PluginManager(cfg)
    manager.plugins = [TpuDevicePlugin(
        cfg, "v5e", registry, registry.devices_by_model["0063"],
        torus_dims=(2, 4))]
    server = StatusServer(manager, port=0, dra_driver=driver)
    try:
        s = server.status()
        assert s["dra"]["fragmentation"]["v5e"]["free"] == 8
        assert "frag_recomputes_total" in s["dra"]["placement"]
        text = server.metrics()
        assert 'tpu_plugin_dra_fragmentation{generation="v5e"} 0.0' in text
        assert 'tpu_plugin_dra_largest_free_box{generation="v5e"} 8' in text
        assert 'tpu_plugin_dra_free_chips{generation="v5e"} 8' in text
        assert "tpu_plugin_dra_frag_recomputes_total" in text
        assert "tpu_plugin_dra_defrag_proposals_total 0" in text
        assert "tpu_plugin_pref_placement_score" in text
    finally:
        server._httpd.server_close()


def test_debug_defrag_endpoint_over_http(rig):
    from tpu_device_plugin.lifecycle import PluginManager
    from tpu_device_plugin.status import StatusServer
    cfg, _registry, _generations, driver, apiserver = rig
    v = driver.host_views()["v5e"]
    raw_at = {c: r for r, c in v.coords.items()}
    for i, c in enumerate([(0, 1), (1, 0), (0, 3), (1, 2)]):
        _prepare(driver, apiserver, f"u{i}", [v.names[raw_at[c]]])
    manager = PluginManager(cfg)
    server = StatusServer(manager, port=0, dra_driver=driver)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(f"{base}/debug/defrag?shape=2x2",
                                    timeout=5) as r:
            prop = json.load(r)
        assert not prop["placeable"] and prop["satisfiable"]
        assert prop["moves"] >= 1 and prop["target"]["node"] == "n"
        # ISSUE 14 satellite: the advisory carries the per-generation
        # fragmentation records alongside the proposal (same values
        # /status publishes), keyed by generation
        assert prop["fragmentation"]["v5e"]["free"] == 4
        assert prop["fragmentation"]["v5e"]["fragmentation"] > 0
        # malformed requests answer 400, not a stack trace — including
        # a generation with NO host view and overflow shapes
        for bad in ("/debug/defrag", "/debug/defrag?shape=0x2",
                    "/debug/defrag?shape=4294967296x2",
                    "/debug/defrag?shape=2x2&generation=nope",
                    "/debug/defrag?shape=2x2&generation="):
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + bad, timeout=5)
            assert exc.value.code == 400, bad
    finally:
        server.stop()


def test_debug_defrag_404_without_dra():
    from tpu_device_plugin.lifecycle import PluginManager
    from tpu_device_plugin.status import StatusServer
    server = StatusServer(PluginManager(Config()), port=0)
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/defrag?shape=2x2",
                timeout=5)
        assert exc.value.code == 404
    finally:
        server.stop()


def test_preferred_allocation_reports_placement_score(rig):
    cfg, registry, _generations, _driver, _apiserver = rig
    plugin = TpuDevicePlugin(cfg, "v5e", registry,
                             registry.devices_by_model["0063"],
                             torus_dims=(2, 4))
    ids = [d.bdf for d in registry.devices_by_model["0063"]]
    req = pb.PreferredAllocationRequest(container_requests=[
        pb.ContainerPreferredAllocationRequest(
            available_deviceIDs=ids, allocation_size=4)])
    resp = plugin.GetPreferredAllocation(req, None)
    chosen = list(resp.container_responses[0].deviceIDs)
    assert len(chosen) == 4
    snap = plugin.status_snapshot()["placement"]
    assert snap["scored_total"] == 1
    # a full host of free chips always yields one sub-box
    assert snap["last_score"] == 1.0
