"""The checked production scenarios — weave's reason to exist.

Each scenario drives REAL production code (epoch.py, trace.py, dra.py,
brokeripc.py, fleetsim.py, resilience.py, allocate.py) under the
cooperative scheduler and asserts a cross-thread protocol invariant
over EVERY explored interleaving. Scenarios come in pairs:

- the production scenario must pass (complete or stated-bounded
  exploration, zero counterexamples);
- its TWIN seeds a concurrency bug of exactly the class the invariant
  guards against (a forgotten notify, a torn seqlock write, a TOCTOU
  CAS, an ACK before durability) and must FAIL — a checker that cannot
  fire is a failing test (tests/test_weave.py enforces both directions,
  and `python -m tools.weave --twins` runs the mutation side in CI).

Scenario bodies construct their objects inside ``setup`` so the locks
and conditions production __init__ code creates are the cooperative
shims; module-level primitives (trace._maintenance_lock, faults._lock)
stay real, which is safe because no schedule point sits inside their
critical sections (see trace.Histogram._claim_cell).
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
import time
from bisect import bisect_right
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from tools.weave.core import Scenario
from tpu_device_plugin import schedcheck
from tpu_device_plugin.allocate import LiveAttrReader
from tpu_device_plugin.brokeripc import (RING_DEFAULT_TTL_S, RingReader,
                                         RingWriter, ring_key,
                                         _json_bytes, _RING_HEADER_PAD,
                                         _RING_SLOT_HDR)
from tpu_device_plugin.dra import DraDriver
from tpu_device_plugin.epoch import AtomicCounter, Epoch, EpochStore
from tpu_device_plugin.fleetsim import FleetApiServer
from tpu_device_plugin.resilience import CircuitBreaker
from tpu_device_plugin.trace import Histogram


# =====================================================================
# 1. epoch publish vs ListAndWatch waiter
# =====================================================================

class EpochPublishWaiter(Scenario):
    """A writer publishes epoch 1 while a ListAndWatch-style waiter
    parks on the store condition. No schedule may lose the wakeup (the
    wait is untimed, so a lost notify is a detected deadlock) and the
    woken waiter must observe the published payload, never a stale
    epoch-0 view."""

    name = "epoch-publish-waiter"
    description = "epoch publish vs parked ListAndWatch waiter"

    PAYLOAD = b"lw-payload-gen-1"

    def setup(self) -> Dict[str, Any]:
        store = EpochStore(Epoch(0))
        return {"store": store, "seen": [], "woke": []}

    def _publish(self, store: EpochStore) -> None:
        ep = Epoch(1, lw_payload=self.PAYLOAD)
        with store.lock():
            store.publish_locked(ep)

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        store = state["store"]

        def writer() -> None:
            self._publish(store)

        def waiter() -> None:
            woke = store.wait_for(lambda: store.current.epoch_id >= 1)
            state["woke"].append(woke)
            state["seen"].append(store.current.lw_payload)

        return [("writer", writer), ("waiter", waiter)]

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        store = state["store"]
        assert state["woke"] == [True], f"waiter never woke: {state}"
        assert state["seen"] == [self.PAYLOAD], \
            f"stale payload observed: {state['seen']!r}"
        assert store.publishes.value == 1
        assert store.waiters == 0, "waiter gauge leaked"


class EpochPublishNoNotifyTwin(EpochPublishWaiter):
    """SEEDED BUG twin: the writer swaps the epoch pointer without the
    notify_all — the classic forgotten wakeup. Weave must find the
    schedule where the waiter parks first and starves (deadlock)."""

    name = "twin-epoch-publish-no-notify"
    twin_of = "epoch-publish-waiter"

    def _publish(self, store: EpochStore) -> None:
        ep = Epoch(1, lw_payload=self.PAYLOAD)
        with store.lock():
            # seeded bug: publish without waking the waiters (setattr so
            # tsalint's epoch-mutation rule, which polices production
            # writers, is not what this deliberately-broken twin tests)
            setattr(store, "current", ep)
            store.publishes.add()


# =====================================================================
# 2. counter / histogram shard adoption vs concurrent observe
# =====================================================================

class CounterShardObserve(Scenario):
    """Two threads each count one event through AtomicCounter (each
    adopts its own shard on first add) while a reader sums a snapshot
    mid-flight. The mid-read may be anything from 0 to 2 but the final
    sum must be exactly 2 — the sharded design's whole claim."""

    name = "counter-shard-observe"
    description = "AtomicCounter shard adoption vs concurrent value read"

    def setup(self) -> Dict[str, Any]:
        return {"counter": AtomicCounter(), "mid": []}

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        c = state["counter"]

        def bump() -> None:
            c.add()

        def reader() -> None:
            state["mid"].append(c.value)

        return [("add-1", bump), ("add-2", bump), ("reader", reader)]

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        final = state["counter"].value
        assert final == 2, f"lost count: final={final}"
        mid = state["mid"][0]
        assert 0 <= mid <= 2, f"impossible mid-read {mid}"


class _LossyTotalCounter:
    """SEEDED BUG: the store-last-total counter design the AtomicCounter
    docstring warns against — a shared read-modify-write total."""

    def __init__(self) -> None:
        self._total = 0

    def add(self) -> None:
        schedcheck.yield_point("lossy.counter.read", obj=self, mode="r")
        total = self._total
        schedcheck.yield_point("lossy.counter.write", obj=self)
        self._total = total + 1

    @property
    def value(self) -> int:
        schedcheck.yield_point("lossy.counter.snapshot", obj=self,
                               mode="r")
        return self._total


class CounterLostUpdateTwin(CounterShardObserve):
    """SEEDED BUG twin: swap in the lossy shared-total counter. Weave
    must find the read-read-write-write schedule where one count is
    lost (final == 1)."""

    name = "twin-counter-lost-update"
    twin_of = "counter-shard-observe"

    def setup(self) -> Dict[str, Any]:
        return {"counter": _LossyTotalCounter(), "mid": []}


class HistogramAdoptObserve(Scenario):
    """Two observers race shard adoption on a Histogram that holds one
    dead-owner cell (a retired checkpoint-writer thread's shard, with
    counts already in it) while a scraper snapshots mid-flight. The
    adopted shard's history must never be lost and the final snapshot
    must count every observation exactly once."""

    name = "histogram-adopt-observe"
    description = "Histogram dead-shard adoption vs concurrent snapshot"

    class _DeadOwner:
        def is_alive(self) -> bool:
            return False

    def setup(self) -> Dict[str, Any]:
        h = Histogram("tdp_weave_scenario_ms", "weave scenario fixture",
                      bounds=(1.0,))
        # one retired shard with history: 5 observations totalling 2.5ms
        h._cells.append([self._DeadOwner(), [5, 0, 2.5]])
        return {"hist": h, "mid": []}

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        h = state["hist"]

        def observe() -> None:
            h.observe(0.5)

        def scraper() -> None:
            state["mid"].append(h.snapshot())

        return [("obs-1", observe), ("obs-2", observe),
                ("scraper", scraper)]

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        final = state["hist"].snapshot()
        assert final["count"] == 7, \
            f"lost count: {final['count']} != 7 (5 adopted + 2 new)"
        assert abs(final["sum"] - 3.5) < 1e-9, f"lost sum: {final['sum']}"
        mid = state["mid"][0]
        assert 5 <= mid["count"] <= 7, \
            f"impossible mid-scrape count {mid['count']}"
        # derived-count consistency: buckets can never exceed +Inf
        assert mid["buckets"][-1][1] <= mid["count"]


class _RacyAdoptHistogram(Histogram):
    """SEEDED BUG: shard adoption without the maintenance lock — two
    threads can both pass the dead-owner check and adopt the SAME cell.
    The per-bucket `cell[i] += 1` is only safe because ownership is
    exclusive, so with a shared cell the C-level read-modify-write
    (modeled here by the split around the schedule point) loses counts."""

    def _claim_cell(self) -> list:
        me = threading.current_thread()
        for entry in self._cells:
            schedcheck.yield_point("twin.hist.scan", obj=self, mode="r")
            if not entry[0].is_alive():
                # seeded bug: dead-check and adopt-write in different
                # steps, no lock — both observers adopt this shard
                schedcheck.yield_point("twin.hist.adopt", obj=self)
                entry[0] = me
                return entry[1]
        cell = [0] * (len(self.bounds) + 1) + [0.0]
        self._cells.append([me, cell])
        return cell

    def observe(self, value_ms: float,
                exemplar: Optional[str] = None) -> None:
        cell = self._claim_cell()
        i = bisect_right(self.bounds, value_ms)
        schedcheck.yield_point("twin.hist.read", obj=self, mode="r")
        count, total = cell[i], cell[-1]
        schedcheck.yield_point("twin.hist.write", obj=self)
        cell[i] = count + 1
        cell[-1] = total + value_ms


class HistogramDoubleAdoptTwin(HistogramAdoptObserve):
    """SEEDED BUG twin: the unlocked-adoption histogram above. Weave
    must find the schedule where both observers adopt the one dead
    shard and a count is lost to the shared-cell read-modify-write."""

    name = "twin-histogram-double-adopt"
    twin_of = "histogram-adopt-observe"

    def setup(self) -> Dict[str, Any]:
        h = _RacyAdoptHistogram("tdp_weave_scenario_ms",
                                "weave scenario fixture", bounds=(1.0,))
        h._cells.append([self._DeadOwner(), [5, 0, 2.5]])
        return {"hist": h, "mid": []}


# =====================================================================
# 3. dra group-commit writer vs claim mutations vs flush barrier
# =====================================================================

def _minimal_dra_driver(checkpoint_path: str) -> DraDriver:
    """A DraDriver stripped to its group-commit plane: enough real
    attributes for _claim_task / _checkpoint_flush / the writer loop to
    run unmodified. Built via __new__ so setup stays O(checkpoint) —
    the full __init__ wants sockets, inventory and kubelet plumbing."""
    drv = object.__new__(DraDriver)
    drv._lock = threading.Lock()
    drv._ckpt_cond = threading.Condition()
    drv._ckpt_dirty_gen = 0
    drv._ckpt_result_gen = 0
    drv._ckpt_durable_gen = 0
    drv._ckpt_pending_claims = 0
    drv._ckpt_failures = []
    drv._ckpt_error = None
    drv._ckpt_stopped = False
    drv._ckpt_thread = None
    drv._attach_active = 0
    drv._prepare_inflight = 0
    drv._checkpoint = {}
    drv._handoffs = {}
    drv._checkpoint_bytes = 0
    drv.checkpoint_path = checkpoint_path
    drv.checkpoint_commit_window_s = 0.010
    drv.checkpoint_stats_counters = {
        "checkpoint_commits_total": 0,
        "checkpoint_claims_coalesced_total": 0,
    }
    # the scenario runs the writer as an explicit controlled thread
    drv._ensure_checkpoint_writer_locked = lambda: None
    drv._recompute_fragmentation = lambda: None
    return drv


class DraGroupCommit(Scenario):
    """Two claims bracket real attach work (_claim_task), mutate the
    checkpoint under the driver lock, and hit the real flush barrier
    while the REAL _checkpoint_writer_loop group-commits. Every
    schedule must ACK both claims exactly once, only after their
    mutation is durable on disk, and drain the in-flight gauges."""

    name = "dra-group-commit"
    description = "group-commit writer vs claim mutations vs flush barrier"
    # quick matrix: preemption bound 1 completes in ~1s (condition-plane
    # switches at blocking points are free — only body preemptions
    # count); the soak leg (+1 bound, x25 budget) exhausts bound 2
    max_executions = 6000
    preemption_bound = 1

    def setup(self) -> Dict[str, Any]:
        fd, path = tempfile.mkstemp(prefix="weave-ckpt-")
        os.close(fd)
        os.unlink(path)
        drv = _minimal_dra_driver(path)
        return {"drv": drv, "path": path, "acked": [], "errors": {}}

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        drv = state["drv"]

        def claim(i: int) -> Callable[[], None]:
            def body() -> None:
                with drv._claim_task() as task:
                    with drv._lock:
                        drv._checkpoint[f"claim{i}"] = {"devices": [i]}
                    try:
                        drv._checkpoint_flush(task)
                    except BaseException as exc:
                        with drv._lock:
                            drv._checkpoint.pop(f"claim{i}", None)
                        state["errors"][i] = exc
                        return
                state["acked"].append(i)
            return body

        return [("claim-0", claim(0)), ("claim-1", claim(1)),
                ("writer", drv._checkpoint_writer_loop)]

    def drain(self, state: Dict[str, Any]) -> None:
        drv = state["drv"]
        with drv._ckpt_cond:
            drv._ckpt_stopped = True
            drv._ckpt_cond.notify_all()
        for leftover in (state["path"], state["path"] + ".tmp"):
            try:
                os.unlink(leftover)
            except OSError:
                pass

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        drv = state["drv"]
        assert sorted(state["acked"]) == [0, 1], \
            f"claims not all ACKed: {state['acked']} " \
            f"errors={state['errors']}"
        assert not state["errors"], f"unexpected errors: {state['errors']}"
        assert drv._ckpt_durable_gen == drv._ckpt_dirty_gen, \
            "ACK released before the covering write was durable"
        stats = drv.checkpoint_stats_counters
        assert stats["checkpoint_claims_coalesced_total"] == 2
        assert 1 <= stats["checkpoint_commits_total"] <= 2
        assert drv._attach_active == 0 and drv._prepare_inflight == 0, \
            "in-flight gauges leaked"


class DraCommitFailure(DraGroupCommit):
    """Same protocol with every checkpoint write FAILING (the
    checkpoint directory does not exist): no schedule may ACK either
    claim — both must see the write error through the failed-interval
    scan, roll back, and still drain the gauges."""

    name = "dra-commit-failure"
    description = "failing group commit: error fan-out, never a false ACK"

    def setup(self) -> Dict[str, Any]:
        # the checkpoint "directory" is a regular file, so the write's
        # os.makedirs fails deterministically on every attempt
        fd, blocker = tempfile.mkstemp(prefix="weave-ckpt-blocker-")
        os.close(fd)
        path = os.path.join(blocker, "ckpt.json")
        drv = _minimal_dra_driver(path)
        return {"drv": drv, "path": path, "blocker": blocker,
                "acked": [], "errors": {}}

    def drain(self, state: Dict[str, Any]) -> None:
        super().drain(state)
        try:
            os.unlink(state["blocker"])
        except OSError:
            pass

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        drv = state["drv"]
        assert state["acked"] == [], \
            f"claim ACKed despite failed commit: {state['acked']}"
        assert sorted(state["errors"]) == [0, 1], \
            f"claims did not all see the write error: {state['errors']}"
        assert drv._ckpt_durable_gen == 0
        assert drv._ckpt_result_gen == drv._ckpt_dirty_gen
        assert drv._ckpt_failures, "failed attempt interval not recorded"
        assert drv._attach_active == 0 and drv._prepare_inflight == 0, \
            "in-flight gauges leaked"


class DraAckBeforeDurableTwin(DraCommitFailure):
    """SEEDED BUG twin: a flush barrier that releases on attempt
    COMPLETION instead of durability (no failed-interval scan, no
    durable-generation check) — with the write failing, every schedule
    ACKs a claim whose checkpoint never reached disk."""

    name = "twin-dra-ack-before-durable"
    twin_of = "dra-commit-failure"

    def setup(self) -> Dict[str, Any]:
        state = super().setup()
        drv = state["drv"]

        def buggy_flush_impl(task: dict) -> None:
            with drv._ckpt_cond:
                drv._ckpt_dirty_gen += 1
                drv._ckpt_pending_claims += 1
                target = drv._ckpt_dirty_gen
                if task.get("active"):
                    task["active"] = False
                    drv._attach_active -= 1
                drv._ckpt_cond.notify_all()
                while drv._ckpt_result_gen < target \
                        and not drv._ckpt_stopped:
                    drv._ckpt_cond.wait()
                # seeded bug: "the writer ran" is treated as "my claim
                # is durable" — no durable check, no failure scan

        drv._checkpoint_flush_impl = buggy_flush_impl
        return state


# =====================================================================
# 4. seqlock response ring: writer vs reader vs slot retirement
# =====================================================================

class RingSeqlock(Scenario):
    """The broker overwrites a primed ring slot (retiring the old
    payload) while the daemon-side reader does a seqlock-validated
    lookup. Across every interleaving of the stamped C-atomic accesses
    the reader must return one of the two published values whole, or
    cleanly fall back (miss/torn/stale) — never a mixed payload."""

    name = "ring-seqlock"
    description = "seqlock ring writer vs reader vs slot retirement"

    VAL_A = {"v": "AAAAAA"}
    VAL_B = {"v": "BBBBBB"}

    def _writer_cls(self) -> Type[RingWriter]:
        return RingWriter

    def setup(self) -> Dict[str, Any]:
        w = self._writer_cls()(slots=1, slot_size=256)
        key = ring_key("read_attr", "/sys/devices/tpu0/serial")
        w.publish(key, self.VAL_A)          # primed: uncontended
        rd = RingReader(w.fd)
        return {"w": w, "rd": rd, "key": key, "obs": []}

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        w, rd, key = state["w"], state["rd"], state["key"]

        def writer() -> None:
            w.publish(key, self.VAL_B)      # retire A, publish B

        def reader() -> None:
            value, status = rd.lookup(key, ttl_s=RING_DEFAULT_TTL_S)
            state["obs"].append((status, value))

        return [("writer", writer), ("reader", reader)]

    def drain(self, state: Dict[str, Any]) -> None:
        state["rd"].close()
        state["w"].close()

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        (status, value), = state["obs"]
        assert status in ("hit", "torn", "miss", "stale"), status
        if status == "hit":
            assert value in (self.VAL_A, self.VAL_B), \
                f"mixed/garbage ring payload: {value!r}"
        else:
            assert value is None


class _TornRingWriter(RingWriter):
    """SEEDED BUG: publishes without the seqlock brackets — the body is
    written in two visible halves under an even, unchanged sequence, so
    a racing reader can validate a mixed payload as a hit."""

    def publish(self, key: bytes, value: dict) -> bool:
        val = _json_bytes(value)
        off = _RING_HEADER_PAD      # slots=1: everything is slot 0
        mm = self._mm
        base = off + _RING_SLOT_HDR.size
        split = max(0, len(val) - 3)
        schedcheck.yield_point("ring.pub.body", key=f"ring.slot.{off}")
        mm[base:base + len(key)] = key
        mm[base + len(key):base + len(key) + split] = val[:split]
        schedcheck.yield_point("ring.pub.body2", key=f"ring.slot.{off}")
        mm[base + len(key) + split:base + len(key) + len(val)] = \
            val[split:]
        (seq,) = struct.unpack_from(">I", mm, off)
        schedcheck.yield_point("ring.pub.seq_even",
                               key=f"ring.slot.{off}")
        _RING_SLOT_HDR.pack_into(mm, off, (seq + 2) & 0xFFFFFFFF,
                                 len(key), len(val), time.monotonic())
        self.published += 1
        return True


class RingTornWriteTwin(RingSeqlock):
    """SEEDED BUG twin: the torn writer above. Weave must find the
    schedule where the reader returns a half-A half-B payload as a
    validated hit."""

    name = "twin-ring-torn-write"
    twin_of = "ring-seqlock"

    def _writer_cls(self) -> Type[RingWriter]:
        return _TornRingWriter


# =====================================================================
# 5. CAS placement commit race
# =====================================================================

def _minimal_fleet_server() -> FleetApiServer:
    """A FleetApiServer stripped to the placement-CAS plane (the full
    __init__ binds a socket and starts a serve thread)."""
    srv = object.__new__(FleetApiServer)
    srv._lock = threading.Lock()
    srv.commit_crossing_s = 0.0
    srv.multiclaims = {}
    srv.multiclaim_log = []
    srv.placement_log = []
    srv.node_placements = {}
    srv.node_placement_gens = {}
    srv.slices = {}
    srv._slices_by_node = {}
    srv.stats = {"placement_conflicts_total": 0,
                 "commit_rounds_total": 0}
    return srv


class PlacementCasRace(Scenario):
    """Two schedulers planned the same chip against the same observed
    placement generation and race their CAS commits. Every schedule
    must produce exactly one winner, a counted clean conflict for the
    loser, and an audit log with exactly one commit."""

    name = "placement-cas-race"
    description = "CAS placement commit race: at most one winner"

    def _make_server(self) -> FleetApiServer:
        return _minimal_fleet_server()

    def setup(self) -> Dict[str, Any]:
        srv = self._make_server()
        for uid in ("claim-a", "claim-b"):
            srv.multiclaim_begin(uid, shape=[1, 1],
                                 shards=[("node-0", ["tpu-chip-0"])])
        return {"srv": srv, "res": {}}

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        srv = state["srv"]

        def committer(uid: str) -> Callable[[], None]:
            def body() -> None:
                state["res"][uid] = srv.multiclaim_commit(
                    uid, observed={"node-0": 0})
            return body

        return [("sched-a", committer("claim-a")),
                ("sched-b", committer("claim-b"))]

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        srv, res = state["srv"], state["res"]
        wins = sorted(u for u, r in res.items() if r.get("committed"))
        assert len(wins) == 1, f"CAS let {len(wins)} committers win: {res}"
        loser = next(u for u in res if u != wins[0])
        assert res[loser]["conflicts"] == ["node-0"], res[loser]
        commits = [e for e in srv.multiclaim_log if e[2] == "commit"]
        assert len(commits) == 1, \
            f"audit log shows {len(commits)} commits"
        assert srv.node_placements["node-0"] == {"tpu-chip-0": wins[0]}
        assert srv.node_placement_gens["node-0"] == 1
        assert srv.stats["placement_conflicts_total"] == 1


class _ToctouFleetServer(FleetApiServer):
    """SEEDED BUG: the CAS check and the apply run in separate lock
    crossings with a schedule point between — both racers can pass the
    check before either applies."""

    def multiclaim_commit_batch(self, commits) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for uid, observed in commits:
            with self._lock:
                rec = self.multiclaims[uid]
                conflicts = sorted({
                    node for node, raws in rec["shards"]
                    if observed.get(node, 0)
                    != self.node_placement_gens.get(node, 0)
                    or any(r in (self.node_placements.get(node) or {})
                           for r in raws)})
            if conflicts:
                with self._lock:
                    self.stats["placement_conflicts_total"] += 1
                    self.multiclaim_log.append(
                        (time.monotonic(), uid, "conflict", conflicts))
                out[uid] = {"committed": False, "conflicts": conflicts,
                            "gens": dict(self.node_placement_gens)}
                continue
            schedcheck.yield_point("twin.cas.toctou", obj=self)
            with self._lock:
                rec["phase"] = "committed"
                self.multiclaim_log.append(
                    (time.monotonic(), uid, "commit", None))
                gens: Dict[str, int] = {}
                for node, raws in rec["shards"]:
                    owners = self.node_placements.setdefault(node, {})
                    for r in raws:
                        owners[r] = uid
                    gen = self.node_placement_gens.get(node, 0) + 1
                    self.node_placement_gens[node] = gen
                    gens[node] = gen
                out[uid] = {"committed": True, "gens": gens}
        return out


class PlacementToctouTwin(PlacementCasRace):
    """SEEDED BUG twin: check/apply split across lock crossings —
    weave must find the double-commit."""

    name = "twin-placement-toctou"
    twin_of = "placement-cas-race"

    def _make_server(self) -> FleetApiServer:
        srv = object.__new__(_ToctouFleetServer)
        srv._lock = threading.Lock()
        srv.commit_crossing_s = 0.0
        srv.multiclaims = {}
        srv.multiclaim_log = []
        srv.placement_log = []
        srv.node_placements = {}
        srv.node_placement_gens = {}
        srv.slices = {}
        srv._slices_by_node = {}
        srv.stats = {"placement_conflicts_total": 0,
                     "commit_rounds_total": 0}
        return srv


# =====================================================================
# 6. circuit-breaker half-open probe race
# =====================================================================

class BreakerHalfOpenProbe(Scenario):
    """A tripped breaker past its cooldown faces two simultaneous
    callers. Exactly one may receive the half-open probe; the loser is
    rejected and counted. (The breaker's injectable clock is bound to
    the virtual clock, and both callers sleep past the cooldown — the
    quiescence-only clock advance makes the window race exact.)"""

    name = "breaker-half-open-probe"
    description = "half-open window: exactly one probe"

    def _make_breaker(self) -> CircuitBreaker:
        return CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05,
                              clock=time.monotonic, name="weave")

    def setup(self) -> Dict[str, Any]:
        br = self._make_breaker()
        br.record_failure()                  # trip: closed -> open
        return {"br": br, "allowed": []}

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        br = state["br"]

        def caller(tag: str) -> Callable[[], None]:
            def body() -> None:
                time.sleep(0.1)              # ride past the cooldown
                state["allowed"].append((tag, br.allow()))
            return body

        return [("probe-a", caller("a")), ("probe-b", caller("b"))]

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        br = state["br"]
        granted = [tag for tag, ok in state["allowed"] if ok]
        assert len(granted) == 1, \
            f"half-open window granted {len(granted)} probes: " \
            f"{state['allowed']}"
        assert br.half_open_rejected == 1, br.snapshot()
        assert br.state == CircuitBreaker.HALF_OPEN
        assert run.clock.advances >= 1, "cooldown never elapsed"


class _LeakyProbeBreaker(CircuitBreaker):
    """SEEDED BUG: the open->half-open transition checks the cooldown
    OUTSIDE the lock, then transitions in a second crossing."""

    def allow(self) -> bool:
        with self._lock:
            st = self._state
            opened = self._opened_at
        if st == self.CLOSED:
            return True
        if st == self.OPEN \
                and self._clock() - opened >= self.reset_timeout_s:
            schedcheck.yield_point("twin.breaker.toctou", obj=self)
            with self._lock:
                self._state = self.HALF_OPEN
                self._probe_owner = threading.get_ident()
            return True
        with self._lock:
            self.rejected += 1
            if st == self.HALF_OPEN:
                self.half_open_rejected += 1
        return False


class BreakerDoubleProbeTwin(BreakerHalfOpenProbe):
    """SEEDED BUG twin: both callers pass the unlocked cooldown check
    before either claims the window — two probes escape."""

    name = "twin-breaker-double-probe"
    twin_of = "breaker-half-open-probe"

    def _make_breaker(self) -> CircuitBreaker:
        return _LeakyProbeBreaker(failure_threshold=1,
                                  reset_timeout_s=0.05,
                                  clock=time.monotonic, name="weave")


# =====================================================================
# 7. LiveAttrReader stat -> pread -> recheck vs entry swap (ABA)
# =====================================================================

class LiveAttrSwapRace(Scenario):
    """The lock-free attr fast path races a file replace + record swap
    + fd close, with the freed fd number deliberately RECYCLED onto an
    unrelated file (os.dup2 — the ABA the record recheck exists for).
    Every schedule must return the old bytes, the new bytes, or fall
    back; the recycled fd's bytes must never escape."""

    name = "liveattr-swap-race"
    description = "LiveAttrReader fast path vs entry swap + fd recycle"

    OLD, NEW, EVIL = b"OLD!", b"NEW!", b"EVIL"

    def _make_reader(self) -> LiveAttrReader:
        return LiveAttrReader()

    def setup(self) -> Dict[str, Any]:
        def mkfile(content: bytes) -> str:
            fd, path = tempfile.mkstemp(prefix="weave-attr-")
            os.write(fd, content)
            os.close(fd)
            return path

        path = mkfile(self.OLD)
        newpath = mkfile(self.NEW)
        decoy_fd = os.open(mkfile(self.EVIL), os.O_RDONLY)
        rd = self._make_reader()
        primed = rd.read("serial", path)
        assert primed == self.OLD
        old_fd = rd._fds["serial"][0]
        return {"rd": rd, "path": path, "newpath": newpath,
                "decoy_fd": decoy_fd, "old_fd": old_fd, "got": []}

    def threads(self, state: Dict[str, Any]
                ) -> List[Tuple[str, Callable[[], None]]]:
        rd = state["rd"]

        def reader() -> None:
            state["got"].append(rd.read("serial", state["path"]))

        def swapper() -> None:
            os.replace(state["newpath"], state["path"])
            state["swapped"] = rd.read("serial", state["path"])
            # the freed fd number comes back as an UNRELATED file — the
            # ABA hazard the fast path's record recheck must survive
            schedcheck.yield_point("attr.fd.recycle", obj=rd)
            os.dup2(state["decoy_fd"], state["old_fd"])

        return [("reader", reader), ("swapper", swapper)]

    def drain(self, state: Dict[str, Any]) -> None:
        for rec in list(state["rd"]._fds.values()):
            try:
                os.close(rec[0])
            except OSError:
                pass
        state["rd"]._fds.clear()
        for fd in (state["decoy_fd"], state["old_fd"]):
            try:
                os.close(fd)
            except OSError:
                pass
        for path in (state["path"], state["newpath"]):
            try:
                os.unlink(path)
            except OSError:
                pass

    def invariant(self, state: Dict[str, Any], run: Any) -> None:
        got, = state["got"]
        assert got in (self.OLD, self.NEW), \
            f"recycled-fd bytes escaped the fast path: {got!r}"
        assert state["swapped"] == self.NEW


class _NoRecheckReader(LiveAttrReader):
    """SEEDED BUG: the fast path without the record recheck — the
    pre-recheck design whose fd-reuse hole the class docstring
    documents."""

    def read(self, key: str, path: str) -> Optional[bytes]:
        schedcheck.yield_point("attr.read.lookup", obj=self, mode="r")
        rec = self._fds.get(key)
        if rec is not None:
            fd, dev, ino = rec
            try:
                st = os.stat(path)
                if (st.st_dev, st.st_ino) == (dev, ino):
                    schedcheck.yield_point("attr.read.pread", obj=self,
                                           mode="r")
                    raw = os.pread(fd, 256, 0)
                    if raw:        # seeded bug: no record recheck
                        return raw
            except OSError:
                pass
        return self._read_slow(key, path, rec)


class LiveAttrAbaTwin(LiveAttrSwapRace):
    """SEEDED BUG twin: drop the record recheck — weave must find the
    stat/swap/recycle/pread schedule where the decoy bytes escape."""

    name = "twin-liveattr-aba"
    twin_of = "liveattr-swap-race"

    def _make_reader(self) -> LiveAttrReader:
        return _NoRecheckReader()


# =====================================================================
# registry
# =====================================================================

SCENARIOS: Dict[str, Type[Scenario]] = {
    s.name: s for s in (
        EpochPublishWaiter,
        CounterShardObserve,
        HistogramAdoptObserve,
        DraGroupCommit,
        DraCommitFailure,
        RingSeqlock,
        PlacementCasRace,
        BreakerHalfOpenProbe,
        LiveAttrSwapRace,
    )}

TWINS: Dict[str, Type[Scenario]] = {
    s.name: s for s in (
        EpochPublishNoNotifyTwin,
        CounterLostUpdateTwin,
        HistogramDoubleAdoptTwin,
        DraAckBeforeDurableTwin,
        RingTornWriteTwin,
        PlacementToctouTwin,
        BreakerDoubleProbeTwin,
        LiveAttrAbaTwin,
    )}
