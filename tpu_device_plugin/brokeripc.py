"""brokeripc — the wire protocol between the serving daemon and the broker.

The privilege-separated broker (broker.py) owns every vfio/sysfs/iommufd
operation; the unprivileged serving daemon reaches them over a unix
socket. This module is the NARROW, VERSIONED framing both sides speak —
deliberately small enough to audit by reading.

Two framings, one request/reply model (round 20):

  v1 (JSON)   frame = MAGIC (b"TDPB") + length (4-byte big-endian)
              + payload (UTF-8 JSON object, <= MAX_FRAME bytes)
  v2 (binary) frame = BIN_MAGIC (b"TDBB") + length (4-byte big-endian)
              + payload (compact varint op-table records — the PR 13
              protobuf wire vocabulary: epoch.encode_varint /
              epoch.encode_delimited; see _FIELD_DEFS)
  fds         passed as SCM_RIGHTS ancillary data ON the frame's first
              send/recv (socket.send_fds / socket.recv_fds; at most
              MAX_FDS per frame). SCM_RIGHTS is reserved for ACTUAL fd
              passage — open_node's device fd and the one-time response
              ring handover at handshake — never for framing tricks.

The framing is NEGOTIATED at `hello` (always a v1 JSON frame, so any
peer can read it): the client offers its version, the broker answers
with the negotiated one. Both at >= 2 → every subsequent frame on the
connection is binary; a v1 peer on either side keeps JSON framing for
the whole connection; an unsupported version is refused BEFORE any op
is served, exactly as before. The two framings decode to the SAME
request/reply dicts — broker.py's dispatch, audit ring and span
plumbing are framing-blind (tests/test_broker.py pins the audit entries
byte-identical across framings).

Every request object carries:
  op      — the operation name (broker.py's dispatch key; on the binary
            framing a 1-byte opcode from the compact op table)
  seq     — a client-assigned sequence number echoed in the reply, so a
            desynced connection is detected instead of mis-pairing
  span    — the caller's active flight-recorder span context (op + seq +
            thread), so every privilege crossing in the broker's audit
            ring links back to the daemon-side trace (/debug/flight)

and every reply carries `ok` (bool), `seq` (echoed), and either result
fields or `error` + `kind`.

Batched crossings: a `batch` request carries up to MAX_BATCH_OPS fd-free
sub-operations in its `ops` field and its reply pairs each with a typed
sub-result in `results` — one round trip for a whole claim's
revalidation + readlinks or a whole health cycle's probes, with
PARTIAL-FAILURE semantics (one refused sub-op never poisons the batch;
a dead broker types EVERY sub-result as unavailable).

The response ring (spawn mode): the broker mmaps a small file-backed
slot array (RingWriter) and hands the fd to the client ONCE at
handshake. After serving a hot read-only op (config probes, readlinks,
vendor/attr reads) over the socket, the broker PUBLISHES the result
into the slot keyed by (op, path); the client (RingReader) consults the
ring before paying a socket round trip. Each slot is seqlock-stamped
(odd = write in progress; changed = torn) and publish-timestamped, so a
torn or stale read is DETECTED and falls back to the socket path — the
ring can serve bounded-staleness reads or nothing, never garbage.

Robustness rules, enforced on BOTH sides:
  - a frame without a known magic, or longer than MAX_FRAME, is a
    protocol error: the receiver raises (server side: replies
    kind="protocol" then closes) — a corrupt length prefix must never
    turn into a multi-GB allocation;
  - short reads (peer died mid-frame) raise BrokerConnectionLost, the
    typed signal broker.BrokerClient turns into "typed unavailable"
    claim errors;
  - received fds are closed on EVERY decode-error path (bad magic,
    oversized frame, malformed payload, short read) — never leaked.

No threading in this module: callers serialize access to a connection
(broker.SocketBrokerClient holds one plain lock around each
request/reply pair; the broker serves each connection on its own
thread). The ring writer has one writer (the broker process) by
construction; readers are wait-free.
"""

from __future__ import annotations

import json
import mmap
import os
import socket
import struct
import tempfile
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import schedcheck
from .epoch import encode_delimited, encode_varint

MAGIC = b"TDPB"          # v1 JSON framing
BIN_MAGIC = b"TDBB"      # v2 binary framing (negotiated at hello)
PROTOCOL_VERSION = 2
SUPPORTED_VERSIONS = frozenset({1, 2})
# one frame must fit a batched revalidation for a large claim plus audit
# context, and nothing else — 1 MiB is orders of magnitude above both
MAX_FRAME = 1 << 20
MAX_FDS = 8
# per-batch op cap: a whole claim's revalidation or a whole probe
# cycle fits in a few dozen sub-ops; anything larger is a bug (or an
# attempt to wedge the sequential broker behind one giant crossing)
MAX_BATCH_OPS = 128

_LEN = struct.Struct(">I")
_HEADER_SIZE = len(MAGIC) + _LEN.size


class BrokerProtocolError(Exception):
    """The peer spoke something that is not this protocol (bad magic,
    oversized/underflowing frame, malformed payload, non-object payload,
    mismatched seq). The connection is unusable afterwards."""


class BrokerConnectionLost(Exception):
    """The peer vanished mid-conversation (EOF, ECONNRESET, EPIPE) — the
    kill -9 signal the serving daemon maps to typed-unavailable errors."""


# ------------------------------------------------------ binary op table
#
# The compact op table (round 20): every known operation gets a 1-byte
# opcode and every known request/reply field a fixed tag + value kind,
# so a hot crossing encodes to a handful of varint records instead of a
# JSON object — and the static part of a request can be PRE-SERIALIZED
# once and reused (RequestEncoder). Kinds:
#   o  opcode (varint, OP_CODE table; unknown names ride the catch-all)
#   i  signed int (zigzag varint)        u  unsigned int (varint)
#   b  bool (varint 0/1)                 s  UTF-8 string (delimited)
#   j  JSON value (delimited)            B  repeated nested body (delimited)
#   t  trace-span context (delimited; op/seq/trace_id/span_id joined by
#      US (0x1f) — the one per-crossing dict, so it gets a codec that
#      skips the nested-JSON round trip; anything but the canonical
#      span_context() shape rides the catch-all)
# Anything else — unknown keys, wrong-typed values, empty B lists —
# rides a _TAG_OTHER record carrying JSON [key, value], so the binary
# framing can carry EVERY dict the JSON framing can: the two framings
# decode to identical requests by construction.

OPS = ("hello", "node_exists", "open_node", "read_attr", "read_link",
       "write_sysfs", "probe_config", "probe_node", "chip_alive",
       "chip_diagnostics", "revalidate", "stats", "shutdown", "batch")
OP_CODE: Dict[str, int] = {name: i + 1 for i, name in enumerate(OPS)}
OP_NAME: Dict[int, str] = {i + 1: name for i, name in enumerate(OPS)}

_FIELD_DEFS: Tuple[Tuple[str, int, str], ...] = (
    ("op", 1, "o"),
    ("seq", 2, "i"),
    ("span", 3, "t"),
    ("path", 4, "s"),
    ("data", 5, "s"),
    ("ok", 6, "b"),
    ("error", 7, "s"),
    ("kind", 8, "s"),
    ("version", 9, "i"),
    ("pid", 10, "u"),
    ("exists", 11, "b"),
    ("target", 12, "s"),
    ("verdict", 13, "i"),
    ("alive", 14, "b"),
    ("bits", 15, "i"),
    ("link", 16, "s"),
    ("pci_base", 17, "s"),
    ("bdf", 18, "s"),
    ("node", 19, "s"),
    ("vendors", 20, "j"),
    ("pairs", 21, "j"),
    ("errors", 22, "j"),
    ("broker", 23, "j"),
    ("ops", 24, "B"),
    ("results", 25, "B"),
    ("ring", 26, "b"),
    ("ring_slots", 27, "u"),
    ("ring_slot_size", 28, "u"),
    ("key", 29, "s"),
)
_TAG_OTHER = 31
_FIELD_BY_KEY = {key: (tag, kind) for key, tag, kind in _FIELD_DEFS}
_FIELD_BY_TAG = {tag: (key, kind) for key, tag, kind in _FIELD_DEFS}

# precompute each field's record prefix (tag word varint — one byte for
# tags <= 15, two for the rest) so the hot encoder does a dict lookup,
# not an encode_varint call
_PFX_VARINT = {key: encode_varint(tag << 3) for key, tag, _k in _FIELD_DEFS}
_PFX_DELIM = {key: encode_varint((tag << 3) | 2)
              for key, tag, _k in _FIELD_DEFS}

_JSON_SEP = (",", ":")

# the two per-call tail records RequestEncoder appends on every crossing
_SEQ_PFX = _PFX_VARINT["seq"]
_SPAN_PFX = _PFX_DELIM["span"]


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n << 1) - 1)


def _unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """Decode one base-128 varint at `pos` → (value, new pos)."""
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise BrokerProtocolError("truncated varint in binary frame")
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise BrokerProtocolError("varint overflow in binary frame")


def _json_bytes(value: object) -> bytes:
    return json.dumps(value, separators=_JSON_SEP,
                      sort_keys=True).encode("utf-8")


_US = "\x1f"


def _encode_span(span: object) -> Optional[bytes]:
    """The canonical span_context() dict → compact US-joined payload, or
    None when the value is not that exact shape (then the catch-all
    record carries it with full JSON fidelity)."""
    if not isinstance(span, dict):
        return None
    op = span.get("op")
    seq = span.get("seq")
    if not isinstance(op, str) or _US in op \
            or not isinstance(seq, int) or isinstance(seq, bool):
        return None
    tid = span.get("trace_id")
    sid = span.get("span_id")
    if tid is None and sid is None:
        if len(span) != 2:
            return None
        text = op + _US + str(seq)
    else:
        if len(span) != 4 or not isinstance(tid, str) \
                or not isinstance(sid, str) or _US in tid or _US in sid:
            return None
        text = op + _US + str(seq) + _US + tid + _US + sid
    return text.encode("utf-8")


def _decode_span(chunk: bytes) -> Dict[str, Any]:
    parts = chunk.decode("utf-8").split(_US)
    if len(parts) == 2:
        return {"op": parts[0], "seq": int(parts[1])}
    if len(parts) == 4:
        return {"op": parts[0], "seq": int(parts[1]),
                "trace_id": parts[2], "span_id": parts[3]}
    raise ValueError(f"span context with {len(parts)} segments")


def encode_body(obj: Dict[str, Any]) -> bytes:
    """One request/reply dict → compact binary records (no frame header).
    Total: decode_body(encode_body(obj)) == obj for every JSON-able dict
    (modulo None-valued keys, which both framings treat as absent)."""
    parts: List[bytes] = []
    for key, value in obj.items():
        if value is None:
            continue
        spec = _FIELD_BY_KEY.get(key)
        tag, kind = spec if spec is not None else (None, None)
        if kind == "o" and isinstance(value, str) and value in OP_CODE:
            parts.append(_PFX_VARINT[key]
                         + encode_varint(OP_CODE[value]))
        elif kind == "i" and isinstance(value, int) \
                and not isinstance(value, bool):
            parts.append(_PFX_VARINT[key]
                         + encode_varint(_zigzag(value)))
        elif kind == "u" and isinstance(value, int) \
                and not isinstance(value, bool) and value >= 0:
            parts.append(_PFX_VARINT[key] + encode_varint(value))
        elif kind == "b" and isinstance(value, bool):
            parts.append(_PFX_VARINT[key]
                         + encode_varint(1 if value else 0))
        elif kind == "s" and isinstance(value, str):
            raw = value.encode("utf-8")
            parts.append(_PFX_DELIM[key] + encode_varint(len(raw)) + raw)
        elif kind == "t" and (raw := _encode_span(value)) is not None:
            parts.append(_PFX_DELIM[key] + encode_varint(len(raw)) + raw)
        elif kind == "j":
            raw = _json_bytes(value)
            parts.append(_PFX_DELIM[key] + encode_varint(len(raw)) + raw)
        elif kind == "B" and isinstance(value, (list, tuple)) and value \
                and all(isinstance(v, dict) for v in value):
            for sub in value:
                parts.append(encode_delimited(tag, encode_body(sub)))
        else:
            # catch-all: unknown key or a value this field's compact
            # kind cannot carry — full fidelity beats compactness
            parts.append(encode_delimited(
                _TAG_OTHER, _json_bytes([key, value])))
    return b"".join(parts)


def decode_body(payload: bytes) -> Dict[str, Any]:
    """Binary records → the request/reply dict. Unknown tags are skipped
    by wire type (forward-compatible within v2); malformed records raise
    BrokerProtocolError."""
    obj: Dict[str, Any] = {}
    pos = 0
    n = len(payload)
    while pos < n:
        tagword, pos = _read_varint(payload, pos)
        tag, wire = tagword >> 3, tagword & 7
        if wire == 0:
            value, pos = _read_varint(payload, pos)
            spec = _FIELD_BY_TAG.get(tag)
            if spec is None:
                continue
            key, kind = spec
            if kind == "o":
                name = OP_NAME.get(value)
                if name is None:
                    raise BrokerProtocolError(
                        f"unknown opcode {value} in binary frame")
                obj[key] = name
            elif kind == "i":
                obj[key] = _unzigzag(value)
            elif kind == "b":
                obj[key] = bool(value)
            elif kind == "u":
                obj[key] = value
            else:
                raise BrokerProtocolError(
                    f"field {key!r} arrived as varint, expected "
                    f"delimited (kind {kind!r})")
        elif wire == 2:
            length, pos = _read_varint(payload, pos)
            if length > n - pos:
                raise BrokerProtocolError(
                    "truncated delimited record in binary frame")
            chunk = payload[pos:pos + length]
            pos += length
            try:
                if tag == _TAG_OTHER:
                    pair = json.loads(chunk.decode("utf-8"))
                    if not (isinstance(pair, list) and len(pair) == 2
                            and isinstance(pair[0], str)):
                        raise BrokerProtocolError(
                            "malformed catch-all record")
                    obj[pair[0]] = pair[1]
                    continue
                spec = _FIELD_BY_TAG.get(tag)
                if spec is None:
                    continue
                key, kind = spec
                if kind == "s":
                    obj[key] = chunk.decode("utf-8")
                elif kind == "t":
                    obj[key] = _decode_span(chunk)
                elif kind == "j":
                    obj[key] = json.loads(chunk.decode("utf-8"))
                elif kind == "B":
                    obj.setdefault(key, []).append(decode_body(chunk))
                else:
                    raise BrokerProtocolError(
                        f"field {key!r} arrived delimited, expected "
                        f"varint (kind {kind!r})")
            except (UnicodeDecodeError, ValueError) as exc:
                raise BrokerProtocolError(
                    f"malformed binary record (tag {tag}): {exc}") from exc
        else:
            raise BrokerProtocolError(
                f"unsupported wire type {wire} in binary frame")
    return obj


class RequestEncoder:
    """Pre-serialized binary request frames (the RPCAcc move, applied to
    the broker boundary): the STATIC field segment of a request — opcode
    plus its scalar operands, which repeat across crossings (the same
    probe path every health cycle, the same readlink every prepare) —
    is encoded once and cached; a crossing appends only the per-call
    seq + span records and the frame header. The cache is a small LRU
    keyed by the static field items; unhashable operands (batch sub-op
    lists) simply encode fresh."""

    def __init__(self, maxsize: int = 256) -> None:
        self._cache: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._maxsize = maxsize
        self.static_hits = 0

    def encode_frame(self, obj: Dict[str, Any]) -> bytes:
        # key on the UNSORTED item tuple: hot requests are built at one
        # construction site, so their key order repeats; two orderings
        # of the same operands just occupy two cache slots
        static_items = tuple(
            (k, v) for k, v in obj.items() if k != "seq" and k != "span")
        static: Optional[bytes] = None
        key: Optional[tuple] = None
        try:
            static = self._cache.get(static_items)
            key = static_items
        except TypeError:
            pass   # unhashable operand (lists/dicts): encode fresh
        if static is None:
            static = encode_body(dict(static_items))
            if key is not None:
                self._cache[key] = static
                self._cache.move_to_end(key)
                if len(self._cache) > self._maxsize:
                    self._cache.popitem(last=False)
        else:
            self.static_hits += 1
        # the per-call tail is hand-rolled — no dict build, no generic
        # field walk — because it runs once per crossing
        payload = static
        seq = obj.get("seq")
        if seq is not None:
            payload += _SEQ_PFX + encode_varint(_zigzag(seq))
        span = obj.get("span")
        if span is not None:
            raw = _encode_span(span)
            if raw is not None:
                payload += _SPAN_PFX + encode_varint(len(raw)) + raw
            else:
                payload += encode_delimited(
                    _TAG_OTHER, _json_bytes(["span", span]))
        if len(payload) > MAX_FRAME:
            raise BrokerProtocolError(
                f"frame payload {len(payload)} bytes exceeds MAX_FRAME "
                f"{MAX_FRAME}")
        return BIN_MAGIC + _LEN.pack(len(payload)) + payload


# ---------------------------------------------------------- frame codec

def _encode(obj: Dict[str, Any], binary: bool = False) -> bytes:
    if binary:
        payload = encode_body(obj)
        magic = BIN_MAGIC
    else:
        payload = _json_bytes(obj)
        magic = MAGIC
    if len(payload) > MAX_FRAME:
        raise BrokerProtocolError(
            f"frame payload {len(payload)} bytes exceeds MAX_FRAME "
            f"{MAX_FRAME}")
    return magic + _LEN.pack(len(payload)) + payload


def send_frame(sock: socket.socket, obj: Dict[str, Any],
               fds: Tuple[int, ...] = (), binary: bool = False) -> None:
    """Send one frame; `fds` ride as SCM_RIGHTS on the first byte."""
    send_encoded(sock, _encode(obj, binary=binary), fds=fds)


def send_encoded(sock: socket.socket, data: bytes,
                 fds: Tuple[int, ...] = ()) -> None:
    """Send pre-encoded frame bytes (RequestEncoder output) — the
    fast-path twin of send_frame."""
    try:
        if fds:
            if len(fds) > MAX_FDS:
                raise BrokerProtocolError(
                    f"{len(fds)} fds exceed MAX_FDS {MAX_FDS}")
            socket.send_fds(sock, [data], list(fds))
        else:
            sock.sendall(data)
    except (BrokenPipeError, ConnectionResetError, OSError) as exc:
        raise BrokerConnectionLost(f"peer gone during send: {exc}") from exc


def _recv_exact(sock: socket.socket, n: int,
                first: bytes = b"") -> bytes:
    buf = first
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (ConnectionResetError, OSError) as exc:
            raise BrokerConnectionLost(
                f"peer gone during recv: {exc}") from exc
        if not chunk:
            raise BrokerConnectionLost("peer closed mid-frame"
                                       if buf else "peer closed")
        buf += chunk
    return buf


def recv_frame(sock: socket.socket, want_fds: int = 0,
               ) -> Tuple[dict, List[int]]:
    """Receive one frame → (object, fds); framing auto-detected."""
    obj, fds, _binary = recv_frame_ex(sock, want_fds=want_fds)
    return obj, fds


def recv_frame_ex(sock: socket.socket, want_fds: int = 0,
                  ) -> Tuple[dict, List[int], bool]:
    """Receive one frame → (object, fds, was_binary). `want_fds` is the
    MAXIMUM fd count the caller will accept; extras are closed, never
    leaked. Received fds are closed on EVERY decode-error path — a peer
    that passes an fd and then speaks garbage must not leak it into this
    process (the round-20 regression pin)."""
    fds: List[int] = []
    if want_fds > 0:
        # the ancillary data arrives with the first data bytes; ask for
        # the whole header in one recv_fds call, then drain the rest
        try:
            head, received, _flags, _addr = socket.recv_fds(
                sock, _HEADER_SIZE, min(want_fds, MAX_FDS))
        except (ConnectionResetError, OSError) as exc:
            raise BrokerConnectionLost(
                f"peer gone during recv: {exc}") from exc
        if not head:
            raise BrokerConnectionLost("peer closed")
        fds = list(received)
    else:
        head = b""
    # EVERYTHING after the first fd-bearing recv runs under the close-on
    # -error guard: a short read completing the header, a bad magic, an
    # oversized length, a malformed payload — each closes received fds
    # before raising
    try:
        header = _recv_exact(sock, _HEADER_SIZE, first=head)
        magic = header[:len(MAGIC)]
        if magic == MAGIC:
            binary = False
        elif magic == BIN_MAGIC:
            binary = True
        else:
            raise BrokerProtocolError(f"bad frame magic {magic!r}")
        (length,) = _LEN.unpack(header[len(MAGIC):])
        if length > MAX_FRAME:
            raise BrokerProtocolError(
                f"frame length {length} exceeds MAX_FRAME {MAX_FRAME}")
        payload = _recv_exact(sock, length)
        if binary:
            obj = decode_body(payload)
        else:
            try:
                obj = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, ValueError) as exc:
                raise BrokerProtocolError(
                    f"malformed frame payload: {exc}") from exc
        if not isinstance(obj, dict):
            raise BrokerProtocolError(
                f"frame payload is {type(obj).__name__}, not an object")
    except Exception:
        close_fds(fds)
        raise
    return obj, fds, binary


def close_fds(fds: Iterable[int]) -> None:
    """Best-effort close of received fds (error paths, unwanted extras)."""
    for fd in fds:
        try:
            os.close(fd)
        except OSError:
            pass


# ------------------------------------------------------------ handshake

def hello_request(seq: int = 0, version: int = PROTOCOL_VERSION,
                  ring: bool = False) -> Dict[str, Any]:
    req: Dict[str, Any] = {"op": "hello", "seq": seq, "version": version}
    if ring:
        req["ring"] = True
    return req


def check_hello_reply(reply: Dict[str, Any],
                      requested: int = PROTOCOL_VERSION) -> int:
    """Raise BrokerProtocolError unless the broker accepted a version we
    speak; returns the NEGOTIATED version (<= requested). A v1 broker
    answering version 1 to a v2 client is a valid downgrade — the client
    keeps JSON framing; anything outside SUPPORTED_VERSIONS (or above
    what we asked for) is a refusal."""
    if not reply.get("ok"):
        raise BrokerProtocolError(
            f"broker refused handshake: {reply.get('error', 'unknown')} "
            f"(kind={reply.get('kind')!r}, broker version "
            f"{reply.get('version')!r}, ours {PROTOCOL_VERSION})")
    version = reply.get("version")
    if not isinstance(version, int) or version not in SUPPORTED_VERSIONS \
            or version > requested:
        raise BrokerProtocolError(
            f"broker answered version {version!r}, ours "
            f"{PROTOCOL_VERSION} (requested {requested})")
    return version


def span_context() -> Optional[dict]:
    """The caller's active flight-recorder span as a small JSON-able
    context (None outside any span, or with tracing disabled). Carried on
    every request so the broker's audit ring links each privilege
    crossing back to the daemon-side trace. Since round 17 the context
    is the FULL trace-propagation carrier — `trace_id`/`span_id` ride
    along (counted as one propagation), so the broker process opens its
    own linked `broker.serve` span and its audit-ring entries join the
    caller's fleet trace (`/debug/fleet/trace?trace=`)."""
    from . import trace
    stack = getattr(trace._tls, "stack", None)
    if not stack:
        return None
    span = stack[-1]
    out = {"op": span.op, "seq": span.seq}
    ctx = trace.propagate_context()
    if ctx is not None:
        out["trace_id"] = ctx["trace_id"]
        out["span_id"] = ctx["span_id"]
    return out


# -------------------------------------------------------- response ring
#
# The shared-memory response ring (round 20): a file-backed slot array
# the broker WRITES and the serving daemon READS, handed over once via
# SCM_RIGHTS at handshake. Layout:
#
#   header  RING_MAGIC + u32 slot_count + u32 slot_size (+ pad to 64)
#   slot    u32 seqlock | u32 key_len | u32 val_len | f64 publish_ts
#           | key bytes | value bytes (JSON)          (fixed slot_size)
#
# Writer protocol (single writer — the broker process): bump the seqlock
# ODD, write header + key + value, bump it EVEN. Reader protocol: read
# seqlock (odd → torn), read the body, re-read the seqlock (changed →
# torn), compare the key (hash-slot collision → miss), check the publish
# timestamp against the caller's TTL (CLOCK_MONOTONIC is system-wide on
# Linux, so the stamp is comparable across the two processes). Torn,
# stale and missed reads all fall back to the socket path — detected,
# counted, never wrong. CPython cannot order individual stores, but the
# seqlock brackets make ANY interleaving detectable: a reader either
# sees both brackets unchanged (consistent body) or retries.

RING_MAGIC = b"TDPR"
RING_SLOTS = 512
RING_SLOT_SIZE = 512
RING_DEFAULT_TTL_S = 0.5
_RING_HEADER = struct.Struct(">4sII")
_RING_HEADER_PAD = 64
_RING_SLOT_HDR = struct.Struct(">IIId")


def ring_key(op: str, path: str) -> bytes:
    return f"{op}\x00{path}".encode("utf-8", "surrogatepass")


class RingWriter:
    """The broker-side (single-writer) half of the response ring."""

    def __init__(self, slots: int = RING_SLOTS,
                 slot_size: int = RING_SLOT_SIZE) -> None:
        if slots <= 0 or slot_size <= _RING_SLOT_HDR.size:
            raise ValueError("ring geometry too small")
        self.slots = slots
        self.slot_size = slot_size
        self.published = 0
        self.skipped_oversize = 0
        size = _RING_HEADER_PAD + slots * slot_size
        try:
            fd = os.memfd_create("tdp-broker-ring")
        except (AttributeError, OSError):
            # pre-memfd kernel / container: an unlinked temp file is the
            # same thing with a directory-entry lifetime of microseconds
            fd, path = tempfile.mkstemp(prefix="tdp-broker-ring-")
            os.unlink(path)
        os.ftruncate(fd, size)
        self.fd = fd
        self._mm = mmap.mmap(fd, size)
        _RING_HEADER.pack_into(self._mm, 0, RING_MAGIC, slots, slot_size)

    def publish(self, key: bytes, value: Dict[str, Any]) -> bool:
        """Publish one (key, value) into its hash slot; False when the
        entry cannot fit (counted, never truncated)."""
        val = _json_bytes(value)
        if _RING_SLOT_HDR.size + len(key) + len(val) > self.slot_size:
            self.skipped_oversize += 1
            return False
        off = _RING_HEADER_PAD + (zlib.crc32(key) % self.slots) \
            * self.slot_size
        mm = self._mm
        (seq,) = struct.unpack_from(">I", mm, off)
        seq_odd = (seq + 1) & 0xFFFFFFFF
        if not seq_odd & 1:   # heal an even+1 landing even (wrap)
            seq_odd = (seq_odd + 1) & 0xFFFFFFFF
        schedcheck.yield_point("ring.pub.seq_odd", key=f"ring.slot.{off}")
        struct.pack_into(">I", mm, off, seq_odd)
        _RING_SLOT_HDR.pack_into(mm, off, seq_odd, len(key), len(val),
                                 time.monotonic())
        base = off + _RING_SLOT_HDR.size
        schedcheck.yield_point("ring.pub.body", key=f"ring.slot.{off}")
        mm[base:base + len(key)] = key
        mm[base + len(key):base + len(key) + len(val)] = val
        schedcheck.yield_point("ring.pub.seq_even", key=f"ring.slot.{off}")
        struct.pack_into(">I", mm, off, (seq_odd + 1) & 0xFFFFFFFF)
        self.published += 1
        return True

    def stats(self) -> Dict[str, Any]:
        return {"slots": self.slots, "slot_size": self.slot_size,
                "published_total": self.published,
                "skipped_oversize_total": self.skipped_oversize}

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        try:
            os.close(self.fd)
        except OSError:
            pass


class RingReader:
    """The daemon-side (wait-free) half: maps the fd received at
    handshake read-only and serves seqlock-validated lookups. The fd can
    be closed by the caller after construction — the mapping survives."""

    def __init__(self, fd: int) -> None:
        size = os.fstat(fd).st_size
        if size < _RING_HEADER_PAD:
            raise BrokerProtocolError("response ring file too small")
        self._mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        magic, slots, slot_size = _RING_HEADER.unpack_from(self._mm, 0)
        if magic != RING_MAGIC or slots <= 0 \
                or slot_size <= _RING_SLOT_HDR.size \
                or _RING_HEADER_PAD + slots * slot_size > size:
            self._mm.close()
            raise BrokerProtocolError("response ring header invalid")
        self.slots = slots
        self.slot_size = slot_size

    def lookup(self, key: bytes,
               ttl_s: float = RING_DEFAULT_TTL_S
               ) -> Tuple[Optional[dict], str]:
        """→ (value, "hit") or (None, "miss" | "torn" | "stale"). Torn
        and stale readers fall back to the socket path — the ring serves
        bounded-staleness values or nothing."""
        mm = self._mm
        off = _RING_HEADER_PAD + (zlib.crc32(key) % self.slots) \
            * self.slot_size
        schedcheck.yield_point("ring.read.s1", mode="r",
                               key=f"ring.slot.{off}")
        (s1,) = struct.unpack_from(">I", mm, off)
        if s1 == 0:
            return None, "miss"
        if s1 & 1:
            return None, "torn"
        schedcheck.yield_point("ring.read.hdr", mode="r",
                               key=f"ring.slot.{off}")
        _seq, key_len, val_len, ts = _RING_SLOT_HDR.unpack_from(mm, off)
        if _RING_SLOT_HDR.size + key_len + val_len > self.slot_size:
            return None, "torn"
        base = off + _RING_SLOT_HDR.size
        schedcheck.yield_point("ring.read.body", mode="r",
                               key=f"ring.slot.{off}")
        body = bytes(mm[base:base + key_len + val_len])
        schedcheck.yield_point("ring.read.s2", mode="r",
                               key=f"ring.slot.{off}")
        (s2,) = struct.unpack_from(">I", mm, off)
        if s2 != s1:
            return None, "torn"
        if body[:key_len] != key:
            return None, "miss"
        if time.monotonic() - ts > ttl_s:
            return None, "stale"
        try:
            return json.loads(body[key_len:].decode("utf-8")), "hit"
        except (UnicodeDecodeError, ValueError):
            return None, "torn"

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
