"""Kubelet devicemanager conformance: the real daemon vs the kubelet's rules.

Drives `python -m tpu_device_plugin` (the DaemonSet process) through
tests/kubelet_sim.py, which implements the kubelet SIDE of the v1beta1
protocol — registration validation, dial-back, a held ListAndWatch stream
backing allocatable, preferred-allocation consultation, and devicemanager
admission bookkeeping (VERDICT r2 next-item #3: the kubeletapi wiring was
previously only exercised against this repo's own one-directional stubs).

The true real-kubelet check is the kind-based nightly job
(.github/workflows/e2e.yml); this suite is its no-cluster approximation.
"""

import json
import os
import signal
import subprocess
import sys
import time

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost
from tests.kubelet_sim import ConformanceError, DeviceManagerSim
from tpu_device_plugin.config import Config

V5E = "cloud-tpus.google.com/v5e"
VHALF = "cloud-tpus.google.com/TPU_vhalf"


@pytest.fixture
def node(short_root, tmp_path):
    """(sim, host, cfg, proc): a running daemon + devicemanager sim."""
    host = FakeHost(short_root)
    for i in range(8):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i), numa_node=i // 4))
    host.add_mdev("conf-uuid-0", "TPU vhalf", "0000:00:04.0",
                  iommu_group="31")
    host.add_mdev("conf-uuid-1", "TPU vhalf", "0000:00:05.0",
                  iommu_group="32")
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    sim = DeviceManagerSim(cfg.device_plugin_path)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_device_plugin", "--root", host.root,
         "--rediscovery-seconds", "0.5"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        yield sim, host, cfg, proc
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        sim.stop()


def test_registration_and_allocatable(node):
    sim, host, cfg, proc = node
    assert sim.wait_for_resource(V5E)
    assert sim.wait_for_resource(VHALF)
    assert not sim.rejections
    assert sim.wait_for_allocatable(V5E, 8)
    assert sim.wait_for_allocatable(VHALF, 2)
    # options contract: passthrough advertises preferred allocation
    assert sim.endpoints[V5E].options.get_preferred_allocation_available


def test_admission_lifecycle_and_exhaustion(node):
    sim, host, cfg, proc = node
    assert sim.wait_for_allocatable(V5E, 8)
    ids1, resp1 = sim.admit_pod(V5E, 4)
    env = dict(resp1.container_responses[0].envs)
    key = "PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V5E"
    assert sorted(env[key].split(",")) == sorted(ids1)
    # vfio cdev + one group per chip (one chip per group on this host)
    assert len(resp1.container_responses[0].devices) == 5

    ids2, _ = sim.admit_pod(V5E, 4)
    assert not set(ids1) & set(ids2)
    with pytest.raises(ConformanceError, match="insufficient"):
        sim.admit_pod(V5E, 1)
    sim.release_pod(V5E, ids1)
    ids3, _ = sim.admit_pod(V5E, 2)
    assert set(ids3) <= set(ids1)


def test_unknown_device_allocate_fails_cleanly(node):
    """A kubelet sending a stale id gets INVALID_ARGUMENT, not a hang."""
    from tpu_device_plugin import kubeletapi as api
    from tpu_device_plugin.kubeletapi import pb
    sim, host, cfg, proc = node
    assert sim.wait_for_resource(V5E)
    ep = sim.endpoints[V5E]
    with pytest.raises(grpc.RpcError) as exc:
        ep.stub.Allocate(pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(devices_ids=["0000:ff:00.0"])]),
            timeout=5)
    assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # pool untouched: full admission still possible afterwards
    ids, _ = sim.admit_pod(V5E, 8)
    assert len(ids) == 8


def test_health_flip_updates_allocatable(node):
    sim, host, cfg, proc = node
    assert sim.wait_for_allocatable(V5E, 8)
    host.remove_vfio_group("11")
    assert sim.wait_for_allocatable(V5E, 7, timeout=20)
    # recreate -> recovers
    host._write(os.path.join(host.devfs, "vfio", "11"), "")
    assert sim.wait_for_allocatable(V5E, 8, timeout=20)


def test_vtpu_admission_prefers_same_parent_packing(node):
    sim, host, cfg, proc = node
    assert sim.wait_for_allocatable(VHALF, 2)
    ids, resp = sim.admit_pod(VHALF, 2)
    assert sorted(ids) == ["conf-uuid-0", "conf-uuid-1"]
    env = dict(resp.container_responses[0].envs)
    assert "MDEV_PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_TPU_VHALF" in env


def test_reregistration_after_kubelet_restart(node):
    """Kubelet restart (socket vanishes) -> plugin re-registers; the sim
    replaces the endpoint like the real devicemanager."""
    sim, host, cfg, proc = node
    assert sim.wait_for_resource(V5E)
    first_updates = sim.endpoints[V5E].updates
    # simulate kubelet restart: a restarting kubelet wipes its
    # device-plugins dir, removing every plugin socket — THAT removal is
    # the restart signal the plugin watches (reference :677-687)
    sim.stop()
    for name in os.listdir(cfg.device_plugin_path):
        if name.endswith(".sock"):
            os.unlink(os.path.join(cfg.device_plugin_path, name))
    sim2 = DeviceManagerSim(cfg.device_plugin_path)
    try:
        assert sim2.wait_for_resource(V5E, timeout=30)
        assert sim2.wait_for_allocatable(V5E, 8, timeout=20)
        ids, _ = sim2.admit_pod(V5E, 1)
        assert len(ids) == 1
    finally:
        sim2.stop()
    assert first_updates >= 1
