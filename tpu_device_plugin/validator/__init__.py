"""Guest-side slice validator — the JAX/TPU compute component.

The host plugin's job ends when a VMI boots with its VFIO groups attached;
proof that the slice actually *works* comes from inside the guest. This
package is that proof: it enumerates `jax.devices()`, builds a `Mesh` shaped
like the allocated slice, and runs an SPMD transformer burn-in whose matmuls
exercise the MXU and whose gradient reduction exercises ICI collectives. Run
it in the guest right after boot:

    python -m tpu_device_plugin.validator

It measures the north-star metric (process start → `jax.devices()` visible →
first compiled step) and reports per-chip matmul throughput, mirroring the
acceptance-test role NVML/DCGM diagnostics play on GPU nodes (the reference
plugin itself has no guest-side validation — README.md:208 lists health
improvement as a TODO; this closes that gap TPU-first).
"""

from .mesh import infer_mesh_shape, slice_mesh  # noqa: F401
from .workload import ModelConfig, build_workload  # noqa: F401
