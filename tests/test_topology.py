"""ICI topology: coordinate assignment and 3-tier preferred allocation."""

import pytest

from tpu_device_plugin.naming import GenerationInfo
from tpu_device_plugin.topology import (
    AllocatableDevice,
    MustIncludeTooLarge,
    assign_coords,
    preferred_allocation,
)

V5E = GenerationInfo("v5e", 8, (2, 4))
V4 = GenerationInfo("v4", 4, (2, 2, 1))


def bdfs(n, start=4):
    return [f"0000:00:{i:02x}.0" for i in range(start, start + n)]


def test_assign_coords_lexicographic():
    ids = bdfs(4)
    coords = assign_coords(ids, V4)
    assert coords[ids[0]] == (0, 0, 0)
    assert coords[ids[1]] == (0, 1, 0)
    assert coords[ids[2]] == (1, 0, 0)
    assert coords[ids[3]] == (1, 1, 0)


def test_assign_coords_hints_win():
    ids = bdfs(2)
    coords = assign_coords(ids, V4, hints={ids[1]: (1, 1, 0)})
    assert coords[ids[1]] == (1, 1, 0)
    assert coords[ids[0]] == (0, 0, 0)  # first free slot


def test_assign_coords_overflow_gets_none():
    ids = bdfs(5)
    coords = assign_coords(ids, V4)
    assert sum(1 for c in coords.values() if c is None) == 1


def _v5e_devices():
    ids = bdfs(8)
    coords = assign_coords(ids, V5E)
    return ids, [AllocatableDevice(i, numa_node=0 if coords[i][0] == 0 else 1,
                                   coords=coords[i]) for i in ids]


def test_ici_contiguous_pair_preferred():
    ids, devs = _v5e_devices()
    # ask for 2 with a scattered availability order: a contiguous pair must win
    order = [ids[0], ids[7], ids[1], ids[6]]
    picked = preferred_allocation(devs, order, [], 2, torus_dims=(2, 4))
    by_id = {d.device_id: d for d in devs}
    c0, c1 = by_id[picked[0]].coords, by_id[picked[1]].coords
    # manhattan-adjacent on the torus
    dist = sum(min(abs(a - b), dim - abs(a - b))
               for a, b, dim in zip(c0, c1, (2, 4)))
    assert dist == 1


def test_ici_full_host_slice():
    ids, devs = _v5e_devices()
    picked = preferred_allocation(devs, ids, [], 8, torus_dims=(2, 4))
    assert sorted(picked) == sorted(ids)


def test_must_include_kept_and_box_built_around_it():
    ids, devs = _v5e_devices()
    picked = preferred_allocation(devs, ids, [ids[5]], 4, torus_dims=(2, 4))
    assert ids[5] in picked
    assert len(picked) == 4


def test_must_include_too_large():
    ids, devs = _v5e_devices()
    with pytest.raises(MustIncludeTooLarge):
        preferred_allocation(devs, ids, ids[:3], 2, torus_dims=(2, 4))


def test_numa_tier_without_coords():
    # no torus dims -> reference-style NUMA preference
    devs = [AllocatableDevice(f"d{i}", numa_node=i % 2) for i in range(6)]
    order = [f"d{i}" for i in range(6)]  # alternating numa 0/1
    picked = preferred_allocation(devs, order, [], 3)
    assert {d for d in picked} == {"d0", "d2", "d4"}  # single NUMA node 0


def test_kubelet_order_fallback():
    # sizes too big for any single numa node -> kubelet order preserved
    devs = [AllocatableDevice(f"d{i}", numa_node=i % 2) for i in range(4)]
    order = ["d3", "d1", "d0", "d2"]
    picked = preferred_allocation(devs, order, [], 4)
    assert picked == order


def test_numa_respects_must_include_node():
    devs = [AllocatableDevice(f"d{i}", numa_node=0 if i < 3 else 1) for i in range(6)]
    order = [f"d{i}" for i in range(6)]
    picked = preferred_allocation(devs, order, ["d4"], 3)
    assert "d4" in picked
    assert all(d in {"d3", "d4", "d5"} for d in picked)


def test_no_false_wraparound_adjacency():
    # free chips at (0,0) and (0,3) are NOT adjacent on a partial axis of a
    # larger pod torus; a truly adjacent pair must win
    devs = [
        AllocatableDevice("a", 0, (0, 0)),
        AllocatableDevice("b", 0, (0, 3)),
        AllocatableDevice("c", 0, (1, 1)),
        AllocatableDevice("d", 0, (1, 2)),
    ]
    picked = preferred_allocation(devs, ["a", "b", "c", "d"], [], 2,
                                  torus_dims=(2, 4))
    assert sorted(picked) == ["c", "d"]


def test_malformed_hints_ignored():
    ids = bdfs(2)
    coords = assign_coords(ids, V5E, hints={ids[0]: (1,), ids[1]: (9, 9)})
    # both hints invalid (arity / range) -> chips fall back to free slots
    assert coords[ids[0]] == (0, 0)
    assert coords[ids[1]] == (0, 1)


def test_short_arity_coords_never_match_boxes():
    devs = [
        AllocatableDevice("short", 0, (1,)),
        AllocatableDevice("c", 0, (1, 1)),
        AllocatableDevice("d", 0, (1, 2)),
    ]
    picked = preferred_allocation(devs, ["short", "c", "d"], [], 2,
                                  torus_dims=(2, 4))
    assert sorted(picked) == ["c", "d"]


def test_load_topology_hints_bad_json(tmp_path):
    from tpu_device_plugin.topology import load_topology_hints
    p = tmp_path / "h.json"
    p.write_text("[1,2,3]")
    assert load_topology_hints(str(p)) == {}
    p.write_text("{\"bdf\": [0, 1]}")
    assert load_topology_hints(str(p)) == {"bdf": (0, 1)}
    assert load_topology_hints(None) == {}


def test_pcie_siblings_get_adjacent_coords(tmp_path):
    """Chips sharing an upstream PCIe switch must land on adjacent torus
    slots even when raw BDF order interleaves the switches (SURVEY §7 hard
    part (a): host-side ICI adjacency from the PCIe hierarchy)."""
    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin.config import Config
    from tpu_device_plugin import discovery
    host = FakeHost(tmp_path)
    # adversarial: BDF sort = 04, 05, 06, 07 but switches pair (04,06), (05,07)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           pcie_parent="0000:00:01.0"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12",
                           pcie_parent="0000:00:02.0"))
    host.add_chip(FakeChip("0000:00:06.0", iommu_group="13",
                           pcie_parent="0000:00:01.0"))
    host.add_chip(FakeChip("0000:00:07.0", iommu_group="14",
                           pcie_parent="0000:00:02.0"))
    cfg = Config().with_root(host.root)
    registry, _ = discovery.discover_passthrough(cfg)
    coords = {d.bdf: d.ici_coords for d in registry.devices_by_model["0062"]}
    # v4 torus is (2, 2, 1): siblings must differ in exactly one axis by 1
    def adjacent(a, b):
        diffs = [abs(x - y) for x, y in zip(coords[a], coords[b])]
        return sum(diffs) == 1
    assert adjacent("0000:00:04.0", "0000:00:06.0"), coords
    assert adjacent("0000:00:05.0", "0000:00:07.0"), coords
    # preferred allocation for 2 chips picks a sibling pair, not a BDF pair
    from tpu_device_plugin.topology import AllocatableDevice, preferred_allocation
    devs = [AllocatableDevice(d.bdf, d.numa_node, d.ici_coords)
            for d in registry.devices_by_model["0062"]]
    picked = preferred_allocation(
        devs, [d.bdf for d in sorted(registry.devices_by_model["0062"],
                                     key=lambda x: x.bdf)], [], 2,
        torus_dims=(2, 2, 1))
    assert set(picked) in ({"0000:00:04.0", "0000:00:06.0"},
                           {"0000:00:05.0", "0000:00:07.0"}), picked


def test_flat_sysfs_keeps_bdf_order(tmp_path):
    """Without a resolvable PCIe hierarchy (flat fixture dirs), coordinate
    assignment stays in sorted-BDF order — previous behavior unchanged."""
    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin.config import Config
    from tpu_device_plugin import discovery
    host = FakeHost(tmp_path)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", iommu_group=str(11 + i)))
    cfg = Config().with_root(host.root)
    registry, _ = discovery.discover_passthrough(cfg)
    coords = {d.bdf: d.ici_coords for d in registry.devices_by_model["0062"]}
    assert coords["0000:00:04.0"] == (0, 0, 0)
    assert coords["0000:00:05.0"] == (0, 1, 0)
    assert coords["0000:00:06.0"] == (1, 0, 0)
    assert coords["0000:00:07.0"] == (1, 1, 0)


def test_switch_topology_with_downstream_ports(tmp_path):
    """Real switches give each endpoint its own downstream port; chips
    behind one switch still sort adjacently via the shared upstream-port
    path prefix."""
    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin.config import Config
    from tpu_device_plugin import discovery
    host = FakeHost(tmp_path)
    # switch A upstream 0000:00:01.0, downstream ports 01:00.0 / 01:01.0
    host.add_chip(FakeChip("0000:02:00.0", iommu_group="11",
                           pcie_parent="0000:00:01.0/0000:01:00.0"))
    host.add_chip(FakeChip("0000:03:00.0", iommu_group="12",
                           pcie_parent="0000:00:01.0/0000:01:01.0"))
    # switch B upstream 0000:00:09.0 — sorts BEFORE A's chips by raw BDF? no:
    # chips 02:00/03:00 vs 0a:00/0b:00; make B's chips interleave by BDF
    host.add_chip(FakeChip("0000:02:01.0", iommu_group="13",
                           pcie_parent="0000:00:09.0/0000:09:00.0"))
    host.add_chip(FakeChip("0000:03:01.0", iommu_group="14",
                           pcie_parent="0000:00:09.0/0000:09:01.0"))
    cfg = Config().with_root(host.root)
    registry, _ = discovery.discover_passthrough(cfg)
    coords = {d.bdf: d.ici_coords for d in registry.devices_by_model["0062"]}

    def adjacent(a, b):
        return sum(abs(x - y) for x, y in zip(coords[a], coords[b])) == 1
    # raw BDF order would pair (02:00.0, 02:01.0) — across switches; the
    # path order pairs each switch's own chips instead
    assert adjacent("0000:02:00.0", "0000:03:00.0"), coords
    assert adjacent("0000:02:01.0", "0000:03:01.0"), coords


def test_boxes_memoized_across_index_rebuilds():
    """Plugin restarts / rediscovery rebuilds construct a fresh
    AllocationIndex for the same torus; the sub-box enumeration (the
    expensive, purely dims-derived part) must be served from the _boxes
    memo, not re-enumerated per construction."""
    from tpu_device_plugin.topology import AllocationIndex, _boxes

    dims = (4, 4, 4)
    devs = [AllocatableDevice(f"d{i}", numa_node=0,
                              coords=(i // 16, (i // 4) % 4, i % 4))
            for i in range(64)]
    _boxes.cache_clear()
    AllocationIndex(devs, dims)
    after_first = _boxes.cache_info()
    assert after_first.misses == 1
    for _ in range(3):  # rediscovery rebuilds on the same torus
        AllocationIndex(devs, dims)
    after = _boxes.cache_info()
    assert after.misses == 1, "sub-box enumeration re-paid on rebuild"
    assert after.hits >= after_first.hits + 3


def test_duplicate_coordinate_hints_rejected(caplog):
    """ISSUE 10 satellite: two hints landing on ONE torus slot used to be
    silently accepted — both chips at the same coordinate poisons every
    sub-box score. Colliding hints are now dropped (with a warning) like
    the arity/range check drops malformed ones; the chips fall back to
    layout order and every chip still gets a UNIQUE slot."""
    import logging

    bdfs = ["0000:00:04.0", "0000:00:05.0", "0000:00:06.0", "0000:00:07.0"]
    info = GenerationInfo("v4", 4, (2, 2, 1))
    hints = {"0000:00:04.0": (0, 0, 0), "0000:00:05.0": (0, 0, 0),
             "0000:00:06.0": (1, 1, 0)}
    with caplog.at_level(logging.WARNING, "tpu_device_plugin.topology"):
        coords = assign_coords(bdfs, info, hints=hints)
    assert sum("duplicates another hint" in r.message
               for r in caplog.records) == 2
    # the non-colliding hint still wins; the colliders were re-laid
    assert coords["0000:00:06.0"] == (1, 1, 0)
    placed = [c for c in coords.values() if c is not None]
    assert len(placed) == len(set(placed)) == 4, coords
    assert coords["0000:00:04.0"] != coords["0000:00:05.0"]
