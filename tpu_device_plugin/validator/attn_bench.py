"""Flash-vs-einsum attention benchmark (single device, one process claim).

The build environment's TPU tunnel grants one exclusive claim per process
and has historically been flaky, so this packs the whole kernel-tuning
protocol — forward and train timings for the Pallas flash kernel against
the einsum reference across sequence lengths and block sizes — into one
command:

    python -m tpu_device_plugin.validator --mode attn-bench \
        --seqs 1024,2048,4096 --blocks 128x128,256x128

Emits one JSON line per (seq, block) cell plus a winner summary, feeding
BASELINE.md and the flash block-size tuning loop (roadmap item 2).
On CPU the kernel runs in interpret mode (slow): keep seqs small there.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _time_fn(fn, args, iters: int) -> float:
    """Median wall-clock seconds per call, after one warmup/compile call."""
    import jax
    jax.block_until_ready(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.monotonic()
        jax.block_until_ready(fn(*args))
        samples.append(time.monotonic() - t0)
    return _median(samples)


def bench_attention(
    seq_lens: Sequence[int] = (1024, 2048, 4096),
    blocks: Sequence[Tuple[int, int]] = ((128, 128),),
    hb: int = 8,
    head_dim: int = 128,
    iters: int = 10,
    causal: bool = True,
    device=None,
    interpret: Optional[bool] = None,
) -> dict:
    """Compare Pallas flash vs einsum reference on one device.

    Returns {"cells": [...], "flash_wins_at": [...], "device_kind": ...}.
    Each cell: seq, block_q, block_k, flash/einsum forward + train (ms) and
    speedups (>1 means flash is faster).
    """
    import jax
    import jax.numpy as jnp

    from .flash_attention import _reference_attention, flash_attention

    if device is None:
        # local: in a multi-VMI slice jax.devices() spans other guests'
        # non-addressable devices (same trap probe._microbench documents)
        device = jax.local_devices()[0]
    if interpret is None:
        interpret = device.platform != "tpu"
    iters = max(iters, 1)  # _median needs >=1 sample

    def rand(shape, seed):
        x = jax.random.normal(jax.random.key(seed), shape, jnp.float32)
        return jax.device_put(x.astype(jnp.bfloat16), device)

    sm = head_dim ** -0.5
    cells = []
    for seq in seq_lens:
        q, k, v = (rand((hb, seq, head_dim), i) for i in (1, 2, 3))
        ein_fwd = jax.jit(
            lambda q, k, v: _reference_attention(q, k, v, sm, causal))
        ein_train = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(
                _reference_attention(q, k, v, sm, causal)
                .astype(jnp.float32) ** 2), argnums=(0, 1, 2)))
        try:
            ein_fwd_s = _time_fn(ein_fwd, (q, k, v), iters)
            ein_train_s = _time_fn(ein_train, (q, k, v), iters)
            ein_err = ""
        except Exception as exc:
            # the einsum reference materializes the (S, S) matrix and can
            # OOM at lengths flash handles fine — keep sweeping
            ein_fwd_s = ein_train_s = None
            ein_err = f"einsum: {type(exc).__name__}: {exc}"
        for bq, bk in blocks:
            fl_fwd = jax.jit(
                lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, None, causal, bq, bk, interpret))
            fl_train = jax.jit(jax.grad(
                lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                    flash_attention(q, k, v, None, causal, bq, bk, interpret)
                    .astype(jnp.float32) ** 2), argnums=(0, 1, 2)))
            try:
                fl_fwd_s = _time_fn(fl_fwd, (q, k, v), iters)
                fl_train_s = _time_fn(fl_train, (q, k, v), iters)
                err = ein_err
            except Exception as exc:  # report the cell, keep sweeping
                fl_fwd_s = fl_train_s = None  # None -> JSON null, never NaN
                err = "; ".join(
                    x for x in (ein_err,
                                f"flash: {type(exc).__name__}: {exc}") if x)

            def ms(s):
                return None if s is None else s * 1e3

            def speedup(ref_s, new_s):
                return (ref_s / new_s
                        if ref_s is not None and new_s else None)

            cells.append({
                "seq": seq, "block_q": bq, "block_k": bk,
                "flash_fwd_ms": ms(fl_fwd_s),
                "einsum_fwd_ms": ms(ein_fwd_s),
                "flash_train_ms": ms(fl_train_s),
                "einsum_train_ms": ms(ein_train_s),
                "fwd_speedup": speedup(ein_fwd_s, fl_fwd_s),
                "train_speedup": speedup(ein_train_s, fl_train_s),
                "error": err,
            })
    wins = sorted({c["seq"] for c in cells
                   if c["flash_fwd_ms"] is not None
                   and (c["fwd_speedup"] or 0) > 1.0})
    return {
        "device_kind": device.device_kind,
        "platform": device.platform,
        "interpret": interpret,
        "hb": hb,
        "head_dim": head_dim,
        "cells": cells,
        "flash_wins_at": wins,
        # the verdict the CLI uses: the FLASH kernel must have run in every
        # cell; an einsum-reference failure (it OOMs at lengths flash
        # handles fine) degrades that cell's comparison, never the sweep
        "flash_ok": bool(cells) and all(
            c["flash_fwd_ms"] is not None for c in cells),
    }
