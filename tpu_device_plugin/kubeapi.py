"""Minimal Kubernetes API client — stdlib only, no `kubernetes` package.

Shared by the node labeler (PATCH node labels) and the DRA driver
(ResourceSlice publish, ResourceClaim reads). Authenticates with the pod's
service-account token and trusts the in-cluster CA, exactly like the
labeler always has; the dependency-free stance mirrors the reference's
single-static-binary posture (its only runtime deps are grpc + sysfs,
reference: go.mod:1-12 — it never talks to the API server at all).
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import urllib.error
import urllib.request
from typing import Optional

log = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_server() -> Optional[str]:
    """https://host:port of the API server from the in-cluster env, if any."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        return None
    return f"https://{host}:{port}"


class ApiError(Exception):
    """HTTP-level API failure carrying the status code (0 = transport)."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


class ApiClient:
    """Bearer-token REST client for one API server."""

    def __init__(self, server: str,
                 token_path: str = os.path.join(SA_DIR, "token"),
                 ca_path: str = os.path.join(SA_DIR, "ca.crt"),
                 timeout_s: float = 10.0):
        self.server = server.rstrip("/")
        self.token_path = token_path
        self.ca_path = ca_path
        self.timeout_s = timeout_s

    def request(self, path: str, method: str = "GET",
                body: Optional[bytes] = None,
                content_type: Optional[str] = None) -> bytes:
        """Raw request against an API path; raises ApiError on failure."""
        url = self.server + path
        req = urllib.request.Request(url, data=body, method=method)
        if content_type:
            req.add_header("Content-Type", content_type)
        try:
            with open(self.token_path, "r", encoding="ascii") as f:
                req.add_header("Authorization", f"Bearer {f.read().strip()}")
        except OSError:
            pass  # no token (e.g. test server without auth)
        ctx = None
        if url.startswith("https"):
            ctx = ssl.create_default_context(
                cafile=self.ca_path if os.path.exists(self.ca_path) else None)
        try:
            with urllib.request.urlopen(
                    req, context=ctx, timeout=self.timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            detail = ""
            try:
                detail = exc.read().decode("utf-8", "replace")[:300]
            except OSError:
                pass
            raise ApiError(f"{method} {url}: HTTP {exc.code} {detail}",
                           code=exc.code) from exc
        except (urllib.error.URLError, OSError) as exc:
            raise ApiError(f"{method} {url}: {exc}") from exc

    # -- JSON convenience wrappers against resource paths ---------------------

    def get_json(self, path: str) -> dict:
        return json.loads(self.request(path))

    def post_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="POST", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def put_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="PUT", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def delete(self, path: str) -> None:
        self.request(path, method="DELETE")

    def patch_strategic(self, path: str, obj: dict) -> bytes:
        return self.request(
            path, method="PATCH", body=json.dumps(obj).encode(),
            content_type="application/strategic-merge-patch+json")
