"""Physics-honest perf: datasheet peak table, MFU reporting, timing floor.

Round 3 recorded a 289 TFLOP/s microbench on a 197 TF-peak v5e (VERDICT r3
item 1). These tests pin the three defenses added in round 4:

  1. validator/peaks.py — per-generation datasheet peaks + suspect check;
  2. validator/timing.py — median-of-paired-differences estimator with a
     minimum-differenced-time floor (noise cannot fabricate compute time);
  3. probe.validate_slice — refuses (ok=False, perf_suspect=True) any run
     whose microbench exceeds ~1.05x the chip's datasheet peak, and reports
     mfu / microbench_mfu / hbm_frac against the peak otherwise.

Round 6 adds the incremental-discovery honesty guard: the warm dirty-set
rescan must do strictly fewer — and at least 5x fewer — SYSFS READS than
the cold full scan at 64 devices. Counted, not timed, so the guard is
load-insensitive and CI-safe.

Round 7 adds the shared-health-plane guards (bench.py --health): (a) the
hub holds ONE inotify fd regardless of resource count — counted, not
timed; (b) a probe cycle with one hung chip is bounded by the per-cycle
deadline, never the serial sum — the margins are seconds wide (hang 3 s,
deadline 0.2 s, ceiling 1.5 s) so CI load cannot flip the verdict.
"""

import pytest

jax = pytest.importorskip("jax")

from tpu_device_plugin.validator import peaks
from tpu_device_plugin.validator import timing
from tpu_device_plugin.validator.probe import PRESETS, SliceReport, validate_slice
from tpu_device_plugin.validator.workload import ModelConfig


def cpus():
    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("need 8 virtual CPU devices")
    return devs


SMALL = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=1,
                    seq_len=16, batch=4)


# ---------------------------------------------------------------- peaks ----

def test_peaks_lookup_known_kinds():
    assert peaks.lookup("TPU v5 lite").bf16_tflops == 197.0
    assert peaks.lookup("TPU v5e").generation == "v5e"
    assert peaks.lookup("TPU v5p").bf16_tflops == 459.0
    assert peaks.lookup("TPU v5").generation == "v5p"  # bare v5 = v5p
    assert peaks.lookup("TPU v4").bf16_tflops == 275.0
    assert peaks.lookup("TPU v6 lite").bf16_tflops == 918.0
    assert peaks.lookup("TPU v3").hbm_gbps == 900.0
    assert peaks.lookup("TPU v2").bf16_tflops == 45.0


def test_peaks_lookup_unknown_kinds():
    assert peaks.lookup("cpu") is None
    assert peaks.lookup("") is None
    assert peaks.lookup(None) is None
    # a future generation must degrade to "no physics check", not a veto
    assert peaks.lookup("TPU v9 mega") is None


def test_peaks_check_flags_impossible_tflops():
    peak, suspect, why = peaks.check("TPU v5 lite", tflops=289.2)
    assert peak.generation == "v5e"
    assert suspect
    assert "289.2" in why and "197" in why


def test_peaks_check_accepts_plausible_and_boost_margin():
    # at peak and slightly above (clock boost / measurement wiggle) is fine
    for tf in (100.0, 197.0, 197.0 * 1.04):
        _, suspect, _ = peaks.check("TPU v5 lite", tflops=tf)
        assert not suspect, tf
    _, suspect, _ = peaks.check("TPU v5 lite", gbps=819.0 * 1.2)
    assert suspect


def test_peaks_check_unknown_kind_never_vetoes():
    peak, suspect, why = peaks.check("cpu", tflops=1e6, gbps=1e6)
    assert peak is None and not suspect and why == ""


# --------------------------------------------------------------- timing ----

class _FakeClock:
    """Deterministic stand-in for the time module inside validator.timing."""

    def __init__(self):
        self.t = 0.0

    def monotonic(self):
        return self.t


def _fake_build(clock, per_iter, extra_by_call=None):
    """build(k) -> fn advancing the fake clock k*per_iter (+ scheduled
    extras, consumed one per call, to model load spikes)."""
    extras = list(extra_by_call or [])
    calls = []

    def build(k):
        calls.append(k)

        def fn(*args):
            clock.t += k * per_iter
            if extras:
                clock.t += extras.pop(0)
            return 0.0
        return fn
    build.calls = calls
    return build


def test_paired_time_is_median_of_pair_differences(monkeypatch):
    clock = _FakeClock()
    monkeypatch.setattr(timing, "time", clock)
    # 1 ms per iteration; one 50 ms load spike hits a single call — the
    # median over 5 pairs must shrug it off (the old median(t2)-median(t1)
    # form is immune here too, but a spike on exactly the median element
    # of one side was not; per-pair differencing makes the outlier local)
    spikes = [0.0] * 4 + [0.05] + [0.0] * 20
    build = _fake_build(clock, 1e-3, spikes)
    est = timing.paired_time(build, (), iters=5, repeats=4)
    assert est == pytest.approx(1e-3, rel=1e-6)


def test_paired_time_grows_repeats_to_floor(monkeypatch):
    clock = _FakeClock()
    monkeypatch.setattr(timing, "time", clock)
    build = _fake_build(clock, 1e-3)
    est = timing.paired_time(build, (), iters=3, repeats=1,
                             min_diff_s=0.064)
    assert est == pytest.approx(1e-3, rel=1e-6)
    # the floor demands repeats * 1ms >= 64 ms of differenced compute
    assert max(build.calls) >= 64
    # growth is geometric/jump-sized, not one-at-a-time
    assert len(build.calls) < 40


def test_paired_time_no_floor_keeps_legacy_paths(monkeypatch):
    clock = _FakeClock()
    monkeypatch.setattr(timing, "time", clock)
    build = _fake_build(clock, 2e-3)
    # repeats<=1 without a floor: plain per-call timing (CPU/test path)
    est = timing.paired_time(build, (), iters=3, repeats=1)
    assert est == pytest.approx(2e-3, rel=1e-6)
    assert build.calls == [1]


# ---------------------------------------------------------------- probe ----

def _force_v5e(monkeypatch):
    """Make peaks.lookup see a v5e regardless of the CPU device kind."""
    monkeypatch.setattr(peaks, "lookup", lambda kind: peaks.PEAKS["v5e"])


def test_impossible_microbench_vetoes_the_run(monkeypatch):
    from tpu_device_plugin.validator import probe as probe_mod
    _force_v5e(monkeypatch)
    monkeypatch.setattr(probe_mod, "_microbench",
                        lambda device, min_diff_s=None: (289.2, 400.0))
    report = probe_mod.validate_slice(cfg=SMALL, steps=2, devices=cpus()[:1])
    assert report.perf_suspect
    assert report.ok is False
    assert "datasheet peak" in report.error
    # loss still decreased — the veto is about measurement, not training
    assert report.loss_end < report.loss_start


def test_suspect_reading_retries_at_taller_floor(monkeypatch):
    from tpu_device_plugin.validator import probe as probe_mod
    _force_v5e(monkeypatch)
    readings = [(289.2, 400.0), (150.0, 400.0)]  # glitch, then clean
    floors = []

    def fake_microbench(device, min_diff_s=None):
        floors.append(min_diff_s)
        return readings.pop(0)

    monkeypatch.setattr(probe_mod, "_microbench", fake_microbench)
    report = probe_mod.validate_slice(cfg=SMALL, steps=2, devices=cpus()[:1])
    assert report.ok, report.error
    assert not report.perf_suspect
    assert report.matmul_tflops == 150.0
    # the retry used a 4x noise floor
    assert floors == [None, probe_mod.MICROBENCH_MIN_DIFF_S * 4]


def test_report_carries_mfu_fractions(monkeypatch):
    from tpu_device_plugin.validator import probe as probe_mod
    _force_v5e(monkeypatch)
    monkeypatch.setattr(probe_mod, "_microbench",
                        lambda device, min_diff_s=None: (98.5, 409.5))
    report = probe_mod.validate_slice(cfg=SMALL, steps=2, devices=cpus()[:1])
    assert report.ok, report.error
    assert report.peak_tflops == 197.0
    assert report.peak_hbm_gbps == 819.0
    assert report.microbench_mfu == pytest.approx(0.5)
    assert report.hbm_frac == pytest.approx(0.5)
    # train-step MFU against the same peak (tiny CPU steps can difference
    # to 0 under noise, so consistency — not positivity — is the contract)
    assert report.mfu == pytest.approx(report.tflops_per_chip / 197.0)
    payload = report.to_json()
    assert '"mfu"' in payload and '"perf_suspect": false' in payload


def test_unknown_generation_reports_no_fractions():
    # plain CPU path: no peak known -> fractions stay 0, never a veto
    report = validate_slice(cfg=SMALL, steps=2, devices=cpus()[:1])
    assert report.ok, report.error
    assert report.peak_tflops == 0.0
    assert report.mfu == 0.0 and report.microbench_mfu == 0.0
    assert not report.perf_suspect


# --------------------------------------------------------------- preset ----

def test_mfu_preset_shape():
    p = PRESETS["mfu"]
    assert p["d_model"] == 2048 and p["seq_len"] == 2048
    assert p["d_model"] % p["n_heads"] == 0
    assert p["d_model"] // p["n_heads"] == 128  # MXU/flash-friendly head dim
    from tpu_device_plugin.validator.workload import FLASH_MIN_SEQ
    assert p["seq_len"] >= FLASH_MIN_SEQ  # auto mode picks the flash kernel


def test_cli_preset_builds_sized_config(monkeypatch):
    from tpu_device_plugin.validator import probe as probe_mod
    seen = {}

    def fake_validate(cfg=None, **kw):
        seen["cfg"] = cfg
        return SliceReport(ok=True)

    monkeypatch.setattr(probe_mod, "validate_slice", fake_validate)
    rc = probe_mod.main(["--preset", "mfu", "--steps", "1"])
    assert rc == 0
    assert seen["cfg"].d_model == 2048
    assert seen["cfg"].n_layers == 8
    assert not seen["cfg"].remat


def test_cli_preset_mfu_lite_builds_reduced_config(monkeypatch):
    """mfu-lite: ~7x fewer FLOPs/step than mfu — matmul FLOPs 8x lighter,
    the 4*S^2*d attention term only 4x at the unchanged seq (capture
    insurance: it runs BEFORE the unbounded full-size attempt, because a
    hung relay compile cannot be killed without wedging the claim); same
    MXU-friendly head_dim 128 and flash-eligible seq. MFU itself is
    size-independent, so nothing is ever scaled back up."""
    from tpu_device_plugin.validator import probe as probe_mod
    from tpu_device_plugin.validator.workload import FLASH_MIN_SEQ
    seen = {}

    def fake_validate(cfg=None, **kw):
        seen["cfg"] = cfg
        return SliceReport(ok=True)

    monkeypatch.setattr(probe_mod, "validate_slice", fake_validate)
    assert probe_mod.main(["--preset", "mfu-lite", "--steps", "1"]) == 0
    cfg = seen["cfg"]
    assert cfg.d_model == 1024 and cfg.n_layers == 4
    assert cfg.d_model // cfg.n_heads == 128     # MXU/flash head dim kept
    assert cfg.seq_len >= FLASH_MIN_SEQ          # auto mode -> flash kernel
    full, lite = probe_mod.PRESETS["mfu"], probe_mod.PRESETS["mfu-lite"]
    # matmul-FLOP proxy (d_model^2 * layers) is 8x lighter; the attention
    # term (4*S^2*d per layer) only 4x at the shared seq — so the true
    # step ratio is ~7x, and MFU (measured/peak) needs no scale-up anyway
    matmul = lambda p: (p["d_model"] ** 2 * p["n_layers"])
    attn = lambda p: (p["seq_len"] ** 2 * p["d_model"] * p["n_layers"])
    assert matmul(full) == 8 * matmul(lite)
    assert attn(full) == 4 * attn(lite)


def test_cli_preset_composes_with_overrides(monkeypatch):
    from tpu_device_plugin.validator import probe as probe_mod
    seen = {}

    def fake_validate(cfg=None, **kw):
        seen["cfg"] = cfg
        return SliceReport(ok=True)

    monkeypatch.setattr(probe_mod, "validate_slice", fake_validate)
    rc = probe_mod.main(["--preset", "mfu", "--seq-len", "4096", "--remat"])
    assert rc == 0
    assert seen["cfg"].d_model == 2048
    assert seen["cfg"].seq_len == 4096
    assert seen["cfg"].remat


# ------------------------------------------------------------ ring bench

def test_ring_bench_cpu_small():
    """ring-bench runs end-to-end on the virtual CPU mesh (sp=2,
    interpret-mode kernels): both impls timed, speedups populated."""
    from tpu_device_plugin.validator.ring_bench import bench_ring
    result = bench_ring(seq_lens=(64,), blocks=((32, 32),), sp=2, hb=2,
                        head_dim=32, iters=1, devices=cpus()[:2])
    assert result["platform"] == "cpu" and result["interpret"] is True
    assert result["sp"] == 2
    cell = result["cells"][0]
    assert cell["error"] == ""
    assert cell["ring_flash_fwd_ms"] > 0
    assert cell["einsum_ring_train_ms"] > 0
    assert cell["train_speedup"] is not None
    assert result["ring_flash_ok"]


def test_ring_bench_cli_json_line(capsys):
    from tpu_device_plugin.validator.probe import main
    rc = main(["--mode", "ring-bench", "--seqs", "64", "--blocks", "32x32",
               "--sp", "2", "--hb", "2", "--steps", "1"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    import json as json_mod
    payload = json_mod.loads(out)
    assert payload["cells"][0]["seq"] == 64
    assert rc == 0 and payload["ok"] is True


def test_ring_bench_rejects_indivisible_seq():
    from tpu_device_plugin.validator.ring_bench import bench_ring
    with pytest.raises(ValueError, match="not divisible"):
        bench_ring(seq_lens=(65,), sp=2, hb=2, head_dim=32,
                   devices=cpus()[:2])


# ------------------------------------------------- incremental discovery


def test_warm_dirty_rescan_reads_strictly_fewer_than_cold(tmp_path):
    """bench.py --discovery honesty floor at 64 devices: the warm dirty-set
    rescan (one flapped chip) must do STRICTLY fewer sysfs reads than the
    cold full scan — and hold the 5x acceptance ratio. Read counts come
    from discovery.count_reads (every listdir/readlink/stat/file-read in
    the discovery module), so the assertion is immune to CI load."""
    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import HostSnapshot, count_reads

    host = FakeHost(tmp_path)
    for i in range(64):
        host.add_chip(FakeChip(f"0000:{i // 32:02x}:{4 + i % 32:02x}.0",
                               device_id="0063", iommu_group=str(11 + i),
                               numa_node=i // 32))
    cfg = Config().with_root(host.root)

    snap = HostSnapshot(cfg)
    with count_reads() as cold:
        registry, _ = snap.rescan()
    assert len(registry.all_devices()) == 64

    with count_reads() as warm:
        warm_registry, _ = snap.rescan(dirty={"0000:00:04.0"})
    assert len(warm_registry.all_devices()) == 64
    assert warm.reads < cold.reads, (warm.reads, cold.reads)
    assert cold.reads >= 5 * warm.reads, \
        f"warm rescan {warm.reads} reads vs cold {cold.reads}: ratio " \
        f"{cold.reads / warm.reads:.1f}x below the 5x acceptance floor"
    # the warm window touched ONLY the dirty chip's files (plus the three
    # class listdirs); no other BDF was read
    other_bdf_reads = [p for p in warm.paths
                      if "/devices/0000:" in p and "0000:00:04.0" not in p]
    assert other_bdf_reads == [], other_bdf_reads


# ------------------------------------------------------ shared health plane


def test_health_hub_one_inotify_fd_at_8_and_256_resources(tmp_path):
    """bench.py --health honesty: the hub's inotify fd count is pinned at
    ONE whether 8 or 256 resources subscribe (the old per-server monitors
    held one fd each). Counted, load-insensitive."""
    from tpu_device_plugin.healthhub import HealthHub, HubSubscription

    nodes = tmp_path / "vfio"
    nodes.mkdir()
    for n_resources in (8, 256):
        hub = HealthHub(poll_interval_s=3600, probe_workers=2)
        try:
            for i in range(n_resources):
                p = nodes / f"n{i}"
                if not p.exists():
                    p.write_text("")
                hub.subscribe(HubSubscription(
                    name=f"r{i}", group_paths={f"g{i}": str(p)},
                    group_bdfs={f"g{i}": [f"bdf{i}"]},
                    on_device_health=lambda *a: None,
                    probe=lambda b, n: True))
            stats = hub.stats()
            assert stats["subscriptions"] == n_resources
            assert stats["inotify_fds"] == 1, \
                f"{n_resources} resources must share ONE inotify fd, " \
                f"got {stats['inotify_fds']}"
        finally:
            hub.stop()


def test_health_probe_cycle_with_one_slow_chip_is_deadline_bounded():
    """bench.py --health honesty: one chip hanging its config read for 3 s
    must cost the cycle ~the 0.2 s deadline, NOT the serial sum (>= 3 s,
    what the old back-to-back loop paid). The 1.5 s ceiling leaves seconds
    of CI-load margin on both sides of the serial/parallel divide."""
    import threading as threading_mod
    import time

    from tpu_device_plugin.healthhub import HealthHub, HubSubscription

    release = threading_mod.Event()

    def probe(bdf, node):
        if bdf == "bdf-slow":
            release.wait(3.0)
        return True

    hub = HealthHub(poll_interval_s=3600, probe_workers=4,
                    probe_deadline_s=0.2)
    hits = []
    try:
        hub.subscribe(HubSubscription(
            name="r",
            group_bdfs={**{f"g{i}": [f"bdf{i}"] for i in range(16)},
                        "slow": ["bdf-slow"]},
            on_device_health=lambda k, ok, src: hits.append((k, ok)),
            probe=probe))
        t0 = time.monotonic()
        verdicts = hub.probe_cycle()
        wall = time.monotonic() - t0
        assert wall < 1.5, \
            f"probe cycle took {wall:.2f}s — the hung chip serialized the " \
            f"cycle (deadline-bounding is broken)"
        # every fast chip's verdict landed despite the hung one
        assert all(verdicts[f"bdf{i}"] for i in range(16))
        assert verdicts["bdf-slow"] is False
        assert ("slow", False) in hits
        assert hub.stats()["probe_timeouts_total"] == 1
    finally:
        release.set()
        hub.stop()


# ------------------------------------------------------------ attach path


def test_attach_burst_32_claims_coalesce_to_few_checkpoint_writes(short_root):
    """bench.py --attach-burst honesty: a 32-claim concurrent prepare burst
    must cost <= 4 checkpoint writes (the old per-claim rewrite paid 32) —
    COUNTED commits, load-insensitive. The commit window is widened to
    250 ms here so CI scheduling jitter cannot split the burst across
    extra windows; the barrier semantics under test are identical."""
    from dataclasses import replace

    from tests.fakehost import FakeChip, FakeHost
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover
    from tpu_device_plugin.dra import DraDriver, slice_device_name
    from tpu_device_plugin.kubeapi import ApiClient
    from tpu_device_plugin.kubeletapi import drapb

    host = FakeHost(short_root)
    for i in range(8):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i)))
    cfg = replace(Config().with_root(host.root), prepare_workers=8)
    apiserver = FakeApiServer()
    try:
        registry, generations = discover(cfg)
        driver = DraDriver(cfg, registry, generations, node_name="n",
                           api=ApiClient(apiserver.url,
                                         token_path="/nonexistent"))
        driver.checkpoint_commit_window_s = 0.25
        names = [slice_device_name(f"0000:00:{4 + i:02x}.0")
                 for i in range(8)]
        uids = [f"honesty-{i}" for i in range(32)]
        for i, uid in enumerate(uids):
            apiserver.add_claim("ns", uid, uid, driver.driver_name,
                                [{"device": names[i % 8]}])
        claims = [drapb.Claim(namespace="ns", name=uid, uid=uid)
                  for uid in uids]
        resp = driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=claims), None)
        for uid in uids:
            assert resp.claims[uid].error == "", resp.claims[uid].error
        stats = driver.checkpoint_stats()
        assert stats["checkpoint_claims_coalesced_total"] == 32
        assert stats["checkpoint_commits_total"] <= 4, \
            f"32-claim burst cost {stats['checkpoint_commits_total']} " \
            f"checkpoint writes — group commit is not coalescing"
        # every ACK is on disk (flush barrier honored): a fresh driver
        # recovers all 32 without a single API re-fetch
        import json
        with open(driver.checkpoint_path) as f:
            # versioned envelope: claims live under the "claims" key
            assert set(json.load(f)["claims"]) == set(uids)
        driver.stop()
    finally:
        apiserver.stop()


def test_fragment_hit_plan_is_5x_cheaper_by_counted_reads(tmp_path):
    """bench.py --attach-burst honesty: the fragment-hit plan must do at
    least 5x fewer FRAGMENT-PATH sysfs reads (vfio-dev cdev listdirs: 8
    cold, 0 warm here) than the cold plan, while the TOCTOU revalidation
    reads stay EQUAL in both (live by design — caching them would be the
    dishonest speedup). Counted via allocate.count_plan_reads, so CI load
    cannot flip the verdict."""
    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin import allocate
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover_passthrough

    host = FakeHost(tmp_path)
    for i in range(8):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i),
                               vfio_dev=f"vfio{i}"))
    host.enable_iommufd()
    cfg = Config().with_root(host.root)
    registry, _ = discover_passthrough(cfg)
    planner = allocate.AllocationPlanner(cfg, registry, "v4")
    bdfs = [f"0000:00:{4 + i:02x}.0" for i in range(8)]
    with allocate.count_plan_reads() as cold:
        planner.plan(bdfs)
    with allocate.count_plan_reads() as warm:
        planner.plan(bdfs)

    def fragment_reads(w):
        return len([p for p in w.paths if "vfio-dev" in p])

    def reval_reads(w):
        return len([p for p in w.paths
                    if p.endswith("iommu_group") or p.endswith("vendor")])

    assert fragment_reads(cold) >= 8
    assert fragment_reads(cold) >= 5 * max(1, fragment_reads(warm)), \
        f"fragment path: {fragment_reads(cold)} cold vs " \
        f"{fragment_reads(warm)} warm reads — below the 5x floor"
    assert fragment_reads(warm) == 0
    assert reval_reads(cold) == reval_reads(warm) == 16
    assert warm.reads < cold.reads


# ------------------------------------------------------- epoch read plane


def test_bench_attach_r09_pins_lock_free_attach():
    """Round-9 honesty pins against the RECORDED docs/bench_attach_r09.json
    (file content, so CI load cannot flip it). The claims this PR makes:

      - COUNTED: a steady-state attach acquires ZERO registered locks
        (the pre-epoch tree measured 11/attach) — every hot read path's
        per-path counter is zero;
      - COUNTED: the live TOCTOU revalidation's sysfs syscall shape is
        recorded (4 syscalls per allocated member; caching them away
        would be the dishonest speedup);
      - the environment-comparable daemon overhead (wall minus the
        counted-syscalls x in-run-calibration I/O floor) is under the
        200 us target — the RAW wall is recorded next to its syscall
        calibration because the I/O floor is an environment property
        (sub-us native syscalls where BENCH_r05 was recorded, ~20 us in
        sandboxed kernels; docs/perf.md "lock-free read plane").
    """
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_attach_r09.json")
    with open(path) as f:
        data = json.load(f)

    # counted: zero registered-lock acquisitions, on every hot path
    assert data["lock_acquisitions_per_attach"] == 0
    for name, rec in data["lock_path_stats"].items():
        assert rec["lock_acquisitions"] == 0, (name, rec)
        assert rec["calls"] > 0, (name, rec)
    assert {"server.Allocate", "server.GetPreferredAllocation",
            "server.ListAndWatch.assembly", "server.status_snapshot"} \
        <= set(data["lock_path_stats"])

    # counted: the TOCTOU revalidation stays live — one readlink (group
    # link) and one pread (vendor) per allocated member, with their
    # staleness guards; zero would mean the guard got cached away
    sys_counts = data["sysfs_syscalls_per_attach"]
    assert sys_counts["readlink"] == data["allocation_size"]
    assert sys_counts["pread"] >= data["allocation_size"]
    assert data["sysfs_syscalls_per_attach_total"] <= 24

    # the breakdown adds up and the daemon-side overhead meets the
    # target; the RAW wall must also beat r05's recorded 761.9 us even
    # though this environment runs syscalls ~30x slower than the one
    # that recorded r05 (the raw <200 us reading needs native-speed
    # syscalls — the floor alone exceeds it here; see baseline_source)
    assert data["value"] < 761.9, data
    assert data["daemon_overhead_p50_us"] < 200, data
    assert data["sysfs_io_floor_p50_us"] + data["daemon_overhead_p50_us"] \
        == pytest.approx(data["value"], abs=0.2)
    # the r05-comparable transport figure is recorded, unclaimed
    assert data["transport_wall_p50_us"] > 0
    assert data["syscall_cost_calibration_us"]["stat"] > 0


def test_attach_zero_locks_is_live_not_just_recorded(short_root):
    """Runtime half of the r09 pin: re-count the zero-lock claim on the
    CURRENT tree (lockdep.scoped proxies; load-insensitive). The full
    gate lives in tests/test_epoch.py — this is the minimal version the
    CI bench-smoke job runs next to the artifact pins."""
    import os

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin import lockdep
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover_passthrough
    from tpu_device_plugin.kubeletapi import pb
    from tpu_device_plugin.server import TpuDevicePlugin

    with lockdep.scoped():
        host = FakeHost(short_root)
        host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
        cfg = Config().with_root(host.root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, _ = discover_passthrough(cfg)
        plugin = TpuDevicePlugin(cfg, "v4", registry,
                                 registry.devices_by_model["0062"])
        req = pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"])])
        plugin.Allocate(req, None)       # warm-up may lock (slow paths)
        lockdep.reset()
        plugin.Allocate(req, None)
        stats = lockdep.path_stats()
        assert stats["server.Allocate"]["lock_acquisitions"] == 0, stats


def test_bench_attach_r10_pins_trace_overhead():
    """Round-10 honesty pin (ISSUE 8): the flight recorder's attach-path
    cost, against the RECORDED docs/bench_attach_r10.json.

      - COUNTED: a steady-state attach produces exactly 3 trace records
        (the GetPreferredAllocation + Allocate spans, plus — since the
        r13 privilege seam — the broker.ipc crossing span of the batched
        TOCTOU revalidation) and 0 events — instrumentation creep on the
        hot path fails this, not a human reviewer;
      - the recorded overhead is within the documented bound: <= 35 us
        absolute AND <= 10% of the untraced wall (the timed half lives
        in the committed artifact so CI load cannot flip it;
        docs/observability.md).
    """
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_attach_r10.json")
    with open(path) as f:
        data = json.load(f)
    assert data["trace_spans_per_attach"] == 3
    assert data["trace_events_per_attach"] == 0
    assert data["value"] <= 35.0, data
    assert data["overhead_pct"] <= 10.0, data
    assert data["untraced_wall_p50_us"] > 0
    assert data["traced_wall_p50_us"] >= data["untraced_wall_p50_us"] * 0.9


def test_trace_records_per_attach_is_live_not_just_recorded(short_root):
    """Runtime half of the r10 pin: re-count the records-per-attach claim
    on the CURRENT tree (counted, load-insensitive — the bench-smoke job
    runs this next to the artifact pins)."""
    import os

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin import trace
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover_passthrough
    from tpu_device_plugin.kubeletapi import pb
    from tpu_device_plugin.server import TpuDevicePlugin

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"])
    pref_req = pb.PreferredAllocationRequest(container_requests=[
        pb.ContainerPreferredAllocationRequest(
            available_deviceIDs=["0000:00:04.0"], allocation_size=1)])
    alloc_req = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"])])
    plugin.GetPreferredAllocation(pref_req, None)   # warm (fragments)
    plugin.Allocate(alloc_req, None)
    trace.reset()
    try:
        plugin._pref_cache.clear()
        plugin.GetPreferredAllocation(pref_req, None)
        plugin.Allocate(alloc_req, None)
        recs = trace.snapshot()
        ops = sorted(r["op"] for r in recs)
        # r13 added the audited privilege seam: the one batched TOCTOU
        # revalidation inside Allocate records its broker.ipc crossing
        # span — by design, every privilege crossing is traceable. The
        # steady-state record set is exactly these three.
        assert ops == ["broker.ipc", "server.Allocate",
                       "server.GetPreferredAllocation"], \
            f"steady-state attach produced unexpected trace records: " \
            f"{[(r['op'], r['kind']) for r in recs]}"
        assert all(r["kind"] == "span" for r in recs)
    finally:
        trace.reset()


# ------------------------------------------------------- fleet + 4096 scale


def test_bench_scale_r11_pins_single_daemon_ceiling():
    """Round-11 honesty pins against the RECORDED docs/bench_scale_r11.json
    (artifact content — CI load cannot flip it). The scale claims:

      - COUNTED: warm discovery at 4096 devices + 1024 partitions stays
        within the PR 2 read floor (>= 5x fewer reads than cold; the
        recording measured 11 warm reads vs 30k cold);
      - COUNTED: ONE health flip across 16 resources = ONE epoch build
        fleet-wide, every other resource's pre-serialized ListAndWatch
        payload identity-reused;
      - COUNTED: the /metrics render materializes every byte exactly
        once (bytes_joined == bytes_rendered — list-append + single
        join, never incremental += concat), and the recorded scrape
        walls scale sub-quadratically (4x devices => ~4x wall, not 16x);
      - COUNTED: a 1024-claim burst commits at the group-commit bound
        (claims/commit >= 8), with the compact-separator checkpoint at
        a bounded bytes/claim and the indent=1 size it replaced recorded.
    """
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_scale_r11.json")
    with open(path) as f:
        d = json.load(f)

    assert d["n_devices"] == 4096 and d["n_partitions"] == 1024
    disc = d["discovery"]
    assert disc["read_ratio"] >= 5.0, disc
    assert disc["warm_reads"] <= 16, disc

    ep = d["epoch"]
    assert ep["one_flip_epoch_builds"] == 1, ep
    assert ep["payloads_identity_reused"] == ep["resources"] - 1, ep

    sc = d["scrape"]
    assert sc["bytes_once"] is True, sc
    assert sc["scrape_stats"]["bytes_joined"] \
        == sc["scrape_stats"]["bytes_rendered"]
    # linear assembly: 4x the devices costs ~4x the wall; the quadratic
    # += baseline would be ~16x. 10 leaves recording-noise margin while
    # still separating the regimes.
    assert sc["metrics_wall_ratio_4x"] <= 10, sc
    assert sc["status_wall_ratio_4x"] <= 10, sc

    ck = d["checkpoint"]
    assert ck["claims"] == 1024
    assert ck["claims_coalesced"] == 1024, ck
    assert ck["commits"] <= ck["group_commit_bound"], ck
    assert ck["commits"] * 8 <= ck["claims"], ck
    assert ck["bytes_per_claim"] <= 420, ck
    assert ck["compact_saving_pct"] >= 15, ck


def test_bench_fleet_r11_pins_pacing_wins():
    """Round-11 fleet pins against the RECORDED docs/bench_fleet_r11.json:
    at N=64 the paced boot storm's apiserver peak in-flight is <= 1/4 of
    the unpaced herd's (the ISSUE 9 acceptance), write p99 improves, and
    every storm held its exactly-once / zero-lost-claims contract."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_fleet_r11.json")
    with open(path) as f:
        d = json.load(f)

    cell = next(c for c in d["boot_storms"] if c["nodes"] == 64)
    assert cell["peak_inflight_ratio"] >= 4.0, cell
    assert cell["paced"]["exactly_once"], cell
    assert cell["unpaced"]["exactly_once"], cell
    assert cell["paced"]["write_wall_p99_ms"] \
        < cell["unpaced"]["write_wall_p99_ms"], cell
    # the biggest recorded fleet also held the herd down
    big = max(d["boot_storms"], key=lambda c: c["nodes"])
    assert big["nodes"] == 256
    assert big["peak_inflight_ratio"] >= 4.0, big

    attach = d["attach_storm"]
    assert attach["errors"] == []
    assert attach["prepared_total"] == attach["claims_total"] == 1024
    # fleet-wide checkpoint writes never exceed one per claim (the deep
    # coalescing pin lives in bench_scale_r11: a congested fabric
    # TRICKLES completions into each node's 10 ms window, so the fleet
    # figure measures correctness of the bound, not the burst win)
    assert attach["checkpoint_commits"] <= attach["claims_total"]
    assert d["flip_wave"]["converged"] and d["flip_wave"]["exactly_once"]
    assert d["drain_upgrade"]["converged"]
    assert d["drain_upgrade"]["exactly_once"]
    assert d["drain_upgrade"]["prepared_total"] == 1024


def test_metrics_scrape_materializes_each_byte_once_at_4096_devices():
    """LIVE half of the scrape pin (counted, CI-safe): a 4096-device
    /metrics render's assembly accounting must show every byte
    materialized exactly once (bytes_joined == bytes_rendered == the
    text's length) and parts growing with series, not series² — the
    O(series) guard the ISSUE 9 satellite asks for."""
    import types
    import threading

    from tpu_device_plugin.status import StatusServer

    def stub_plugin(i, n_devices):
        return types.SimpleNamespace(status_snapshot=lambda: {
            "resource": f"cloud-tpus.google.com/v5e-r{i:02d}",
            "socket": "/dev/null", "serving": True, "restarts": 0,
            "epoch": 1, "epoch_builds": 1,
            "preferred_cache": {"hits": 0, "misses": 0},
            "lw_resends": 0, "alloc_fragments": {"hits": 0, "misses": 0},
            "restart_backoff": {"attempts": 0, "total_attempts": 0},
            "devices": {f"0000:{d // 32:02x}:{4 + d % 32:02x}.{i}":
                        "Healthy" for d in range(n_devices)},
            "pci_errors": {}, "degraded_links": {},
            "allocations_total": 0, "recent_allocations": []},
            serving=True, resource_name=f"r{i}")

    def rig(n_plugins, devices_per_plugin):
        manager = types.SimpleNamespace(
            plugins=[stub_plugin(i, devices_per_plugin)
                     for i in range(n_plugins)],
            pending=[], native_info={}, draining=False,
            running=threading.Event())
        server = StatusServer(manager, port=0)
        try:
            text = server.metrics()
            return dict(server.scrape_stats), text
        finally:
            server._httpd.server_close()

    small, _ = rig(4, 256)          # 1024 devices
    big, text = rig(16, 256)        # 4096 devices
    # accounting gauges stay self-consistent (bytes_joined is computed
    # from the parts list, bytes_rendered from the text — equal for any
    # single-join render, so this is a consistency check, NOT the
    # regression tripwire; that is the AST scan below)
    assert big["bytes_joined"] == big["bytes_rendered"] == len(text), big
    assert small["bytes_joined"] == small["bytes_rendered"], small
    # parts grow linearly with the plugin/series count (4x rig => ~4x
    # the per-plugin series), never quadratically
    assert big["parts"] <= 4 * small["parts"], (small, big)
    assert big["series"] > small["series"]


def test_scrape_render_functions_contain_no_string_aug_assign():
    """The actual O(series²) tripwire: parse the scrape-path render
    functions (status.StatusServer.metrics, trace.render_prometheus)
    and fail on any augmented assignment whose target is not the
    `lines` parts list — reintroducing `text += line` (quadratic byte
    copying at 4096 series) trips this even if the accounting gauges
    were updated to match."""
    import ast
    import inspect
    import textwrap

    from tpu_device_plugin import status as status_mod
    from tpu_device_plugin import trace as trace_mod

    for fn in (status_mod.StatusServer.metrics,
               trace_mod.render_prometheus):
        tree = ast.parse(textwrap.dedent(inspect.getsource(fn)))
        for node in ast.walk(tree):
            if isinstance(node, ast.AugAssign):
                target = node.target
                name = target.id if isinstance(target, ast.Name) else None
                assert name == "lines", \
                    f"{fn.__qualname__} line {node.lineno}: augmented " \
                    f"assignment to {ast.dump(target)} on a scrape " \
                    f"render path — assemble into the `lines` list and " \
                    f"join once (docs/perf.md 'fleet scale')"


def test_checkpoint_compact_write_and_bytes_gauge_at_1024_claims(short_root):
    """LIVE half of the checkpoint pin (counted): 1024 claim entries
    group-commit into a COMPACT serialization (no indent, no
    key/value-separator padding), the checkpoint_bytes gauge equals the
    file's true size, and the per-claim footprint holds the recorded
    bound (346 B/claim recorded; 420 pinned)."""
    import json
    import os

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover
    from tpu_device_plugin.dra import DraDriver

    host = FakeHost(short_root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i)))
    cfg = Config().with_root(host.root)
    registry, generations = discover(cfg)
    driver = DraDriver(cfg, registry, generations, node_name="ck")
    try:
        with driver._lock:
            for i in range(1024):
                driver._checkpoint[f"bound-{i:04d}"] = {
                    "name": f"claim-{i:04d}", "namespace": "scale",
                    "spec_path": os.path.join(
                        driver.cdi_dir, f"claim-bound-{i:04d}.json"),
                    "devices": [f"cloud-tpus.google.com/claim="
                                f"claim-bound-{i:04d}"],
                    "device_raws": [f"0000:00:{4 + i % 4:02x}.0"],
                    "generation": 1,
                }
        driver._checkpoint_flush({})     # barrier: durable before asserts
        stats = driver.checkpoint_stats()
        size = os.path.getsize(driver.checkpoint_path)
        assert stats["checkpoint_bytes"] == size, (stats, size)
        with open(driver.checkpoint_path) as f:
            text = f.read()
        # compact separators: no indentation newlines, no ": " padding
        assert "\n" not in text.strip()
        assert '": ' not in text
        assert set(json.loads(text)["claims"]) >= {
            f"bound-{i:04d}" for i in range(1024)}
        assert size <= 1024 * 420, size
    finally:
        driver.stop()


def test_bench_placement_r12_pins_placement_quality():
    """Round-12 placement pins against the RECORDED
    docs/bench_placement_r12.json (counted facts, CI-safe): in every
    cell the engine lands at least as many 4-chip requests on one ICI
    ring as the naive first-free baseline (strictly more at N=16), the
    defrag advisory was applied (via migration handoff) and flipped an
    unplaceable 2x2 placeable, and both fabric logs audited
    exactly-once."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_placement_r12.json")
    with open(path) as f:
        d = json.load(f)

    assert {c["nodes"] for c in d["cells"]} >= {4, 16}
    for cell in d["cells"]:
        eng, nai = cell["engine"], cell["naive"]
        assert eng["contiguous"] >= nai["contiguous"], cell
        assert eng["mean_score"] >= nai["mean_score"], cell
        assert cell["exactly_once"], cell
        assert cell["multiclaim_exactly_once"], cell
        assert cell["defrag"]["attempted"], cell
        assert cell["defrag"]["placeable_after"], cell
        assert cell["defrag"]["moves"] >= 1, cell
    big = next(c for c in d["cells"] if c["nodes"] == 16)
    assert big["engine"]["contiguous"] > big["naive"]["contiguous"], big
    assert big["engine"]["placed"] == big["requests"], big


def test_bench_tracefleet_r17_pins_fleet_trace_and_slo_plane():
    """Round-17 fleet-trace + SLO pins against the RECORDED
    docs/bench_tracefleet_r17.json (counted facts, CI-safe):

      - the soak cell ran at 256 nodes, ended green, and its migrated
        pinned claim's cross-node story was reconstructed purely from
        the fleet trace query (the /debug/fleet/trace?trace= body —
        the story names its endpoint and spans BOTH hosts);
      - a scheduler-placed multi-host slice's SINGLE trace= query
        replayed every waterfall stage — scheduler decision, per-shard
        prepare, broker crossing, source release, handoff, destination
        prepare — time-ordered, across >= 3 hosts plus the scheduler;
      - the SLO burn-rate gauge moved under the injected latency fault
        (strictly, from a zero baseline), latched a breach, and its
        exemplar trace id was the injected request's own trace AND
        resolved to real spans on the same fleet trace query;
      - context propagation was live (propagated + attached counted,
        zero malformed drops)."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_tracefleet_r17.json")
    with open(path) as f:
        d = json.load(f)

    soak = d["soak"]
    assert soak["nodes"] == 256
    assert soak["ok"] and soak["violations"] == []
    assert soak["claim_events"] >= 2000
    story = soak["claim_story"]
    assert story is not None, "soak captured no migrated claim story"
    assert story["endpoint"] == \
        f"/debug/fleet/trace?trace={story['trace_id']}"
    assert {story["source"], story["target"]} <= set(story["nodes"])
    for needed in ("dra.prepare.claim", "dra.unprepare.claim",
                   "dra.handoff.completed"):
        assert needed in story["ops"], (needed, story["ops"])

    wf = d["waterfall"]
    assert all(wf["stages"].values()), wf["stages"]
    assert wf["host_count"] >= 3, wf
    assert "scheduler" in wf["nodes"]
    assert wf["time_ordered"] is True
    assert wf["hosts_planned"] >= 2          # genuinely multi-host
    assert wf["single_query"] == \
        f"/debug/fleet/trace?trace={wf['trace_id']}"

    s = d["slo"]
    assert s["burn_after"] > s["burn_before"]
    assert s["burn_before"] == 0.0
    assert s["breached"] and s["breaches_total"] >= 1
    assert s["exemplar_is_injected_request"] is True
    assert s["exemplar_resolved_on_fleet_trace"] is True

    prop = d["propagation"]
    assert prop["ctx_propagated_total"] > 0
    assert prop["ctx_attached_total"] > 0
    assert prop["ctx_dropped_total"] == 0


def test_fleet_trace_reconstruction_is_live_not_just_recorded_r17(
        short_root):
    """Runtime half of the r17 pin: a migrated claim's cross-host story
    reconstructs from ONE FleetFlight trace query on a live 2-node
    fleet — prepare, source release (linked), handoff completion and
    destination prepare all under the ORIGINATING trace id."""
    from tpu_device_plugin import trace as trace_mod
    from tpu_device_plugin.fleetsim import FleetSim

    trace_mod.reset()
    sim = FleetSim(n_nodes=2, devices_per_node=4, latency_s=0.0,
                   max_inflight=0, seed=3, watch=False,
                   root=short_root)
    try:
        sim.boot_storm()
        src, dst = sim.nodes
        uid = "r17-live"
        raw = sorted(src.host_view().free)[0]
        src.claim_devices(uid, [raw])
        tid = trace_mod.parse_traceparent(
            dict(src.driver._checkpoint)[uid]["traceparent"])["trace_id"]
        # migrate via the handoff machinery
        resp = src.detach([uid])
        assert not resp.claims[uid].error
        record = src.driver.export_handoff(uid)
        target = sorted(dst.host_view().free)[0]
        sim.apiserver.add_claim(
            "fleet", uid, uid, dst.driver.driver_name,
            [{"device": dst.host_view().names[target]}])
        dst.driver.import_handoff(record)
        resp = dst.attach([uid])
        assert not resp.claims[uid].error
        story = sim.fleet_flight().trace(tid)
        assert {src.name, dst.name} <= set(story["nodes"])
        ops = set(story["ops"])
        for needed in ("dra.prepare.claim", "dra.unprepare.claim",
                       "dra.handoff.completed", "broker.ipc"):
            assert needed in ops, (needed, sorted(ops))
        # destination prepare CONTINUED the origin trace (link joined)
        dest_prep = [r for r in story["spans"]
                     if r["op"] == "dra.prepare.claim"
                     and r["node"] == dst.name]
        assert dest_prep and dest_prep[-1]["link"]["trace_id"] == tid
    finally:
        sim.stop()
        trace_mod.reset()


def test_bench_fleetplace_r16_pins_cluster_placement():
    """Round-16 fleet-placement pins against the RECORDED
    docs/bench_fleetplace_r16.json (counted facts, CI-safe): the main
    cell ran at 256 simulated nodes with CROSS-HOST slices through the
    watch-stream slice cache, the engine beats the naive first-free
    baseline on contiguity (strictly, and on mean score), the
    fragmentation-over-churn curves are recorded for both arms, the
    global defrag wave flipped an unplaceable 2x2 placeable via the
    migration-handoff machinery, and EVERY cell audited exactly-once on
    the fabric write log, the fabric multiclaim log, and the
    cluster-wide scheduler commit log (fabric cross-check agreeing)."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_fleetplace_r16.json")
    with open(path) as f:
        d = json.load(f)

    for cell in d["cells"]:
        assert cell["exactly_once"], cell
        assert cell["multiclaim_exactly_once"], cell
        assert cell["scheduler_audit_exactly_once"], cell
        assert cell["fabric_agrees"], cell

    main = next(c for c in d["cells"] if c.get("nodes") == 256
                and "engine" in c)
    eng, nai = main["engine"], main["naive"]
    assert main["chips"] == 2048
    assert eng["contiguous"] > nai["contiguous"], main
    assert eng["mean_score"] > nai["mean_score"], main
    # cross-host slices were genuinely exercised and landed contiguous
    assert eng["cross_host_requests"] >= 4, main
    assert eng["cross_host_contiguous"] >= 1, main
    # decisions consumed the watch-stream Reflector's slice cache
    assert main["watch"]["cache_syncs"] >= 1, main
    assert main["watch"]["cache_slices"] == 256, main
    # the compiled-once selector evaluated without a single unknown-
    # attribute or type miss against the published topology attributes
    assert main["selector"]["evals_total"] > 0, main
    assert main["selector"]["unknown_attribute_total"] == 0, main
    assert main["selector"]["type_mismatch_total"] == 0, main
    # fragmentation-over-churn curves recorded for BOTH arms
    curve = main["fragmentation_over_churn"]
    assert len(curve) >= 5, main
    assert all("engine_fragmentation" in p and "naive_fragmentation"
               in p for p in curve)
    assert main["naive_multiclaim_exactly_once"], main

    wave = next(c for c in d["cells"]
                if c.get("cell") == "global_defrag_wave")
    assert wave["moves_applied"] == wave["moves_planned"] >= 1, wave
    assert wave["handoffs_completed"] == wave["moves_applied"], wave
    assert not wave["placeable_before"] and wave["placeable_after"], wave
    assert wave["fragmentation_after"] < wave["fragmentation_before"], \
        wave


def test_placement_scoring_zero_locks_is_live_not_just_recorded(
        short_root):
    """LIVE half of the r12 placement pin (the ISSUE 10 CI guard,
    extending the epoch gate): the ICI placement scoring every
    GetPreferredAllocation answer pays runs inside the
    `placement.score` read-path bracket and acquires ZERO registered
    locks in steady state — counted by lockdep proxies, so CI load
    cannot flip the verdict."""
    import os as _os

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin import lockdep
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover_passthrough
    from tpu_device_plugin.kubeletapi import pb
    from tpu_device_plugin.server import TpuDevicePlugin

    with lockdep.scoped():
        host = FakeHost(short_root)
        for i in range(8):
            host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                                   device_id="0063",
                                   iommu_group=str(11 + i),
                                   numa_node=i // 4))
        cfg = Config().with_root(host.root)
        _os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, _ = discover_passthrough(cfg)
        plugin = TpuDevicePlugin(cfg, "v5e", registry,
                                 registry.devices_by_model["0063"],
                                 torus_dims=(2, 4))
        ids = [d.bdf for d in registry.devices_by_model["0063"]]
        req = pb.PreferredAllocationRequest(container_requests=[
            pb.ContainerPreferredAllocationRequest(
                available_deviceIDs=ids, allocation_size=4)])
        plugin.GetPreferredAllocation(req, None)     # warm-up
        lockdep.reset()
        for _ in range(5):
            plugin.GetPreferredAllocation(req, None)
        stats = lockdep.path_stats()
        rec = stats["placement.score"]
        assert rec["calls"] >= 5, stats
        assert rec["lock_acquisitions"] == 0, \
            f"placement scoring acquired {rec['lock_acquisitions']} " \
            f"registered lock(s) on the preferred-allocation path"
        # the scoring is live, not vestigial: a full free host scores 1.0
        assert plugin.status_snapshot()["placement"]["last_score"] == 1.0


def test_bench_broker_r13_pins_crossing_budget():
    """Round-13 honesty pin (ISSUE 11) against the RECORDED
    docs/bench_broker_r13.json: the privilege boundary costs at most 2
    COUNTED crossings per steady-state attach in BOTH modes (one batched
    TOCTOU revalidation, at most one TTL-expired iommufd probe) — the
    wall overhead of the spawned mode is recorded next to it, unclaimed,
    because the IPC RTT is an environment property like the r09 syscall
    floor."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_broker_r13.json")
    with open(path) as f:
        data = json.load(f)
    assert data["crossings_per_attach_inproc"] <= 2, data
    assert data["crossings_per_attach_spawn"] <= 2, data
    # at least ONE crossing: the TOCTOU revalidation must cross the
    # boundary — zero would mean the guard got cached away
    assert data["crossings_per_attach_inproc"] >= 1, data
    assert data["crossings_per_attach_spawn"] >= 1, data
    # both modes measured on the same host shape, overhead recorded
    assert data["attach_wall_p50_us_spawn"] > 0
    assert data["crossing_overhead_p50_us"] == pytest.approx(
        data["attach_wall_p50_us_spawn"]
        - data["attach_wall_p50_us_inproc"], abs=0.2)


def test_broker_crossings_per_attach_is_live_not_just_recorded(short_root):
    """Runtime half of the r13 pin: count the crossing budget on the
    CURRENT tree (AtomicCounter reads; load-insensitive), against the
    in-process seam the zero-lock gates also run on."""
    import os
    from dataclasses import replace

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin import broker
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover_passthrough
    from tpu_device_plugin.kubeletapi import pb
    from tpu_device_plugin.server import TpuDevicePlugin

    host = FakeHost(short_root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i)))
    cfg = replace(Config().with_root(host.root), shared_scan_ttl_s=60.0)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover_passthrough(cfg)
    client = broker.InProcessBroker()
    prev = broker.set_client(client)
    try:
        plugin = TpuDevicePlugin(cfg, "v4", registry,
                                 registry.devices_by_model["0062"])
        req = pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(
                devices_ids=[d.bdf
                             for d in registry.devices_by_model["0062"]])])
        plugin.Allocate(req, None)          # cold: fragments + iommufd
        before = client.crossings.value
        plugin.Allocate(req, None)          # steady state
        per_attach = client.crossings.value - before
        assert 1 <= per_attach <= 2, per_attach
    finally:
        broker.set_client(prev)


def test_bench_autopilot_r14_pins_watch_convergence_soak():
    """Round-14 pins against the RECORDED docs/bench_autopilot_r14.json
    (ISSUE 12 acceptance): the 256-node / 100k-claim-event autopilot
    soak with EVERY overlapping storm type completed green under watch
    chaos + kubeapi.watch faults (continuous invariant checks, final
    quiesce with zero orphans, exactly-once fabric + multiclaim
    audits), and watch-driven convergence paid >= 5x fewer steady-state
    fabric reads than guarded-PUT read/repair polling."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_autopilot_r14.json")
    with open(path) as f:
        d = json.load(f)

    assert d["quick"] is False
    soak = d["soak"]
    assert soak["ok"] and soak["converged"], soak.get("violations")
    assert soak["violations"] == []
    assert soak["config"]["nodes"] >= 256
    assert soak["config"]["watch"] and soak["config"]["watch_chaos"] \
        and soak["config"]["watch_faults"]
    c = soak["counters"]
    assert c["claim_events"] >= 100_000
    # invariants were checked CONTINUOUSLY, not only at the end
    assert c["invariant_checks"] >= 5
    # every storm type of the acceptance list actually overlapped
    for storm in ("prepares", "unprepares", "multiclaims_placed",
                  "flip_storms", "unplugs", "readmits", "migrations",
                  "upgrades", "republish_waves"):
        assert c[storm] >= 1, (storm, c)
    fi = soak["final_invariants"]
    assert fi["ok"] and fi["exactly_once"] \
        and fi["multiclaim_exactly_once"]
    assert fi["orphaned_claims"] == 0       # zero lost/orphaned claims
    # the watch plane carried the soak and its faults fired throughout
    assert soak["watch"]["watch_events_total"] > 0
    assert sum(soak["faults_fired"].values()) >= 10
    # a cross-node flight-recorder claim story was reconstructed
    story = soak["claim_story"]
    assert story is not None and story["spans"] >= 2
    assert story["source"] != story["target"]

    rr = d["read_repair"]
    assert rr["read_reduction_x"] >= 5.0, rr
    assert rr["watch_reads"] < rr["poll_reads"]
    assert rr["wipe_healed_by_watch"] and rr["exactly_once"]


def test_bench_transport_r15_pins_preserialized_attach():
    """Round-15 honesty pins against the RECORDED
    docs/bench_transport_r15.json (ISSUE 13, transport endgame):

      - the environment-calibrated attach wall (raw wall minus the
        counted-syscalls x in-run-calibrated sysfs floor, r09's
        discipline) is under the 200 us acceptance target with the byte
        plane live;
      - the serialization A/B holds on the ISOLATED pair (response
        construction only, revalidation stubbed on both arms — the
        end-to-end arms are recorded but unpinned because the live
        syscall floor's variance dominates them): the byte plane
        assembles a response cheaper than build-protos + serialize;
      - COUNTED: a warm attach reuses exactly 2 pre-serialized responses
        (GetPreferredAllocation + Allocate) and pays 0 response-plane
        serializations;
      - the TOCTOU revalidation stayed live (readlink per allocated
        member — caching it away would be the dishonest speedup);
      - the wall decomposition is present and each non-derived component
        was measured in-run (sched wakeup, noop RTT, syscall
        calibration).
    """
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_transport_r15.json")
    with open(path) as f:
        d = json.load(f)

    # the acceptance pin: sub-200 us environment-calibrated attach wall —
    # run-median wall minus the TIME-INTERLEAVED run-median floor (both
    # halves of the subtraction saw the same co-tenant load
    # distribution; the per-epoch paired drift is recorded alongside)
    assert d["value"] < 200, d
    assert d["value"] == pytest.approx(
        d["wall_p50_us"] - d["sysfs_io_floor_p50_us"], abs=0.2)
    assert len(d["calibrated_per_epoch_us"]) >= 4
    # the serialization A/B (isolated pair — syscall-noise-free)
    assert d["serialization_bytes_p50_us"] \
        <= d["serialization_reserialize_p50_us"], d
    assert d["serialization_saved_p50_us"] >= 0, d
    # the end-to-end arms are recorded alongside (unpinned)
    assert d["ab_bytes_wall_p50_us"] > 0 \
        and d["ab_reserialize_wall_p50_us"] > 0
    # counted: the byte plane is live, not just recorded
    assert d["bytes_reused_per_warm_attach"] == 2
    assert d["serializations_per_warm_attach"] == 0
    # counted: the TOCTOU guard stayed live
    sys_counts = d["sysfs_syscalls_per_attach"]
    assert sys_counts["readlink"] == d["allocation_size"]
    assert d["sysfs_io_floor_p50_us"] > 0
    # the breakdown components were measured in-run
    assert d["sched_wakeup_p50_us"] > 0
    assert d["grpc_noop_rtt_p50_us"] > 0
    assert d["syscall_cost_calibration_us"]["stat"] > 0
    assert d["transport_wall_p50_us"] > 0
    assert d["devices_advertised"] == 8 and d["allocation_size"] == 4


def test_attach_bytes_reused_is_live_not_just_recorded_r15(short_root):
    """Runtime half of the r15 pin (counted, load-insensitive — the CI
    bench-smoke job runs this next to the artifact pins): a WARM attach
    on the current tree serves both hot responses from pre-serialized
    bytes (2 reused), pays zero response-plane serializations, and the
    raw payloads parse back identical to the message path's protos."""
    import os

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin import kubeletapi as kapi
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover_passthrough
    from tpu_device_plugin.kubeletapi import pb
    from tpu_device_plugin.server import TpuDevicePlugin

    host = FakeHost(short_root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i), numa_node=i // 2))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             torus_dims=generations["0062"].host_topology)
    ids = sorted(registry.bdf_to_group)
    pref_req = pb.PreferredAllocationRequest(container_requests=[
        pb.ContainerPreferredAllocationRequest(
            available_deviceIDs=ids, allocation_size=2)])
    alloc_req = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=ids[:2])])
    # warm-up: memo miss + fragment builds are allowed to serialize
    plugin.GetPreferredAllocation(pref_req, None)
    plugin.Allocate(alloc_req, None)
    expected_pref = plugin.GetPreferredAllocation(pref_req, None)
    expected_alloc = plugin._planner.allocate_response(
        alloc_req, epoch=plugin._store.current.epoch_id)

    r0 = plugin._alloc_bytes_reused.value
    s0 = plugin._alloc_serializations.value
    pref_raw = plugin.GetPreferredAllocation(pref_req, kapi.RAW_CONTEXT)
    alloc_raw = plugin.Allocate(alloc_req, kapi.RAW_CONTEXT)
    assert plugin._alloc_bytes_reused.value - r0 == 2, \
        "warm attach did not serve both responses from the byte plane"
    assert plugin._alloc_serializations.value - s0 == 0, \
        "warm attach paid a response-plane serialization"
    assert pb.PreferredAllocationResponse.FromString(
        pref_raw.data) == expected_pref
    assert pb.AllocateResponse.FromString(alloc_raw.data) == expected_alloc


def test_bench_selfheal_r18_pins_closed_loop():
    """Round-18 self-heal pins against the RECORDED
    docs/bench_selfheal_r18.json (counted facts, CI-safe):

      - the soak ran at 256 nodes with the self-heal drill armed and
        ended green (every storm invariant plus every drill link);
      - EVERY link of the closed loop held: the ramped delay fault
        latched a breach with an exemplar, the remediation engine acted
        through the policy remediate gate (call counted), the exemplar
        attributed to the victim node (placement-biased away), good
        traffic recovered the burn, and every knob rolled back;
      - the story's burn provably ROSE at breach and fell back under
        the fast threshold at recovery;
      - ONE /debug/fleet/trace?trace=<exemplar> query carried the slow
        node-stamped publish, the remediation actions and the
        rollbacks — the endpoint is named in the story;
      - zero remediation errors, zero vetoes, and no hysteresis skips
        were needed for the single incident (no flapping)."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_selfheal_r18.json")
    with open(path) as f:
        d = json.load(f)

    soak = d["soak"]
    assert soak["nodes"] == 256
    assert soak["ok"] and soak["violations"] == []
    assert soak["claim_events"] >= 2000
    assert all(d["chain"].values()), d["chain"]

    story = d["story"]
    assert story["breached"] is True and story["recovered"] is True
    assert story["burn_at_breach"] > 14.4      # over the fast threshold
    assert story["burn_at_recovery"] < 14.4
    assert story["actions"] >= 2 and story["rollbacks"] >= 2
    assert story["policy_remediate_calls"] >= story["actions"]
    assert story["endpoint"] == \
        f"/debug/fleet/trace?trace={story['trace_id']}"
    assert story["victim"] in story["nodes"]
    acted = {a["action"] for a in story["active_actions"]}
    assert {"pacer_backoff", "node_bias"} <= acted
    for op in ("dra.publish", "kubeapi.request", "remediation.action",
               "remediation.rollback"):
        assert op in story["ops"], (op, story["ops"])
    c = story["counters"]
    assert c["errors_total"] == 0 and c["vetoes_total"] == 0
    assert c["actions_total"] == c["rollbacks_total"]


def test_selfheal_closed_loop_is_live_not_just_recorded_r18(short_root):
    """Runtime half of the r18 pin: the drill itself — breach latch,
    policy-gated knob turns, exemplar->node attribution, latched
    recovery, rollback — runs green on a live 2-node fleet, and the
    whole chain reconstructs from ONE fleet-trace query."""
    from tpu_device_plugin import faults
    from tpu_device_plugin import trace as trace_mod
    from tpu_device_plugin.autopilot import AutopilotConfig, FleetAutopilot
    from tpu_device_plugin.fleetsim import FleetSim

    trace_mod.reset()
    sim = FleetSim(n_nodes=2, devices_per_node=4, latency_s=0.0,
                   max_inflight=0, seed=18, watch=False,
                   root=short_root)
    try:
        sim.boot_storm()
        cfg = AutopilotConfig(nodes=2, selfheal=True,
                              selfheal_fault_ramp_s=0.5)
        pilot = FleetAutopilot(cfg, sim=sim)
        story = pilot._selfheal_drill()
        assert pilot.violations == [], pilot.violations
        assert story["breached"] and story["recovered"]
        assert story["actions"] >= 2 and story["rollbacks"] >= 2
        assert story["victim"] in story["nodes"]
        for op in ("remediation.action", "remediation.rollback",
                   "kubeapi.request"):
            assert op in story["ops"], (op, story["ops"])
    finally:
        faults.reset()
        sim.stop()
        trace_mod.reset()


def test_bench_fleetsched_r19_pins_sharded_storm():
    """Round-19 sharded-scheduler pins against the RECORDED
    docs/bench_fleetsched_r19.json (counted facts, CI-safe):

      - the storm cell ran at 4096 nodes / 16384 claims across FOUR
        schedulers and placed EVERYTHING — no phantom "unplaceable"
        (the wait_synced-vs-accountant boot race this round fixed);
      - N=4 sharded throughput is >= 4x the single-scheduler
        per-claim-commit baseline, with p99 decision latency recorded
        in both cells;
      - the contended (unpartitioned) cell actually exercised the
        optimistic-concurrency path: counted conflicts, counted
        replans, a non-zero abort rate — and STILL audited
        exactly-once;
      - EVERY cell proves <=1 commit per claim uid on all three audit
        logs: multiclaim commit log, per-slice write-generation log,
        node checkpoints."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_fleetsched_r19.json")
    with open(path) as f:
        d = json.load(f)

    single, sharded, contended = d["single"], d["sharded"], d["contended"]
    assert single["nodes"] == 4096 and single["schedulers"] == 1
    assert single["per_claim_commits"] is True
    assert sharded["nodes"] == 4096 and sharded["schedulers"] == 4
    assert sharded["claims"] == 16384 and sharded["partition"] is True
    assert sharded["unplaceable"] == 0 and sharded["placed"] == 16384
    assert d["speedup_n4_vs_single"] >= 4.0, d["speedup_n4_vs_single"]
    assert contended["commit_conflicts"] > 0
    assert contended["replans"] > 0
    assert contended["conflict_abort_rate"] > 0
    for name, cell in (("single", single), ("sharded", sharded),
                       ("contended", contended)):
        assert cell["exactly_once"], (name, cell)
        logs = cell["exactly_once_logs"]
        for log in ("multiclaim", "write_log", "placement", "checkpoint"):
            assert logs[log], (name, log, logs)
        assert cell["decision_p99_ms"] > 0, (name, cell)
        assert cell["decision_waves"] > 0, (name, cell)
        assert cell["frag_delta_applies"] > 0, (name, cell)


def test_fleetsched_frag_delta_single_flip_at_4096_nodes_is_o1():
    """Runtime half of the r19 pin, COUNTED: at 4096 nodes, ONE watch
    event costs ONE slice reparse and ZERO full recomputes — the
    accountant's decision-state upkeep scales with the event, not the
    fleet. (A regression to snapshot-rebuild accounting would show
    4096 reparses here.)"""
    from tpu_device_plugin.fleetplace import FragAccountant
    from tpu_device_plugin.fleetsim import synthetic_slice_objects

    objs, pod_dims = synthetic_slice_objects(4096, devices_per_node=8)
    for i, obj in enumerate(objs):
        obj["metadata"]["resourceVersion"] = str(i + 1)
    acc = FragAccountant(pod_dims=pod_dims)
    acc.on_sync({o["metadata"]["name"]: o for o in objs})
    assert acc.stats["slice_reparses_total"].value == 4096
    reparses0 = acc.stats["slice_reparses_total"].value
    recomputes0 = acc.stats["frag_full_recomputes_total"].value
    version0 = acc.version

    flip = dict(objs[7])
    flip["metadata"] = dict(flip["metadata"], resourceVersion="999999")
    acc.on_event({"type": "MODIFIED", "object": flip})

    assert acc.stats["slice_reparses_total"].value - reparses0 == 1
    assert acc.stats["frag_full_recomputes_total"].value \
        - recomputes0 == 0
    assert acc.stats["frag_delta_applies_total"].value >= 1
    assert acc.version > version0       # readers see the new state


def test_bench_brokeripc_r20_pins_framing_batch_and_ring():
    """Round-20 honesty pins (ISSUE 18) against the RECORDED
    docs/bench_brokeripc_r20.json — the three fast-path claims on their
    load-insensitive axes:

      - framing overhead (frame bytes minus the operand floor, same
        corpus, both codecs SAME-RUN) >= 3x smaller than JSON; the
        wall-clock framing costs ride along UNPINNED because the varint
        codec is pure Python (decode loses to C json.loads — recorded,
        not hidden);
      - ONE counted crossing per batched multi-group claim revalidation
        and per batched 8-probe health cycle (vs 16 / 8 unbatched);
      - the shared-memory response ring attached and served hits live.
    """
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_brokeripc_r20.json")
    with open(path) as f:
        d = json.load(f)

    assert d["value"] >= 3.0, d["value"]
    assert d["value"] == pytest.approx(
        d["framing_overhead_json_bytes"]
        / d["framing_overhead_bin_bytes"], abs=0.01)
    # the floor really was subtracted (overheads are the small parts)
    assert d["framing_corpus_floor_bytes"] > d["framing_overhead_bin_bytes"]
    # wall numbers recorded next to the pin, unclaimed
    for k in ("framing_encode_json_us", "framing_encode_bin_us",
              "framing_decode_json_us", "framing_decode_bin_us",
              "syscall_floor_p50_us", "crossing_rtt_p50_us_json",
              "crossing_rtt_p50_us_bin"):
        assert d[k] > 0, k

    # batching: ONE crossing per claim batch at EVERY group size, and
    # per 8-probe health batch — counted live during the bench run
    assert d["batched_claim_crossings"] == 1.0, d
    assert d["batched_claim_unbatched_equiv"] == 16
    assert d["chip_alive_batch_crossings"] == 1.0, d
    assert d["chip_alive_batch_probes"] == 8

    # the ring attached over the real handshake and served hits
    assert d["ring_attached"] is True
    assert d["ring_hits"] > 0, d
    assert d["ring_hit_p50_us"] > 0
    # both peers negotiated what they asked for
    assert d["negotiated_version_json_peer"] == 1
    assert d["negotiated_version_bin_peer"] == 2


def test_brokeripc_framing_overhead_reduction_is_live_not_just_recorded():
    """Runtime half of the r20 framing pin: recompute the byte-overhead
    reduction with the CURRENT codecs on the hot-mix corpus — bytes, not
    wall time, so the guard is load-insensitive. A regression that
    bloats the binary framing (or quietly routes hot fields through the
    JSON catch-all) trips this without any bench run."""
    from tpu_device_plugin import brokeripc
    from tpu_device_plugin.epoch import encode_varint

    span = {"op": "dra.prepare", "seq": 7,
            "trace_id": "c0ffee0ddeadbeefc0ffee0ddeadbeef",
            "span_id": "beefc0ffee0ddead"}
    base = "/sys/bus/pci/devices/0000:00:04.0"
    corpus = [
        ({"op": "read_attr", "seq": 101, "span": span,
          "path": base + "/vendor"},
         {"ok": True, "seq": 101, "data": "0x1ae0"}),
        ({"op": "read_link", "seq": 102, "span": span,
          "path": base + "/iommu_group"},
         {"ok": True, "seq": 102,
          "target": "../../../kernel/iommu_groups/11"}),
        ({"op": "chip_alive", "seq": 103, "span": span,
          "pci_base": "/sys/bus/pci/devices", "bdf": "0000:00:04.0",
          "node": "/dev/vfio/11"},
         {"ok": True, "seq": 103, "alive": True}),
    ]

    def floor(v):
        if isinstance(v, bool):
            return 1
        if isinstance(v, int):
            return len(encode_varint(brokeripc._zigzag(v)))
        if isinstance(v, str):
            return len(v.encode("utf-8"))
        if isinstance(v, dict):
            return sum(floor(x) for x in v.values() if x is not None)
        return 0

    enc = brokeripc.RequestEncoder()
    jo = bo = 0
    for req, rep in corpus:
        for obj, is_req in ((req, True), (rep, False)):
            fl = floor(obj)
            j = len(brokeripc._encode(obj, binary=False))
            b = len(enc.encode_frame(obj) if is_req
                    else brokeripc._encode(obj, binary=True))
            # both frames decode back to the same request — the
            # reduction is compression, not lossiness
            assert brokeripc.decode_body(
                (enc.encode_frame(obj) if is_req else
                 brokeripc._encode(obj, binary=True))
                [brokeripc._HEADER_SIZE:]) == obj
            jo += j - fl
            bo += b - fl
    assert jo / bo >= 3.0, (jo, bo)


def test_brokeripc_batched_claim_and_ring_hit_live(short_root):
    """Runtime half of the r20 crossing pins, COUNTED against a real
    in-thread BrokerServer over a real unix socket: a multi-group claim
    revalidation batch (4 partitions x read_attr+read_link) costs ONE
    privilege crossing, and a repeated hot read is served from the
    shared-memory ring with ZERO additional crossings."""
    import os

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin.broker import BrokerServer, SocketBrokerClient

    host = FakeHost(short_root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i)))
    sock = os.path.join(short_root, "broker.sock")
    server = BrokerServer(sock, root=short_root)
    server.start()
    client = SocketBrokerClient(sock, ring_ttl_s=60.0)
    try:
        assert client.negotiated_version == 2
        pci = os.path.join(short_root, "sys/bus/pci/devices")
        subs = []
        for i in range(4):
            bdf = f"0000:00:{4 + i:02x}.0"
            subs.append({"op": "read_attr",
                         "path": os.path.join(pci, bdf, "vendor")})
            subs.append({"op": "read_link",
                         "path": os.path.join(pci, bdf, "iommu_group")})
        before = client.crossings.value
        results = client.run_batch(subs)
        assert [r["ok"] for r in results] == [True] * 8, results
        assert client.crossings.value - before == 1, \
            "multi-group claim batch must cost exactly ONE crossing"
        assert client.batched_ops.value == 8

        # ring: the publish rides the first (socket) read; the repeat
        # is a shared-memory hit — NO crossing, same bytes
        path = os.path.join(pci, "0000:00:04.0", "vendor")
        first = client.read_attr("0000:00:04.0", path)
        before = client.crossings.value
        hits_before = client.ring_hits.value
        again = client.read_attr("0000:00:04.0", path)
        assert again == first == b"0x1ae0\n"
        assert client.crossings.value == before, \
            "a ring hit must not cross the privilege boundary"
        assert client.ring_hits.value == hits_before + 1
    finally:
        client.close()
        server.stop()


# ------------------------------------------ restart-to-ready (round 21)


def test_bench_restart_r21_pins_restart_fast_path():
    """Round-21 honesty pins against the RECORDED
    docs/bench_restart_r21.json (file content, so CI load cannot flip
    it). The claims this PR makes:

      - COUNTED: the snapshot-warm boot at 4096 devices does >= 10x
        fewer discovery sysfs reads than the cold walk (recorded raw
        counts alongside — warm is a handful of listdir/stat probes,
        cold is ~10 reads/device);
      - TIMED (recorded, medians over multiple samples): warm
        restart-to-ready wall >= 3x lower than cold at 4096;
      - the two-wave boot's first-resource-ready STRICTLY precedes
        all-resources-ready under a membership invalidation;
      - a torn cache is refused, converges via the cold walk, and the
        next boot is warm again (the fallback re-seeds);
      - prepared claims survive cold AND warm restarts exactly-once,
        and the post-restart kubelet replay reuses restored
        pre-serialized ack bytes;
      - the 256-node rolling upgrade's node-seconds-unready is >= 2x
        better warm than the pre-snapshot baseline, with the modeled
        per-read host-IO cost recorded and IDENTICAL for both waves
        (the read-count ratio does the work, not the model).
    """
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_restart_r21.json")
    with open(path) as f:
        data = json.load(f)

    key = data["single_node"][-1]
    assert key["devices"] == 4096
    assert key["reads_ratio"] >= 10.0, key
    assert key["cold_reads"] >= 10 * 4096, key
    assert key["warm_reads"] <= 8, key
    assert key["wall_ratio"] >= 3.0, key
    assert key["samples"]["cold"] >= 2 and key["samples"]["warm"] >= 3

    two = data["two_wave"]
    assert two["invalidated"] >= 1
    assert two["first_resource_ready_ms"] \
        < two["all_resources_ready_ms"], two
    assert two["first_strictly_before_all"] is True

    corrupt = data["corrupt_cache"]
    assert corrupt["fallback_outcome"] == "corrupt"
    assert corrupt["fallback_converged"] is True
    assert corrupt["next_boot_warm"] is True
    assert corrupt["fallback_reads"] >= corrupt["devices"] * 5

    claims = data["claims"]
    assert claims["exactly_once"] is True
    assert claims["violations"] == []
    assert claims["prepared_claims"] >= 4
    assert claims["replay_ack_bytes_reused"] > 0
    assert claims["warm_restart_reads"] * 10 \
        <= claims["cold_restart_reads"]

    roll = data["rolling_upgrade"]
    assert roll["nodes"] == 256
    assert roll["unready_ratio"] >= 2.0, roll
    assert roll["exactly_once"] is True
    assert roll["baseline"]["paths"] == {"cold": 256}
    assert roll["fast"]["paths"] == {"snapshot": 256}
    # modeled IO honesty: same per-read cost charged to BOTH waves,
    # and the fast wave's read total is the thing that actually shrank
    assert roll["baseline"]["sysfs_read_cost_ms"] \
        == roll["fast"]["sysfs_read_cost_ms"]
    assert roll["fast"]["reads_total"] * 10 \
        <= roll["baseline"]["reads_total"]


def test_restart_warm_read_savings_is_live_not_just_recorded(short_root):
    """Runtime half of the r21 pin, COUNTED on the CURRENT tree at 64
    devices (load-insensitive): a full PluginManager cold boot against
    a live fake kubelet, then a snapshot-warm boot of a fresh manager —
    warm must do at least 10x fewer discovery reads, ship the same
    resource, and stamp the readiness edges."""
    import os

    from tests.fakehost import FakeChip, FakeHost, FakeKubelet
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import count_reads
    from tpu_device_plugin.lifecycle import PluginManager

    host = FakeHost(short_root)
    for i in range(64):
        host.add_chip(FakeChip(f"0000:{i // 32:02x}:{4 + i % 32:02x}.0",
                               device_id="0063", iommu_group=str(11 + i),
                               numa_node=i // 32))
    cfg = Config().with_root(short_root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    try:
        mgr = PluginManager(cfg)
        with count_reads() as cold:
            mgr.start()
        assert mgr.boot_stats["boot_path"] == "cold"
        cold_plugins = len(mgr.plugins)
        assert cold_plugins == 1
        mgr.stop()

        mgr = PluginManager(cfg)
        with count_reads() as warm:
            mgr.start()
        stats = mgr.boot_stats
        assert stats["boot_path"] == "snapshot", stats
        assert stats["snapshot_outcome"] == "loaded"
        assert stats["invalidated"] == 0
        assert len(mgr.plugins) == cold_plugins
        assert 0 < stats["first_resource_ready_ms"] \
            <= stats["all_resources_ready_ms"] \
            <= stats["restart_ready_ms"]
        mgr.stop()

        assert warm.reads * 10 <= cold.reads, (warm.reads, cold.reads)
    finally:
        kubelet.stop()
