"""Host discovery: VFIO-bound TPU chips, /dev/accel correlation, partitions.

TPU analogue of the reference's sysfs walks
(`createIommuDeviceMap` device_plugin.go:187-247, `createVgpuIDMap` :255-291):
walk /sys/bus/pci/devices filtering vendor 1ae0 + vfio drivers, read the
iommu_group symlink / NUMA node / device id, then additionally correlate
/sys/class/accel char devices and stamp each chip with ICI torus coordinates.
Discovery is one-shot and side-effect free: it returns an immutable Registry.
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

from . import faults
from . import lockdep
from .config import Config
from .naming import GenerationInfo, load_generation_map
from .readcount import ReadWindow, WindowRegistry  # noqa: F401 (ReadWindow re-exported)
from .registry import Registry, TpuDevice, TpuPartition
from .topology import assign_coords, load_topology_hints

log = logging.getLogger(__name__)

_ACCEL_RE = re.compile(r"^accel(\d+)$")


# --- sysfs access accounting (shared machinery: readcount.py) ----------------
# Every sysfs access (file read, readlink, listdir, stat) made by this
# module inside an open window bumps its counters; the perf-honesty guard
# and `bench.py --discovery` assert on these counts because read COUNTS —
# unlike wall clock on a shared CPU — are load-insensitive.

_read_registry = WindowRegistry()
_note = _read_registry.note


def count_reads(confine_thread: bool = False):
    """Count this module's sysfs accesses inside the with-block. Windows
    nest: each one sees every access made while it is open. With
    `confine_thread`, only the opening thread's accesses count — the
    HostSnapshot stats gauge uses this so concurrent readers on other
    threads (DRA prepare, vtpu monitor) cannot inflate it."""
    return _read_registry.window(confine_thread)


def _listdir(path: str) -> List[str]:
    _note(path)
    return sorted(os.listdir(path))


def _isdir(path: str) -> bool:
    _note(path)
    return os.path.isdir(path)


def _stat_sig(path: str) -> Optional[Tuple[int, int]]:
    """(mtime_ns, size) change signature of a config/override file."""
    _note(path)
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


def _stat_sig_raw(path: str) -> Optional[Tuple[int, int]]:
    """_stat_sig WITHOUT read accounting: used when capturing signatures
    at snapshot-save time (post-boot bookkeeping, not discovery cost)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


def _stat_sigs_batched(paths: List[str]) -> List[Optional[Tuple[int, int]]]:
    """Per-path dir stat signatures for snapshot revalidation, counted one
    read each. In spawn mode the whole pass rides the broker's `run_batch`
    (`stat_sig` sub-ops, ONE crossing per MAX_BATCH_OPS chunk) so a
    4096-device revalidation never pays per-device crossings; in-process
    mode (and any broker degradation) stats locally — same answers, same
    counted cost."""
    for p in paths:
        _note(p)
    if not paths:
        return []
    from . import broker as broker_mod
    client = broker_mod.peek_client()
    if client is not None and getattr(client, "mode", "") == "spawn":
        try:
            from . import brokeripc
            out: List[Optional[Tuple[int, int]]] = []
            for start in range(0, len(paths), brokeripc.MAX_BATCH_OPS):
                chunk = paths[start:start + brokeripc.MAX_BATCH_OPS]
                results = client.run_batch(
                    [{"op": "stat_sig", "path": p} for p in chunk])
                for res in results:
                    sig = res.get("sig") if res.get("ok") else None
                    out.append(tuple(sig) if sig else None)
            return out
        except Exception as exc:
            log.warning("batched stat_sig via broker failed (%s); "
                        "falling back to local stats", exc)
    return [_stat_sig_raw(p) for p in paths]


def _atomic_write_json(path: str, payload: dict) -> None:
    """Crash-safe snapshot write: temp file in the target dir + fsync +
    rename, so a reader observes either the old envelope or the new one,
    never a torn write (same discipline as the DRA checkpoint)."""
    directory = os.path.dirname(path) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".snapshot-")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# --- low-level sysfs readers (unit-testable against tmpdir fixtures) ---------

def read_id_from_file(path: str) -> Optional[str]:
    """Read a sysfs hex id file, stripping the 0x prefix.

    The reference slices bytes 2: unconditionally (device_plugin.go:294-302);
    we only strip an actual `0x` so hand-written fixtures also parse.
    """
    _note(path)
    try:
        with open(path, "r", encoding="ascii", errors="replace") as f:
            data = f.read().strip()
    except OSError as exc:
        log.debug("could not read %s: %s", path, exc)
        return None
    return data[2:] if data.lower().startswith("0x") else data


def read_link_basename(path: str) -> Optional[str]:
    """Basename of a sysfs symlink target (driver name, iommu group number)."""
    _note(path)
    try:
        return os.path.basename(os.readlink(path))
    except OSError as exc:
        log.debug("could not readlink %s: %s", path, exc)
        return None


def read_serial(pci_base_path: str, bdf: str) -> Optional[str]:
    """The chip's stable silicon identity for replug reconciliation
    (lifecycle_fsm.DeviceLifecycle): the sysfs `serial_number` attribute
    when the driver exposes one, else the PCI device id — a different
    model landing on the same BDF is still detected as an identity swap,
    and indistinguishable silicon degrades to BDF-only identity (the
    pre-FSM behavior) rather than false-positive swaps."""
    base = os.path.join(pci_base_path, bdf)
    for attr in ("serial_number", "serial"):
        path = os.path.join(base, attr)
        _note(path)
        try:
            with open(path, "r", encoding="ascii", errors="replace") as f:
                value = f.read().strip()
        except OSError:
            continue
        if value:
            return value
    return read_id_from_file(os.path.join(base, "device"))


def read_numa_node(path: str) -> int:
    """NUMA node, clamping negatives (unset) to 0 (reference :304-320)."""
    _note(path)
    try:
        with open(path, "r", encoding="ascii") as f:
            node = int(f.read().strip())
    except (OSError, ValueError) as exc:
        log.debug("could not read numa node %s: %s", path, exc)
        return 0
    return max(node, 0)


def pcie_path(pci_base_path: str, bdf: str) -> str:
    """Resolved sysfs hierarchy path for a chip (its PCIe position).

    /sys/bus/pci/devices/<bdf> is a symlink into /sys/devices/...; sorting
    chips by the resolved path groups co-packaged chips at ANY nesting
    depth — chips behind one switch share the upstream-port prefix even
    though each sits under its own downstream port. This is the host-side
    ICI-adjacency signal assign_coords uses (SURVEY §7 hard part (a)). On
    flat layouts (fixtures, no symlinks) the path order degenerates to BDF
    order.
    """
    full = os.path.join(pci_base_path, bdf)
    _note(full)
    return os.path.realpath(full)


def scan_accel_class(accel_class_path: str) -> Dict[str, int]:
    """Map PCI BDF → /dev/accelN index via /sys/class/accel/accelN/device.

    Only populated on hosts where the accel driver still owns chips (i.e. the
    vTPU/logical-partition path); vfio-bound chips vanish from this class.
    """
    try:
        entries = _listdir(accel_class_path)
    except OSError:
        return {}
    return _accel_map(accel_class_path, entries)


def _accel_map(accel_class_path: str, entries) -> Dict[str, int]:
    """BDF → accel index from an already-listed /sys/class/accel dir."""
    out: Dict[str, int] = {}
    for entry in entries:
        m = _ACCEL_RE.match(entry)
        if not m:
            continue
        bdf = read_link_basename(os.path.join(accel_class_path, entry, "device"))
        if bdf:
            out[bdf] = int(m.group(1))
    return out


# --- passthrough discovery ---------------------------------------------------

@dataclass(frozen=True)
class _ChipRecord:
    """Raw sysfs attributes of one TPU-vendor PCI endpoint, whatever driver
    owns it (the vfio filter is applied at registry-build time, so logical
    partitions can reuse the same record for accel-owned parents)."""

    bdf: str
    device_id: Optional[str]       # lowercased, no 0x prefix
    driver: Optional[str]
    iommu_group: Optional[str]
    numa_node: int
    pcie_path: str


def _read_chip(cfg: Config, bdf: str) -> Tuple[Optional[_ChipRecord], bool]:
    """Full attribute read for one PCI entry: (record, confirmed_foreign).

    `confirmed_foreign` is True only when the vendor file was READ
    successfully and names non-TPU hardware — a failed read (EIO, vanished
    mid-walk) returns (None, False) so callers never cache a transient
    error as a durable foreign verdict."""
    base = os.path.join(cfg.pci_base_path, bdf)
    if not _isdir(base):
        return None, False
    vendor = read_id_from_file(os.path.join(base, "vendor"))
    if vendor is None:
        return None, False
    if vendor.lower() not in cfg.vendor_ids:
        return None, True
    device_id = read_id_from_file(os.path.join(base, "device"))
    return _ChipRecord(
        bdf=bdf,
        device_id=device_id.lower() if device_id is not None else None,
        driver=read_link_basename(os.path.join(base, "driver")),
        iommu_group=read_link_basename(os.path.join(base, "iommu_group")),
        numa_node=read_numa_node(os.path.join(base, "numa_node")),
        pcie_path=pcie_path(cfg.pci_base_path, bdf),
    ), False


def _devices_from_records(cfg: Config, records: List[_ChipRecord],
                          accel_by_bdf: Dict[str, int]) -> List[TpuDevice]:
    """Apply the vfio/group/id filters (with the original log messages)."""
    raw: List[TpuDevice] = []
    for rec in records:
        if rec.driver not in cfg.vfio_drivers:
            log.info("TPU %s bound to %r, not a vfio driver; skipping",
                     rec.bdf, rec.driver)
            continue
        if rec.iommu_group is None:
            log.warning("TPU %s has no iommu_group; skipping", rec.bdf)
            continue
        if rec.device_id is None:
            log.warning("TPU %s has no device id; skipping", rec.bdf)
            continue
        raw.append(TpuDevice(
            bdf=rec.bdf, device_id=rec.device_id, iommu_group=rec.iommu_group,
            numa_node=rec.numa_node, accel_index=accel_by_bdf.get(rec.bdf)))
    return raw


def _stamp_coords(raw: List[TpuDevice],
                  generations: Dict[str, GenerationInfo],
                  hints, pcie_paths: Dict[str, str]) -> Registry:
    """Stamp ICI coordinates per model (coords are host-local per
    generation) and build the registry lookup maps."""
    by_model: Dict[str, List[TpuDevice]] = {}
    for dev in raw:
        by_model.setdefault(dev.device_id, []).append(dev)
    devices_by_model: Dict[str, Tuple[TpuDevice, ...]] = {}
    iommu_map: Dict[str, List[TpuDevice]] = {}
    bdf_to_group: Dict[str, str] = {}
    for model, devs in by_model.items():
        paths = {d.bdf: pcie_paths[d.bdf] for d in devs}
        coords = assign_coords([d.bdf for d in devs], generations.get(model),
                               hints, pcie_paths=paths)
        stamped = tuple(
            TpuDevice(
                bdf=d.bdf, device_id=d.device_id, iommu_group=d.iommu_group,
                numa_node=d.numa_node, accel_index=d.accel_index,
                ici_coords=coords.get(d.bdf),
            )
            for d in devs
        )
        devices_by_model[model] = stamped
        for d in stamped:
            iommu_map.setdefault(d.iommu_group, []).append(d)
            bdf_to_group[d.bdf] = d.iommu_group

    registry = Registry(
        devices_by_model=devices_by_model,
        iommu_map={g: tuple(ds) for g, ds in iommu_map.items()},
        bdf_to_group=bdf_to_group,
    )
    log.info("discovered %d VFIO TPU chips in %d iommu groups",
             len(raw), len(registry.iommu_map))
    return registry


def discover_passthrough(
    cfg: Config,
    accel_by_bdf: Optional[Dict[str, int]] = None,
) -> Tuple[Registry, Dict[str, GenerationInfo]]:
    """Walk the PCI bus for VFIO-bound TPU endpoints; build the registry maps."""
    generations = load_generation_map(cfg.generation_map_path)
    hints = load_topology_hints(cfg.topology_hints_path)
    if accel_by_bdf is None:
        accel_by_bdf = scan_accel_class(cfg.accel_class_path)

    records: List[_ChipRecord] = []
    try:
        entries = _listdir(cfg.pci_base_path)
    except OSError as exc:
        log.warning("PCI sysfs %s unreadable: %s", cfg.pci_base_path, exc)
        entries = []
    for bdf in entries:
        rec, _foreign_verdict = _read_chip(cfg, bdf)
        if rec is not None:
            records.append(rec)
    raw = _devices_from_records(cfg, records, accel_by_bdf)
    pcie_paths = {rec.bdf: rec.pcie_path for rec in records}
    return _stamp_coords(raw, generations, hints, pcie_paths), generations


# --- vTPU (partition) discovery ----------------------------------------------

def _sanitize_type(raw: str) -> str:
    return raw.strip().replace(" ", "_")


def _read_mdev(cfg: Config, uuid: str,
               numa_reader: Optional[Callable[[str], int]] = None,
               ) -> Optional[TpuPartition]:
    """Read one mdev device's type/parent; None when unreadable."""
    base = os.path.join(cfg.mdev_base_path, uuid)
    name_path = os.path.join(base, "mdev_type", "name")
    _note(name_path)
    try:
        with open(name_path, "r", encoding="ascii", errors="replace") as f:
            type_name = _sanitize_type(f.read())
    except OSError as exc:
        log.warning("mdev %s has no type name (%s); skipping", uuid, exc)
        return None
    # Parent BDF = second-to-last element of the resolved mdev path
    # (reference derives it the same way, :347-357).
    _note(base)
    try:
        real = os.path.realpath(base)
        parent_bdf = real.rstrip("/").split("/")[-2]
    except (OSError, IndexError):
        log.warning("mdev %s parent unresolvable; skipping", uuid)
        return None
    if numa_reader is not None:
        numa = numa_reader(parent_bdf)
    else:
        numa = read_numa_node(
            os.path.join(cfg.pci_base_path, parent_bdf, "numa_node"))
    return TpuPartition(uuid=uuid, type_name=type_name,
                        parent_bdf=parent_bdf, numa_node=numa,
                        provider="mdev")


def discover_mdev_partitions(cfg: Config) -> List[TpuPartition]:
    """Enumerate kernel mdev devices (reference vGPU path, :255-291)."""
    try:
        uuids = _listdir(cfg.mdev_base_path)
    except OSError:
        return []
    return [p for p in (_read_mdev(cfg, uuid) for uuid in uuids)
            if p is not None]


def _sysfs_chip_attrs(cfg: Config) -> Callable[[str], Tuple[bool, Optional[str], int]]:
    """Default (uncached) chip-attribute reader for logical-partition
    synthesis: (is-TPU-vendor, device id, numa node) straight from sysfs."""
    def reader(bdf: str) -> Tuple[bool, Optional[str], int]:
        base = os.path.join(cfg.pci_base_path, bdf)
        vendor = read_id_from_file(os.path.join(base, "vendor"))
        vendor_ok = vendor is not None and vendor.lower() in cfg.vendor_ids
        device_id = read_id_from_file(os.path.join(base, "device")) \
            if vendor_ok else None
        numa = read_numa_node(os.path.join(base, "numa_node"))
        return vendor_ok, (device_id.lower() if device_id else None), numa
    return reader


_SPEC_UNSET = object()  # "caller did not supply a spec" (None = known-absent)


def load_partition_spec(cfg: Config) -> Optional[dict]:
    """Parse the partition config JSON; None when unset/unreadable."""
    if not cfg.partition_config_path:
        return None
    _note(cfg.partition_config_path)
    try:
        with open(cfg.partition_config_path, "r", encoding="utf-8") as f:
            spec = json.load(f)
        if not isinstance(spec, dict):
            raise ValueError("top level must be an object")
    except (OSError, ValueError) as exc:
        log.warning("partition config %s unreadable: %s",
                    cfg.partition_config_path, exc)
        return None
    return spec


def discover_logical_partitions(
    cfg: Config,
    generations: Dict[str, GenerationInfo],
    accel_by_bdf: Optional[Dict[str, int]] = None,
    spec=_SPEC_UNSET,
    attr_reader: Optional[Callable[[str], Tuple[bool, Optional[str], int]]] = None,
) -> List[TpuPartition]:
    """Synthesize partitions where hardware lacks mdev (SURVEY.md §7 hard part d).

    TPU chips expose no mediated-device layer; multi-tenant chip sharing is a
    host-software construct. Two declaration styles in the partition config
    JSON (Config.partition_config_path):

    - {"per_core": true} — split every accel-owned chip into
      `cores_per_chip` partitions named `<gen>-core`, uuid `<bdf>-coreN`.
    - {"partitions": [{"uuid": ..., "type": ..., "parent_bdf": ...}]} —
      explicit list.

    `spec` may carry a pre-parsed config — including None for a
    known-absent/invalid file (the HostSnapshot caches that verdict keyed
    on the file's stat signature) — and `attr_reader` a cached
    chip-attribute source, so the incremental path re-reads neither; both
    default to sysfs when not supplied.
    """
    if spec is _SPEC_UNSET:
        spec = load_partition_spec(cfg)
    if spec is None:
        return []
    out: List[TpuPartition] = []
    if accel_by_bdf is None:
        accel_by_bdf = scan_accel_class(cfg.accel_class_path)
    if attr_reader is None:
        attr_reader = _sysfs_chip_attrs(cfg)
    if spec.get("per_core"):
        for bdf, accel_idx in sorted(accel_by_bdf.items()):
            vendor_ok, device_id, numa = attr_reader(bdf)
            if not vendor_ok:
                continue  # foreign accel-class hardware (VPU/Habana/...) is not a TPU
            info = generations.get(device_id or "")
            cores = info.cores_per_chip if info else 1
            gen = info.name if info else "tpu"
            for core in range(cores):
                out.append(TpuPartition(
                    uuid=f"{bdf}-core{core}", type_name=f"{gen}-core",
                    parent_bdf=bdf, numa_node=numa,
                    provider="logical", accel_index=accel_idx,
                ))
    for entry in spec.get("partitions", []):
        try:
            bdf = entry["parent_bdf"]
            _, _, numa = attr_reader(bdf)
            out.append(TpuPartition(
                uuid=entry["uuid"], type_name=_sanitize_type(entry["type"]),
                parent_bdf=bdf, numa_node=numa,
                provider="logical", accel_index=accel_by_bdf.get(bdf),
            ))
        except KeyError as exc:
            log.warning("partition entry %r missing %s; skipped", entry, exc)
    return out


def discover(cfg: Config) -> Tuple[Registry, Dict[str, GenerationInfo]]:
    """Full discovery: passthrough chips + mdev/logical partitions.

    One-shot form of HostSnapshot.rescan(full=True): a throwaway snapshot
    shares one accel-class pass AND the per-chip PCI records between the
    passthrough walk and partition synthesis (they used to each re-read
    sysfs), then is discarded — still side-effect free for the caller.
    Incremental callers (the PluginManager's rediscovery timer) hold a
    long-lived HostSnapshot instead, which pays per-device reads only for
    changed BDFs.
    """
    return HostSnapshot(cfg).rescan(full=True)


# --- incremental discovery ---------------------------------------------------

# Bump when the cached per-device signature/record layout changes meaning:
# a snapshot built by an older layout must take one full walk before its
# dirty-set path can be trusted again.
SNAPSHOT_SIGNATURE_VERSION = 1

# Persisted-cache envelope version (HostSnapshot.save_cache/load_cache).
# Same refusal rules as the DRA checkpoint envelope (docs/design.md):
# a malformed or FUTURE version is never trusted — but unlike the
# checkpoint (allocation truth, refuse to start), the snapshot is derived
# data, so refusal degrades to the counted cold walk and the stale file
# is simply replaced by the next save.
SNAPSHOT_CACHE_VERSION = 1


class HostSnapshot:
    """Incremental discovery: cache the full sysfs walk, rescan only deltas.

    The full walk (`discover()`) costs ~6 sysfs reads per PCI entry plus the
    accel/mdev class walks — O(inventory) on every rediscovery tick even
    when nothing changed. A HostSnapshot pays that ONCE (first boot, an
    explicit `full=True`, or a SNAPSHOT_SIGNATURE_VERSION bump) and then
    makes rescan cost proportional to *change*:

    - membership changes (hotplug/remove) are caught by the three class
      listdirs (PCI bus, accel class, mdev bus) — one read each;
    - `dirty` ids (BDFs or mdev UUIDs, fed by the health watcher's flap
      events) get a full per-device re-read; every other cached record is
      reused with ZERO per-device reads;
    - config files (partition spec, generation map, topology hints) are
      revalidated by an (mtime_ns, size) stat signature and re-parsed only
      when it moves.

    A driver rebind that produces neither a membership change nor a health
    event is therefore invisible to the warm path until hinted dirty — the
    documented contract (docs/perf.md): flaps dirty their devices through
    the health listener, and operators force `--full-rescan` when mutating
    bindings behind the plugin's back.

    Not thread-safe: confine a snapshot to the rediscovery thread (the
    PluginManager run loop does).
    """

    def __init__(self, cfg: Config) -> None:
        self.cfg = cfg
        self._signature_version = SNAPSHOT_SIGNATURE_VERSION
        self._records: Dict[str, _ChipRecord] = {}  # TPU-vendor PCI entries
        # known non-TPU PCI entries, bdf -> numa node (cached so warm
        # rebuilds never re-read foreign hardware's sysfs files)
        self._foreign: Dict[str, int] = {}
        self._accel_by_bdf: Dict[str, int] = {}
        self._accel_index_of: Dict[str, int] = {}   # accelN entry -> index
        self._mdevs: Dict[str, TpuPartition] = {}
        self._spec: Optional[dict] = None
        self._spec_sig: Optional[Tuple[int, int]] = None
        self._genmap_sig: Optional[Tuple[int, int]] = None
        self._hints_sig: Optional[Tuple[int, int]] = None
        self._generations: Dict[str, GenerationInfo] = {}
        self._hints: Dict[str, Tuple[int, ...]] = {}
        self._scanned = False
        self._last: Optional[Tuple[Registry, Dict[str, GenerationInfo]]] = None
        # dirty hints deferred by a failed bus listdir, re-applied next tick
        # (the caller's dirty set is consumed on hand-off, so dropping them
        # here would lose the flap forever)
        self._pending_dirty: Set[str] = set()
        # logical-partition uuid -> parent BDF from the last build, so a
        # vtpu health flap carrying "<bdf>-coreN" dirties the parent chip
        self._logical_parent: Dict[str, str] = {}
        # surfaced on /status (status.py) and asserted by the perf-honesty
        # guard: read counts are the load-insensitive cost metric. Scans
        # run on the manager's run loop but /status reads from HTTP
        # threads, so mutations take the stats lock (values stay ints —
        # readers see a torn dict never, a stale value at worst)
        # silicon identity cache (read_serial answers), persisted with the
        # snapshot so a warm boot pays ZERO identity reads; invalidated
        # whenever the owning record is re-read or dropped
        self._serials: Dict[str, str] = {}
        # per-BDF device-dir stat signatures captured at save time + the
        # bus dir's own signature: the two-tier revalidation evidence
        self._record_sigs: Dict[str, Optional[Tuple[int, int]]] = {}
        self._bus_sig: Optional[Tuple[int, int]] = None
        self._stats_lock = lockdep.instrument(
            "discovery.HostSnapshot._stats_lock", threading.Lock())
        self.stats = {"full_scans": 0, "dirty_rescans": 0,
                      "last_scan_reads": 0,
                      # persisted-snapshot boot accounting: records served
                      # straight from the cache / records that had to be
                      # re-read cold / whole-cache rejections (missing,
                      # corrupt, version-refused, injected fault)
                      "snapshot_hits": 0, "snapshot_invalidated": 0,
                      "snapshot_fallbacks": 0}

    # ------------------------------------------------------------- public

    def rescan(self, dirty: Optional[Set[str]] = None, full: bool = False,
               ) -> Tuple[Registry, Dict[str, GenerationInfo]]:
        """(registry, generations) after reconciling sysfs deltas.

        `dirty` names ids (chip BDFs / mdev UUIDs) whose cached records
        must be re-read even though they are still listed; unknown ids are
        ignored. `full=True` forces the complete walk."""
        with count_reads(confine_thread=True) as w:
            if (full or not self._scanned
                    or self._signature_version != SNAPSHOT_SIGNATURE_VERSION):
                result = self._full_scan()
            else:
                result = self._dirty_scan(set(dirty or ()))
        with self._stats_lock:
            self.stats["last_scan_reads"] = w.reads
        return result

    # -------------------------------------------------------------- walks

    def _full_scan(self) -> Tuple[Registry, Dict[str, GenerationInfo]]:
        with self._stats_lock:
            self.stats["full_scans"] += 1
        self._signature_version = SNAPSHOT_SIGNATURE_VERSION
        self._genmap_sig = (_stat_sig(self.cfg.generation_map_path)
                            if self.cfg.generation_map_path else None)
        self._generations = load_generation_map(self.cfg.generation_map_path)
        self._hints_sig = (_stat_sig(self.cfg.topology_hints_path)
                           if self.cfg.topology_hints_path else None)
        self._hints = load_topology_hints(self.cfg.topology_hints_path)
        self._spec_sig = (_stat_sig(self.cfg.partition_config_path)
                          if self.cfg.partition_config_path else None)
        self._spec = load_partition_spec(self.cfg)
        self._records = {}
        self._foreign = {}
        try:
            entries = _listdir(self.cfg.pci_base_path)
        except OSError as exc:
            log.warning("PCI sysfs %s unreadable: %s",
                        self.cfg.pci_base_path, exc)
            entries = []
        for bdf in entries:
            self._scan_bdf(bdf)
        self._accel_by_bdf = {}
        self._accel_index_of = {}
        self._rescan_accel()
        self._mdevs = {}
        self._rescan_mdevs(set())
        self._scanned = True
        return self._build()

    def _dirty_scan(self, dirty: Set[str],
                    ) -> Tuple[Registry, Dict[str, GenerationInfo]]:
        with self._stats_lock:
            self.stats["dirty_rescans"] += 1
        changed = False
        dirty |= self._pending_dirty
        # a flapped logical partition names its parent chip's record
        dirty |= {self._logical_parent[i] for i in dirty
                  if i in self._logical_parent}
        known = set(self._records) | set(self._foreign)
        try:
            listed = set(_listdir(self.cfg.pci_base_path))
        except OSError as exc:
            # transient EIO/EACCES must not read as "every device removed"
            # and tear down all plugins: skip this tick's reconciliation
            # entirely and serve the last-known-good build (per-device
            # reads against the same failing bus would only drop records);
            # the dirty hints are deferred, not lost
            log.warning("PCI sysfs %s unreadable: %s; keeping cached "
                        "inventory this tick", self.cfg.pci_base_path, exc)
            self._pending_dirty = dirty
            return self._last if self._last is not None else self._build()
        self._pending_dirty = set()
        for bdf in sorted((listed - known) | (dirty & listed)):
            changed |= self._scan_bdf(bdf)
        for bdf in known - listed:
            changed |= self._drop_bdf(bdf)
        changed |= self._rescan_accel(dirty)
        changed |= self._rescan_mdevs(dirty)
        changed |= self._revalidate_configs()
        if not changed and self._last is not None:
            return self._last
        return self._build()

    # ---------------------------------------------------- per-layer deltas

    def _scan_bdf(self, bdf: str) -> bool:
        """(Re)read one PCI entry fully; True when the cached view moved."""
        rec, foreign = _read_chip(self.cfg, bdf)
        if rec is None:
            changed = self._records.pop(bdf, None) is not None
            if foreign:
                # vendor READ succeeded and names non-TPU hardware — a PCI
                # function's vendor is immutable while its dir exists, so
                # this verdict is cacheable until remove/re-add. A failed
                # read caches NOTHING: the bdf leaves `known`, so the next
                # tick's listdir diff re-attempts it.
                self._foreign[bdf] = read_numa_node(
                    os.path.join(self.cfg.pci_base_path, bdf, "numa_node"))
            return changed
        changed = self._records.get(bdf) != rec
        if changed:
            # a moved record may be different silicon in the same slot:
            # its cached identity is evidence no longer
            self._serials.pop(bdf, None)
        self._records[bdf] = rec
        self._foreign.pop(bdf, None)
        return changed

    def _drop_bdf(self, bdf: str) -> bool:
        self._foreign.pop(bdf, None)
        self._serials.pop(bdf, None)
        return self._records.pop(bdf, None) is not None

    def _rescan_accel(self, dirty: Set[str] = frozenset()) -> bool:
        """Accel-class delta: readlink only entries not seen before (an
        accelN's device symlink target is fixed for the dir's lifetime).
        Dirty BDFs invalidate their cached links first, so an accel entry
        silently reacquired by a different chip is re-readlinked when the
        swap surfaces as a health flap — the same dirty-hint contract as
        the PCI records."""
        try:
            entries = _listdir(self.cfg.accel_class_path)
        except FileNotFoundError:
            entries = []  # no accel class on this host: genuinely empty
        except OSError as exc:
            log.warning("accel class %s unreadable: %s; keeping cached map "
                        "this tick", self.cfg.accel_class_path, exc)
            # re-defer the accel-relevant hints so the dirty re-readlink
            # happens once the class dir recovers (cache left untouched)
            self._pending_dirty |= dirty & set(self._accel_by_bdf)
            return False
        # invalidate dirty links only AFTER the listdir succeeded, so a
        # transient error above never costs cached entries
        invalidated: Dict[str, str] = {}       # entry -> old bdf
        stale_idx = {self._accel_by_bdf[b]: b
                     for b in dirty & set(self._accel_by_bdf)}
        if stale_idx:
            for entry, i in list(self._accel_index_of.items()):
                if i in stale_idx:
                    invalidated[entry] = stale_idx[i]
                    del self._accel_index_of[entry]
            for b in stale_idx.values():
                del self._accel_by_bdf[b]
        current: Dict[str, int] = {}
        for entry in entries:
            m = _ACCEL_RE.match(entry)
            if m:
                current[entry] = int(m.group(1))
        changed = False
        for entry in set(self._accel_index_of) - set(current):
            idx = self._accel_index_of.pop(entry)
            for bdf, i in list(self._accel_by_bdf.items()):
                if i == idx:
                    del self._accel_by_bdf[bdf]
            changed = True
        for entry, idx in current.items():
            if entry in self._accel_index_of:
                continue
            bdf = read_link_basename(
                os.path.join(self.cfg.accel_class_path, entry, "device"))
            if bdf is None:
                # transient readlink failure (device still settling): cache
                # NOTHING so the next tick re-attempts it — same no-caching-
                # of-errors policy as _scan_bdf
                continue
            self._accel_index_of[entry] = idx
            self._accel_by_bdf[bdf] = idx
            if invalidated.get(entry) != bdf:
                changed = True   # an unchanged re-validated link is free
        for entry in invalidated:
            if entry not in self._accel_index_of:
                # the invalidated entry vanished from the class dir (or its
                # re-readlink failed): the dirty device LOST its accel
                # mapping, which the rebuild must see — without this, the
                # pre-invalidation removal diff above never fires for it
                # and the stale registry would be served forever
                changed = True
        return changed

    def _rescan_mdevs(self, dirty: Set[str]) -> bool:
        try:
            uuids = set(_listdir(self.cfg.mdev_base_path))
        except FileNotFoundError:
            uuids = set()  # no mdev bus on this host: genuinely empty
        except OSError as exc:
            log.warning("mdev bus %s unreadable: %s; keeping cached "
                        "partitions this tick", self.cfg.mdev_base_path, exc)
            # re-defer the mdev-relevant hints so the flap is re-read once
            # the bus recovers (the PCI path already consumed the rest)
            self._pending_dirty |= dirty & set(self._mdevs)
            return False
        changed = False
        for uuid in set(self._mdevs) - uuids:
            del self._mdevs[uuid]
            changed = True
        for uuid in sorted((uuids - set(self._mdevs)) | (dirty & uuids)):
            part = _read_mdev(self.cfg, uuid, numa_reader=self._numa_of)
            if part is None:
                changed |= self._mdevs.pop(uuid, None) is not None
                continue
            changed |= self._mdevs.get(uuid) != part
            self._mdevs[uuid] = part
        return changed

    def _revalidate_configs(self) -> bool:
        """Re-parse config files only when their stat signature moved."""
        changed = False
        if self.cfg.generation_map_path:
            sig = _stat_sig(self.cfg.generation_map_path)
            if sig != self._genmap_sig:
                self._genmap_sig = sig
                self._generations = load_generation_map(
                    self.cfg.generation_map_path)
                changed = True
        if self.cfg.topology_hints_path:
            sig = _stat_sig(self.cfg.topology_hints_path)
            if sig != self._hints_sig:
                self._hints_sig = sig
                self._hints = load_topology_hints(self.cfg.topology_hints_path)
                changed = True
        if self.cfg.partition_config_path:
            sig = _stat_sig(self.cfg.partition_config_path)
            if sig != self._spec_sig:
                self._spec_sig = sig
                self._spec = load_partition_spec(self.cfg)
                changed = True
        return changed

    # ------------------------------------------------------ cached readers

    def _numa_of(self, bdf: str) -> int:
        rec = self._records.get(bdf)
        if rec is not None:
            return rec.numa_node
        if bdf in self._foreign:
            return self._foreign[bdf]
        return read_numa_node(
            os.path.join(self.cfg.pci_base_path, bdf, "numa_node"))

    def serial_of(self, bdf: str) -> Optional[str]:
        """Cached silicon identity (lifecycle_fsm replug reconciliation):
        a warm boot serves identity straight from the persisted snapshot
        with zero sysfs reads; re-scanned or dropped records invalidate
        their entry, so a genuine replug still pays the real read."""
        cached = self._serials.get(bdf)
        if cached is not None:
            return cached
        serial = read_serial(self.cfg.pci_base_path, bdf)
        if serial is not None:
            self._serials[bdf] = serial
        return serial

    def _cached_attrs(self, bdf: str) -> Tuple[bool, Optional[str], int]:
        """attr_reader for discover_logical_partitions: serve vendor/id/numa
        from the cache — including the known-foreign verdict, so warm
        rebuilds on hosts with non-TPU accel hardware stay read-free; only
        ids outside the cached PCI walk entirely fall back to sysfs."""
        rec = self._records.get(bdf)
        if rec is not None:
            return True, rec.device_id, rec.numa_node
        if bdf in self._foreign:
            return False, None, self._foreign[bdf]
        return _sysfs_chip_attrs(self.cfg)(bdf)

    # ------------------------------------------------- persisted snapshot

    def save_cache(self, path: Optional[str]) -> bool:
        """Serialize the scanned host view into a versioned envelope via
        atomic temp+rename (same crash-safety discipline as the DRA
        checkpoint beside which it lives). Captures per-BDF device-dir
        stat signatures as the revalidation evidence the next boot's
        batched stat pass compares against. Post-boot bookkeeping: its
        own stats are NOT counted as discovery reads. Returns False (and
        logs) rather than raising — a failed save costs the next boot a
        cold walk, never this boot anything."""
        if not path or not self._scanned:
            return False
        self._bus_sig = _stat_sig_raw(self.cfg.pci_base_path)
        self._record_sigs = {
            bdf: _stat_sig_raw(os.path.join(self.cfg.pci_base_path, bdf))
            for bdf in self._records}
        envelope = {
            "version": SNAPSHOT_CACHE_VERSION,
            "signature_version": self._signature_version,
            "bus_sig": self._bus_sig,
            "record_sigs": self._record_sigs,
            "records": {
                bdf: {"device_id": rec.device_id, "driver": rec.driver,
                      "iommu_group": rec.iommu_group,
                      "numa_node": rec.numa_node,
                      "pcie_path": rec.pcie_path}
                for bdf, rec in self._records.items()},
            "foreign": self._foreign,
            "accel_by_bdf": self._accel_by_bdf,
            "accel_index_of": self._accel_index_of,
            "mdevs": {
                uuid: {"type_name": p.type_name,
                       "parent_bdf": p.parent_bdf,
                       "numa_node": p.numa_node}
                for uuid, p in self._mdevs.items()},
            "serials": self._serials,
            "spec": self._spec,
            "spec_sig": self._spec_sig,
            "genmap_sig": self._genmap_sig,
            "hints_sig": self._hints_sig,
        }
        try:
            _atomic_write_json(path, envelope)
        except OSError as exc:
            log.warning("discovery snapshot save to %s failed: %s",
                        path, exc)
            return False
        return True

    def load_cache(self, path: Optional[str]) -> str:
        """Restore the host view from a persisted envelope. Returns the
        outcome: "loaded" (cache trusted — revalidate() next), or a
        fallback reason ("missing" / "corrupt" / "version" /
        "signature" / "fault"), every one of which leaves the snapshot
        unscanned so the caller's rescan pays the counted cold walk —
        a rejected cache is never trusted stale. Fault site
        `discovery.snapshot` (value kind) makes the next load read as
        corrupt/missing."""
        outcome = self._load_cache_impl(path)
        if outcome != "loaded":
            with self._stats_lock:
                self.stats["snapshot_fallbacks"] += 1
            if outcome != "missing":
                log.warning("discovery snapshot %s rejected (%s); "
                            "falling back to the cold walk", path, outcome)
        return outcome

    def _load_cache_impl(self, path: Optional[str]) -> str:
        if not path:
            return "missing"
        if faults.fire("discovery.snapshot", path=path):
            return "fault"
        _note(path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                env = json.load(f)
            if not isinstance(env, dict):
                raise ValueError("envelope must be an object")
        except FileNotFoundError:
            return "missing"
        except (OSError, ValueError):
            # unreadable or torn mid-write (truncated/garbage JSON)
            return "corrupt"
        version = env.get("version")
        if not isinstance(version, int) or isinstance(version, bool) \
                or version < 0:
            return "corrupt"
        if version != SNAPSHOT_CACHE_VERSION:
            # future AND past versions both refuse: derived data has no
            # migration ladder — one cold walk re-derives everything
            return "version"
        if env.get("signature_version") != SNAPSHOT_SIGNATURE_VERSION:
            return "signature"

        def _sig(value) -> Optional[Tuple[int, int]]:
            if value is None:
                return None
            a, b = value
            return (int(a), int(b))

        try:
            records = {
                str(bdf): _ChipRecord(
                    bdf=str(bdf), device_id=r["device_id"],
                    driver=r["driver"], iommu_group=r["iommu_group"],
                    numa_node=int(r["numa_node"]),
                    pcie_path=str(r["pcie_path"]))
                for bdf, r in env["records"].items()}
            foreign = {str(b): int(n)
                       for b, n in env["foreign"].items()}
            accel_by_bdf = {str(b): int(i)
                            for b, i in env["accel_by_bdf"].items()}
            accel_index_of = {str(e): int(i)
                              for e, i in env["accel_index_of"].items()}
            mdevs = {
                str(uuid): TpuPartition(
                    uuid=str(uuid), type_name=str(m["type_name"]),
                    parent_bdf=str(m["parent_bdf"]),
                    numa_node=int(m["numa_node"]), provider="mdev")
                for uuid, m in env["mdevs"].items()}
            serials = {str(b): str(s)
                       for b, s in env["serials"].items()}
            record_sigs = {str(b): _sig(s)
                           for b, s in env["record_sigs"].items()}
            bus_sig = _sig(env.get("bus_sig"))
            spec = env.get("spec")
            if spec is not None and not isinstance(spec, dict):
                raise ValueError("spec must be an object or null")
            spec_sig = _sig(env.get("spec_sig"))
            genmap_sig = _sig(env.get("genmap_sig"))
            hints_sig = _sig(env.get("hints_sig"))
        except (KeyError, TypeError, ValueError, AttributeError):
            return "corrupt"
        # commit only after the WHOLE envelope parsed — a half-applied
        # cache would be worse than no cache
        self._signature_version = SNAPSHOT_SIGNATURE_VERSION
        self._records = records
        self._foreign = foreign
        self._accel_by_bdf = accel_by_bdf
        self._accel_index_of = accel_index_of
        self._mdevs = mdevs
        self._serials = serials
        self._record_sigs = record_sigs
        self._bus_sig = bus_sig
        self._spec = spec
        self._spec_sig = spec_sig
        self._genmap_sig = genmap_sig
        self._hints_sig = hints_sig
        # config OBJECTS are re-parsed from their (small) files — the
        # cached sigs only spare the re-parse when the next rescan's
        # _revalidate_configs finds them unmoved
        self._generations = load_generation_map(self.cfg.generation_map_path)
        self._hints = load_topology_hints(self.cfg.topology_hints_path)
        self._pending_dirty = set()
        self._logical_parent = {}
        self._last = None
        self._scanned = True
        return "loaded"

    def revalidate(self) -> Set[str]:
        """Two-tier trust pass over a just-loaded cache; returns the ids
        whose cached records may NOT be served (they pay cold per-device
        reads in the next rescan(dirty=...)); everything else boots
        straight from cache.

        Shallow tier (always): one PCI-bus listdir membership diff plus
        the bus dir's own stat signature, one mdev-bus listdir — a
        handful of reads regardless of host size. Deep tier (only when
        the bus dir's signature moved): ONE batched stat pass over every
        surviving cached device dir — `run_batch` `stat_sig` sub-ops in
        spawn mode, one crossing for the whole host — invalidating
        exactly the dirs whose signature differs from the one captured
        at save time. In-place mutations that move no signature follow
        the snapshot's documented warm-path contract: health flaps dirty
        them, operators force --full-rescan."""
        invalidated: Set[str] = set()
        known = set(self._records) | set(self._foreign)
        try:
            listed = set(_listdir(self.cfg.pci_base_path))
        except OSError:
            listed = None   # unreadable bus: the rescan defers, not us
        if listed is not None:
            invalidated |= (listed - known) | (known - listed)
            bus_sig = _stat_sig(self.cfg.pci_base_path)
            if bus_sig is None or bus_sig != self._bus_sig:
                bdfs = sorted(set(self._records) & listed)
                paths = [os.path.join(self.cfg.pci_base_path, b)
                         for b in bdfs]
                for bdf, sig in zip(bdfs, _stat_sigs_batched(paths)):
                    if sig is None or sig != self._record_sigs.get(bdf):
                        invalidated.add(bdf)
        try:
            mdev_listed = set(_listdir(self.cfg.mdev_base_path))
        except OSError:
            mdev_listed = set(self._mdevs)
        invalidated |= mdev_listed.symmetric_difference(self._mdevs)
        with self._stats_lock:
            self.stats["snapshot_invalidated"] += len(invalidated)
            self.stats["snapshot_hits"] += max(
                0, len(known) + len(self._mdevs) - len(invalidated))
        return invalidated

    def taint_groups(self, invalidated: Set[str]) -> Set[str]:
        """Expand invalidated ids to everything wave 1 of the boot
        pipeline must EXCLUDE, so each resource either boots entirely
        from validated cache or waits whole for wave 2: every cached
        chip sharing a device model with an invalidated chip, every
        partition sharing a type with an invalidated partition. Ids the
        cache has never seen expand to nothing — their resource is
        unknown until wave 2 reads them."""
        models = {self._records[b].device_id
                  for b in invalidated if b in self._records}
        types = {self._mdevs[u].type_name
                 for u in invalidated if u in self._mdevs}
        out = set(invalidated)
        out |= {b for b, r in self._records.items()
                if r.device_id in models}
        out |= {u for u, p in self._mdevs.items() if p.type_name in types}
        return out

    # -------------------------------------------------------------- build

    def _build(self) -> Tuple[Registry, Dict[str, GenerationInfo]]:
        """Pure in-memory rebuild from the caches (no sysfs access)."""
        return self._compose(self._records, self._mdevs, commit=True)

    def build_excluding(self, exclude: Set[str],
                        ) -> Tuple[Registry, Dict[str, GenerationInfo]]:
        """Wave-1 boot registry (pure, no sysfs access): every cached
        record EXCEPT the excluded ids, without touching the snapshot's
        last-known-good state — the wave-2 rescan still reconciles from
        the full cached view."""
        records = {b: r for b, r in self._records.items()
                   if b not in exclude}
        mdevs = {u: p for u, p in self._mdevs.items() if u not in exclude}
        return self._compose(records, mdevs, commit=False,
                             exclude=exclude)

    def _compose(self, records_map: Dict[str, _ChipRecord],
                 mdevs_map: Dict[str, TpuPartition], commit: bool,
                 exclude: Set[str] = frozenset(),
                 ) -> Tuple[Registry, Dict[str, GenerationInfo]]:
        records = [records_map[b] for b in sorted(records_map)]
        raw = _devices_from_records(self.cfg, records, self._accel_by_bdf)
        pcie_paths = {rec.bdf: rec.pcie_path for rec in records}
        registry = _stamp_coords(raw, self._generations, self._hints,
                                 pcie_paths)
        partitions = [mdevs_map[u] for u in sorted(mdevs_map)]
        logical = discover_logical_partitions(
            self.cfg, self._generations, self._accel_by_bdf,
            spec=self._spec, attr_reader=self._cached_attrs)
        if exclude:
            # a logical partition rides its parent chip's validation
            logical = [p for p in logical
                       if p.uuid not in exclude
                       and p.parent_bdf not in exclude]
        result = _finalize(self.cfg, registry, self._generations,
                           partitions + logical)
        if commit:
            self._logical_parent = {p.uuid: p.parent_bdf for p in logical}
            self._last = result
        return result


def _finalize(cfg: Config, registry: Registry,
              generations: Dict[str, GenerationInfo],
              partitions: List[TpuPartition],
              ) -> Tuple[Registry, Dict[str, GenerationInfo]]:
    """Pure post-processing shared by discover() and HostSnapshot: name
    collision refusal, unallocatable-partition pruning, VFIO-group
    single-holder rules, the per-chip partition cap, and passthrough
    exclusion of consumed groups. No sysfs access happens here."""
    # A partition type named like a passthrough resource suffix would make
    # two plugins register the same extended-resource name with the kubelet.
    # Refuse the partitions here (not later in the lifecycle), so their
    # parent chips stay advertised as passthrough instead of being consumed
    # by a plugin that can never be built.
    from .naming import resource_name_for
    passthrough_suffixes = set()
    for m in registry.devices_by_model:
        suffix = resource_name_for(m, generations, cfg.pci_ids_path)
        passthrough_suffixes.add(suffix)
        if m not in generations:
            # The packaged ids are documented placeholders (no public Cloud
            # TPU PCI-id table): an unmatched id on a real fleet means the
            # operator must supply --generation-map before resource names
            # mean anything. Warn on BOTH entry points (daemon and
            # --discover-only) — this is the shared path.
            log.warning(
                "device id %s is not in the generation table; advertising "
                "fallback resource name %r — supply --generation-map with "
                "this fleet's real ids (see utils/README.md)", m, suffix)
    kept: List[TpuPartition] = []
    for p in partitions:
        if p.type_name in passthrough_suffixes:
            log.error("partition %s: type %r collides with a passthrough "
                      "resource suffix; dropping partition", p.uuid, p.type_name)
            continue
        kept.append(p)
    partitions = kept
    # A logical partition is only allocatable through its parent's accel node
    # or VFIO group; one with neither would hand a VMI zero DeviceSpecs —
    # refuse it here with a reason instead of failing at Allocate time.
    # And a VFIO group attaches to exactly ONE container at a time, so a
    # vfio-bound IOMMU group can back at most ONE advertised partition —
    # keyed by group, not parent BDF: two partitions on different parents
    # that share a group would still collide in VFIO_GROUP_SET_CONTAINER
    # (EBUSY), making any extra advertised capacity unusable. (Accel-node
    # partitions CAN share — the accel driver multiplexes.)
    allocatable: List[TpuPartition] = []
    vfio_group_seen: Dict[str, str] = {}
    for p in partitions:
        if p.provider == "logical" and p.accel_index is None:
            parent_group = registry.bdf_to_group.get(p.parent_bdf)
            if parent_group is None:
                log.warning(
                    "partition %s (type %s): parent %s has no accel node and "
                    "is not vfio-bound; refusing to advertise an "
                    "unallocatable partition", p.uuid, p.type_name, p.parent_bdf)
                continue
            holder = vfio_group_seen.setdefault(parent_group, p.uuid)
            if holder != p.uuid:
                log.warning(
                    "partition %s (type %s): parent %s's VFIO group %s is "
                    "already backing partition %s — a VFIO group attaches to "
                    "one VM at a time, dropping the extra partition",
                    p.uuid, p.type_name, p.parent_bdf, parent_group, holder)
                continue
        allocatable.append(p)
    partitions = allocatable
    # Operator-set blast-radius cap: accel-backed logical partitions share
    # one /dev/accelN with no hardware isolation (docs/design.md "vTPU
    # trust boundary"), so a fleet can bound tenants-per-chip regardless of
    # what the partition config declares. mdev (kernel-mediated) and
    # vfio-backed (already 1/group) partitions are not capped.
    if cfg.max_partitions_per_chip > 0:
        per_parent: Dict[str, int] = {}
        capped: List[TpuPartition] = []
        for p in partitions:
            if p.provider == "logical" and p.accel_index is not None:
                n = per_parent.get(p.parent_bdf, 0)
                if n >= cfg.max_partitions_per_chip:
                    log.warning(
                        "partition %s (type %s): parent %s already has %d "
                        "advertised partitions (--max-partitions-per-chip); "
                        "dropping", p.uuid, p.type_name, p.parent_bdf, n)
                    continue
                per_parent[p.parent_bdf] = n + 1
            capped.append(p)
        partitions = capped
    # A vfio-bound chip that backs logical partitions is consumed by the vTPU
    # resource: advertising it as passthrough too would let the kubelet grant
    # the same VFIO group to two VMIs. Exclusion is by IOMMU GROUP, not BDF —
    # plan_allocation expands a passthrough request to its whole group, so a
    # kept chip sharing a group with a consumed parent would mount the same
    # /dev/vfio/<group> the vTPU plugin hands out (lookup maps stay intact —
    # the vTPU plugin resolves the parent's group through them). The
    # reference never faces this: mdev parents are bound to the vendor
    # driver, so the sets are disjoint there.
    consumed = {p.parent_bdf for p in partitions
                if p.provider == "logical" and p.accel_index is None}
    consumed_groups = {registry.bdf_to_group[b] for b in consumed
                       if b in registry.bdf_to_group}
    if consumed_groups:
        devices_by_model = {}
        for model, devs in registry.devices_by_model.items():
            kept = tuple(d for d in devs
                         if d.iommu_group not in consumed_groups)
            if kept:
                devices_by_model[model] = kept
        log.info("VFIO groups %s back logical partitions; their chips are "
                 "excluded from passthrough", ",".join(sorted(consumed_groups)))
        registry = Registry(
            devices_by_model=devices_by_model,
            iommu_map=registry.iommu_map,
            bdf_to_group=registry.bdf_to_group,
        )
    by_type: Dict[str, List[TpuPartition]] = {}
    parent_map: Dict[str, List[str]] = {}
    for p in partitions:
        by_type.setdefault(p.type_name, []).append(p)
        parent_map.setdefault(p.parent_bdf, []).append(p.uuid)
    registry = Registry(
        devices_by_model=registry.devices_by_model,
        iommu_map=registry.iommu_map,
        bdf_to_group=registry.bdf_to_group,
        partitions_by_type={t: tuple(ps) for t, ps in by_type.items()},
        parent_to_partitions={b: tuple(us) for b, us in parent_map.items()},
    )
    return registry, generations
