"""slo — declarative latency objectives + multi-window burn rates.

Raw histograms (trace.py) answer "what is the p99"; an operator running
the fleet against an error budget asks a different question: **how fast
am I burning the budget right now, and which trace do I open?** This
module is the objective layer over the existing lock-free histograms:

- An **objective** declares a latency contract over one histogram:
  "``target`` of observations land within ``threshold_ms``" (the
  threshold SNAPS to the histogram's next bucket bound — the math is
  exact against the recorded buckets, never interpolated).
- The engine samples each histogram's (total, bad) cumulative counts
  into a bounded ring and computes **multi-window burn rates** — the
  classic fast (5 m) + slow (1 h) pair: ``burn = error_rate /
  (1 - target)`` over each window, where burn 1.0 = exactly consuming
  the budget, 14.4 = a 30-day budget gone in 2 days. A **breach** is
  the multiwindow gate (fast AND slow over their thresholds, with real
  bad deltas in the window) — page-worthy, not noise — counted,
  recorded as a ``slo.breach`` flight-recorder event, and latched until
  the SLOW window cools below its threshold (recovery is a latched
  transition too: a burning objective whose fast window merely dips is
  still in breach — unlatching on the fast window alone made the latch
  flap under oscillating faults, which is exactly what the remediation
  plane must not act on). Transitions (breach AND recovery) fan out to
  registered subscribers (``subscribe``) OUTSIDE the engine lock — the
  remediation engine (remediation.py) is the shipped subscriber.
- Every burning objective carries an **exemplar trace id** — the latest
  over-threshold observation's trace, pulled from the histogram's
  per-bucket exemplar slots (trace.Histogram) — so a moving
  ``tpu_plugin_slo_burn_rate`` gauge links straight to
  ``/debug/fleet/trace?trace=<exemplar>``.

Surfaces: ``/status`` ``slo`` section + ``tpu_plugin_slo_*`` on
``/metrics`` (status.StatusServer), and the engine registers itself as
a trace-dump extra so every crash/SIGHUP flight dump carries the
current SLO/burn state next to the span ring (docs/observability.md
"SLO objectives").

Concurrency: readers (``snapshot()``, the /metrics render) are
lock-free — the engine swaps one immutable state mapping per
evaluation, and the counters dict is read via a C-atomic copy. The
writer side (``evaluate()``) serializes on a PLAIN, deliberately
UNregistered lock, same contract as trace.py's maintenance lock: it is
cold-path (one evaluation per scrape, rate-limited sampling), invisible
to the zero-lock read-path gates, and never held while touching any
registered lock. tsalint COUNTERS owns ``counters[*]`` under
``slo.SLOEngine._lock``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from types import MappingProxyType
from typing import (Callable, Deque, Dict, List, Mapping, Optional,
                    Sequence, Tuple)

from . import trace

log = logging.getLogger(__name__)

__all__ = ["SLOConfigError", "Objective", "SLOEngine",
           "default_objectives", "load_objectives", "get_engine",
           "set_engine", "render_prometheus"]

# the classic multiwindow pair (SRE workbook): the fast window catches
# a budget-destroying incident in minutes, the slow window keeps a
# brief blip from paging
DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
DEFAULT_BURN_FAST = 14.4        # 2% of a 30-day budget per hour
DEFAULT_BURN_SLOW = 6.0
# at most one ring sample per second per objective; 2h of history at
# that cap bounds each ring
_SAMPLE_GAP_S = 1.0
_SAMPLE_RING = 7200


class SLOConfigError(ValueError):
    """An objective spec that cannot load: unknown histogram, target
    outside (0, 1), non-positive threshold/window. Raised at LOAD time —
    a malformed objective must fail the daemon's boot, never silently
    monitor nothing."""


@dataclass(frozen=True)
class Objective:
    name: str
    histogram: str               # a trace.py-registered histogram family
    threshold_ms: float          # good = observation <= threshold
    target: float                # fraction of good observations promised
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S
    burn_fast: float = DEFAULT_BURN_FAST
    burn_slow: float = DEFAULT_BURN_SLOW

    def validate(self) -> "Objective":
        if not self.name:
            raise SLOConfigError("objective needs a name")
        try:
            trace.histogram(self.histogram)
        except KeyError:
            raise SLOConfigError(
                f"objective {self.name!r}: unknown histogram "
                f"{self.histogram!r}") from None
        if not 0.0 < self.target < 1.0:
            raise SLOConfigError(
                f"objective {self.name!r}: target must be in (0, 1), "
                f"got {self.target!r}")
        if self.threshold_ms <= 0:
            raise SLOConfigError(
                f"objective {self.name!r}: threshold_ms must be > 0")
        if self.fast_window_s <= 0 or self.slow_window_s <= 0:
            raise SLOConfigError(
                f"objective {self.name!r}: windows must be > 0")
        return self


def default_objectives() -> List[Objective]:
    """The shipped objective set, one per plane the operators page on.
    Thresholds sit on histogram bucket bounds (the math snaps there
    anyway); targets are the contract docs/observability.md documents."""
    return [
        Objective("attach_wall", "tdp_attach_wall_ms",
                  threshold_ms=50.0, target=0.99),
        Objective("prepare_wall", "tdp_prepare_wall_ms",
                  threshold_ms=250.0, target=0.99),
        Objective("publish_rtt", "tdp_kubeapi_rtt_ms",
                  threshold_ms=100.0, target=0.99),
        Objective("watch_convergence", "tdp_watch_convergence_ms",
                  threshold_ms=1000.0, target=0.99),
    ]


def load_objectives(spec) -> List[Objective]:
    """Objective list from a declarative spec: a JSON file path, a JSON
    string, or an already-parsed list of dicts (docs/observability.md
    "SLO objective config" documents the fields). Fail-loud
    (SLOConfigError) on anything malformed."""
    if isinstance(spec, str):
        text = spec
        if not spec.lstrip().startswith("["):
            try:
                with open(spec, "r", encoding="utf-8") as f:
                    text = f.read()
            except OSError as exc:
                raise SLOConfigError(
                    f"SLO config {spec!r} is neither a JSON list nor a "
                    f"readable file: {exc}") from exc
        try:
            spec = json.loads(text)
        except ValueError as exc:
            raise SLOConfigError(f"SLO config is not JSON: {exc}") from exc
    if not isinstance(spec, list):
        raise SLOConfigError(
            f"SLO config must be a list of objectives, got "
            f"{type(spec).__name__}")
    out: List[Objective] = []
    for i, item in enumerate(spec):
        if not isinstance(item, dict):
            raise SLOConfigError(f"objective #{i} is not an object")
        unknown = set(item) - {
            "name", "histogram", "threshold_ms", "target",
            "fast_window_s", "slow_window_s", "burn_fast", "burn_slow"}
        if unknown:
            raise SLOConfigError(
                f"objective #{i}: unknown fields {sorted(unknown)}")
        try:
            obj = Objective(**item)
        except TypeError as exc:
            raise SLOConfigError(f"objective #{i}: {exc}") from exc
        out.append(obj.validate())
    names = [o.name for o in out]
    if len(names) != len(set(names)):
        raise SLOConfigError(f"duplicate objective names in {names}")
    return out


def _counts(snap: dict, threshold_ms: float) -> Tuple[int, int, float]:
    """(total, bad, effective_bound) from one histogram snapshot: bad =
    observations STRICTLY above the smallest bucket bound >= threshold
    (the snap point — exact against the recorded buckets)."""
    total = snap["count"]
    buckets = snap["buckets"]
    # threshold beyond the last finite bound: only +Inf overflow is bad
    good = buckets[-1][1] if buckets else total
    bound = float("inf")
    for le, cumulative in buckets:
        if le >= threshold_ms:
            good = cumulative
            bound = le
            break
    return total, total - good, bound


class SLOEngine:
    """The objective evaluator. One per process (``get_engine()``);
    ``evaluate()`` is driven by the /status scrape path (and anything
    else that wants fresh burn rates), ``snapshot()`` is the lock-free
    read every surface consumes."""

    def __init__(self, objectives: Optional[Sequence[Objective]] = None,
                 now=time.monotonic) -> None:
        objectives = list(objectives if objectives is not None
                          else default_objectives())
        for obj in objectives:
            obj.validate()
        self.objectives: Tuple[Objective, ...] = tuple(objectives)
        self._now = now
        # PLAIN unregistered lock (see module doc): cold-path writer
        # serialization, invisible to the zero-lock read-path gates
        self._lock = threading.Lock()
        # name -> deque[(t, total, bad)] — the burn-rate baselines
        self._samples: Dict[str, Deque[Tuple[float, int, int]]] = {
            obj.name: deque(maxlen=_SAMPLE_RING) for obj in objectives}
        self._breached: Dict[str, bool] = {
            obj.name: False for obj in objectives}
        # counters[*] owned by slo.SLOEngine._lock (tsalint COUNTERS);
        # /status reads them via a C-atomic dict copy
        self.counters: Dict[str, int] = {
            "evals_total": 0, "breaches_total": 0, "recoveries_total": 0}
        self._state: Mapping[str, dict] = MappingProxyType({})
        # breach/recovery subscribers — registered at wiring time (before
        # evaluation traffic), fired OUTSIDE _lock so a subscriber may
        # take its own locks without ordering against the engine's
        self._subscribers: List[Callable[[dict], None]] = []

    # ------------------------------------------------------------ writer

    def _burn(self, obj: Objective,
              samples: Deque[Tuple[float, int, int]],
              now: float, total: int, bad: int,
              window_s: float) -> Tuple[float, float, int]:
        """(burn_rate, actual_window_s, bad_delta) over `window_s`: the
        baseline is the OLDEST sample still inside the window (an engine
        younger than the window honestly reports its shorter actual
        window rather than extrapolating). Scanned newest-first and
        stopped at the window edge, so an evaluation pays O(window),
        not O(full sample ring)."""
        baseline: Optional[Tuple[float, int, int]] = None
        horizon = now - window_s
        for sample in reversed(samples):
            if sample[0] < horizon:
                break
            baseline = sample
        if baseline is None and samples:
            baseline = samples[-1]
        if baseline is None:
            return 0.0, 0.0, 0
        d_total = total - baseline[1]
        d_bad = bad - baseline[2]
        if d_total <= 0:
            return 0.0, now - baseline[0], 0
        error_rate = d_bad / d_total
        return (error_rate / (1.0 - obj.target),
                now - baseline[0], d_bad)

    @staticmethod
    def _exemplar(snap: dict, bound: float) -> Optional[dict]:
        """The latest exemplar from a bucket ABOVE the objective's snap
        bound — a trace that actually violated the contract. When the
        bound IS +Inf (threshold beyond the last finite bucket), the
        overflow bucket itself holds every bad observation, so its
        exemplar qualifies — excluding it would leave exactly those
        objectives exemplar-less."""
        best: Optional[dict] = None
        for ex in snap.get("exemplars") or ():
            le = float("inf") if ex["le"] == "+Inf" else float(ex["le"])
            if le <= bound and le != float("inf"):
                continue
            if best is None or ex["ts"] > best["ts"]:
                best = ex
        return best

    def subscribe(self, listener: Callable[[dict], None]) -> None:
        """Register a breach/recovery listener. Called once per latched
        transition with ``{"slo", "kind": "breach"|"recovered",
        "histogram", "burn_fast", "burn_slow", "exemplar"}`` — OUTSIDE
        the engine lock, from whichever thread drove evaluate(). A
        raising listener is logged and never breaks an evaluation.
        Register at wiring time (before evaluation traffic): the list is
        append-only and read without a lock."""
        self._subscribers.append(listener)

    def evaluate(self, now: Optional[float] = None) -> Mapping[str, dict]:
        """One evaluation pass: sample every objective's histogram,
        recompute both windows' burn rates, latch/unlatch breaches
        (transitions count + emit ``slo.breach``/``slo.recovered``
        flight-recorder events carrying the exemplar trace), swap the
        immutable state snapshot readers consume, and fan latched
        transitions out to subscribers after the lock is released."""
        if now is None:
            now = self._now()
        transitions: List[dict] = []
        with self._lock:
            self.counters["evals_total"] += 1
            fresh: Dict[str, dict] = {}
            for obj in self.objectives:
                snap = trace.histogram(obj.histogram).snapshot()
                total, bad, bound = _counts(snap, obj.threshold_ms)
                samples = self._samples[obj.name]
                if not samples or now - samples[-1][0] >= _SAMPLE_GAP_S:
                    samples.append((now, total, bad))
                fast, fast_w, fast_bad = self._burn(
                    obj, samples, now, total, bad, obj.fast_window_s)
                slow, slow_w, _slow_bad = self._burn(
                    obj, samples, now, total, bad, obj.slow_window_s)
                exemplar = self._exemplar(snap, bound)
                was = self._breached[obj.name]
                if not was and fast >= obj.burn_fast \
                        and slow >= obj.burn_slow and fast_bad > 0:
                    self._breached[obj.name] = True
                    self.counters["breaches_total"] += 1
                    trace.event(
                        "slo.breach", slo=obj.name,
                        histogram=obj.histogram,
                        burn_fast=round(fast, 2),
                        burn_slow=round(slow, 2),
                        exemplar_trace=(exemplar or {}).get("trace_id"))
                    transitions.append({
                        "slo": obj.name, "kind": "breach",
                        "histogram": obj.histogram,
                        "burn_fast": fast, "burn_slow": slow,
                        "exemplar": exemplar})
                    log.warning(
                        "SLO BREACH: %s burn fast=%.1f slow=%.1f "
                        "(threshold %gms target %g) exemplar=%s",
                        obj.name, fast, slow, obj.threshold_ms,
                        obj.target, (exemplar or {}).get("trace_id"))
                elif was and slow < obj.burn_slow \
                        and fast < obj.burn_fast:
                    # recovery latches only via the SLOW window: a fast
                    # dip during a sustained burn must not unlatch (the
                    # hysteresis the remediation plane leans on)
                    self._breached[obj.name] = False
                    self.counters["recoveries_total"] += 1
                    trace.event(
                        "slo.recovered", slo=obj.name,
                        histogram=obj.histogram,
                        burn_fast=round(fast, 2),
                        burn_slow=round(slow, 2))
                    transitions.append({
                        "slo": obj.name, "kind": "recovered",
                        "histogram": obj.histogram,
                        "burn_fast": fast, "burn_slow": slow,
                        "exemplar": exemplar})
                    log.warning(
                        "SLO RECOVERED: %s burn fast=%.2f slow=%.2f",
                        obj.name, fast, slow)
                budget = 1.0 - obj.target
                fresh[obj.name] = {
                    "histogram": obj.histogram,
                    "threshold_ms": obj.threshold_ms,
                    "effective_bound_ms": ("+Inf" if bound == float("inf")
                                           else bound),
                    "target": obj.target,
                    "good_total": total - bad,
                    "bad_total": bad,
                    "burn_rate_fast": round(fast, 4),
                    "burn_rate_slow": round(slow, 4),
                    "window_fast_s": obj.fast_window_s,
                    "window_slow_s": obj.slow_window_s,
                    "window_fast_actual_s": round(fast_w, 1),
                    "window_slow_actual_s": round(slow_w, 1),
                    "budget_remaining": round(
                        1.0 - (bad / total / budget), 4) if total else 1.0,
                    "breached": self._breached[obj.name],
                    "exemplar": exemplar,
                }
            self._state = MappingProxyType(fresh)
        for event in transitions:
            for listener in self._subscribers:
                try:
                    listener(dict(event))
                except Exception:
                    log.exception("SLO subscriber failed on %s/%s",
                                  event["slo"], event["kind"])
        return self._state

    # ------------------------------------------------------------ readers

    def snapshot(self) -> dict:
        """Lock-free: one immutable-mapping attribute read + a C-atomic
        counters copy. The /status ``slo`` section."""
        counters = dict(self.counters)
        return {"objectives": {name: dict(rec)
                               for name, rec in self._state.items()},
                "evals_total": counters["evals_total"],
                "breaches_total": counters["breaches_total"],
                "recoveries_total": counters.get("recoveries_total", 0)}

    def dump_state(self) -> dict:
        """The trace-dump extra (register via attach_to_dumps): the full
        burn-rate state for the post-mortem, re-evaluated so a crash
        dump is current, not one scrape stale."""
        try:
            self.evaluate()
        except Exception:               # a dump must never fail on this
            pass
        return self.snapshot()

    def attach_to_dumps(self) -> None:
        """Register this engine's state as the ``slo`` section of every
        crash/SIGHUP flight dump."""
        trace.register_dump_extra("slo", self.dump_state)


# --------------------------------------------------- process-global seam

_engine: Optional[SLOEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> SLOEngine:
    """The process-global engine (built with the default objectives on
    first use, like the trace plane itself — the SLO surfaces are part
    of the always-on observability plane, not opt-in wiring)."""
    global _engine
    engine = _engine
    if engine is None:
        with _engine_lock:
            if _engine is None:
                _engine = SLOEngine()
            engine = _engine
    return engine


def set_engine(engine: Optional[SLOEngine]) -> Optional[SLOEngine]:
    """Swap the process-global engine (cli --slo-config, tests).
    Returns the previous one."""
    global _engine
    with _engine_lock:
        prev, _engine = _engine, engine
    return prev


# ----------------------------------------------------------- /metrics

def render_prometheus(engine: SLOEngine) -> List[str]:
    """tpu_plugin_slo_* families for the /metrics scrape (strict
    text-format: HELP/TYPE per family, contiguous). Reads the lock-free
    snapshot — the caller (status.metrics) drives evaluate() via
    status()."""
    from .status import _esc
    snap = engine.snapshot()
    objectives = snap["objectives"]
    lines: List[str] = [
        "# HELP tpu_plugin_slo_burn_rate Error-budget burn rate per "
        "objective and window (1 = exactly consuming the budget).",
        "# TYPE tpu_plugin_slo_burn_rate gauge",
    ]
    for name, rec in sorted(objectives.items()):
        for window in ("fast", "slow"):
            lines.append(
                f'tpu_plugin_slo_burn_rate{{slo="{_esc(name)}",'
                f'window="{window}"}} {rec[f"burn_rate_{window}"]}')
    lines += ["# HELP tpu_plugin_slo_breached Objective currently in "
              "multiwindow breach (latched until the slow window cools).",
              "# TYPE tpu_plugin_slo_breached gauge"]
    for name, rec in sorted(objectives.items()):
        lines.append(f'tpu_plugin_slo_breached{{slo="{_esc(name)}"}} '
                     f'{int(rec["breached"])}')
    lines += ["# HELP tpu_plugin_slo_bad_total Observations over the "
              "objective threshold (derived from the histogram buckets; "
              "monotone).",
              "# TYPE tpu_plugin_slo_bad_total counter"]
    for name, rec in sorted(objectives.items()):
        lines.append(f'tpu_plugin_slo_bad_total{{slo="{_esc(name)}"}} '
                     f'{rec["bad_total"]}')
    lines += ["# HELP tpu_plugin_slo_good_total Observations within the "
              "objective threshold.",
              "# TYPE tpu_plugin_slo_good_total counter"]
    for name, rec in sorted(objectives.items()):
        lines.append(f'tpu_plugin_slo_good_total{{slo="{_esc(name)}"}} '
                     f'{rec["good_total"]}')
    lines += ["# HELP tpu_plugin_slo_budget_remaining Lifetime error "
              "budget remaining (1 = untouched; negative = overspent).",
              "# TYPE tpu_plugin_slo_budget_remaining gauge"]
    for name, rec in sorted(objectives.items()):
        lines.append(
            f'tpu_plugin_slo_budget_remaining{{slo="{_esc(name)}"}} '
            f'{rec["budget_remaining"]}')
    lines += ["# HELP tpu_plugin_slo_breaches_total Multiwindow breach "
              "transitions since start (slo.breach flight-recorder "
              "events).",
              "# TYPE tpu_plugin_slo_breaches_total counter",
              f"tpu_plugin_slo_breaches_total {snap['breaches_total']}",
              "# HELP tpu_plugin_slo_recoveries_total Latched breach "
              "recoveries (slo.recovered flight-recorder events; the "
              "slow window cooled below its threshold).",
              "# TYPE tpu_plugin_slo_recoveries_total counter",
              f"tpu_plugin_slo_recoveries_total {snap['recoveries_total']}",
              "# HELP tpu_plugin_slo_evals_total Engine evaluation "
              "passes (one per /status scrape).",
              "# TYPE tpu_plugin_slo_evals_total counter",
              f"tpu_plugin_slo_evals_total {snap['evals_total']}",
              "# HELP tpu_plugin_slo_exemplar_info Latest over-threshold "
              "observation's trace per objective (present whenever one "
              "was ever recorded — join with the burn/breached series "
              "before paging); the trace_id label resolves on "
              "/debug/fleet/trace.",
              "# TYPE tpu_plugin_slo_exemplar_info gauge"]
    for name, rec in sorted(objectives.items()):
        ex = rec.get("exemplar")
        if ex:
            lines.append(
                f'tpu_plugin_slo_exemplar_info{{slo="{_esc(name)}",'
                f'trace_id="{_esc(ex["trace_id"])}"}} 1')
    return lines
