"""Allocate semantics (reference: generic_device_plugin_test.go:180-331)."""

import os
from dataclasses import replace

import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import allocate, discovery
from tpu_device_plugin.config import Config
from tpu_device_plugin.kubeletapi import pb


@pytest.fixture
def host4(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:06.0", iommu_group="12", numa_node=1))
    host.add_chip(FakeChip("0000:00:07.0", iommu_group="12", numa_node=1))
    return host


def setup(host, **overrides):
    cfg = Config().with_root(host.root)
    if overrides:
        cfg = replace(cfg, **overrides)
    registry, _ = discovery.discover_passthrough(cfg)
    return cfg, registry


def test_happy_path_expands_group(host4):
    cfg, registry = setup(host4)
    plan = allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])
    # requesting one BDF pulls in its whole iommu group
    assert plan.expanded_bdfs == ["0000:00:04.0", "0000:00:05.0"]
    host_paths = [s.host_path for s in plan.device_specs]
    assert host_paths == [
        cfg.dev_path("dev/vfio/vfio"),
        cfg.dev_path("dev/vfio", "11"),
    ]
    assert all(s.permissions == "mrw" for s in plan.device_specs)
    assert plan.envs == {
        "PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4":
            "0000:00:04.0,0000:00:05.0"}


def test_two_groups_deduped(host4):
    cfg, registry = setup(host4)
    plan = allocate.plan_allocation(
        cfg, registry, "v4",
        ["0000:00:04.0", "0000:00:05.0", "0000:00:06.0"])
    host_paths = [s.host_path for s in plan.device_specs]
    assert host_paths == [
        cfg.dev_path("dev/vfio/vfio"),
        cfg.dev_path("dev/vfio", "11"),
        cfg.dev_path("dev/vfio", "12"),
    ]
    assert len(plan.expanded_bdfs) == 4


def test_unknown_bdf_errors(host4):
    cfg, registry = setup(host4)
    with pytest.raises(allocate.AllocationError, match="not a known TPU"):
        allocate.plan_allocation(cfg, registry, "v4", ["0000:00:99.0"])


def test_toctou_group_change_rejected(host4):
    cfg, registry = setup(host4)
    # after discovery, the kernel moved the device to another iommu group
    link = os.path.join(cfg.pci_base_path, "0000:00:05.0", "iommu_group")
    os.unlink(link)
    os.symlink(os.path.join(host4.iommu_groups, "99"), link)
    with pytest.raises(allocate.AllocationError, match="iommu group changed"):
        allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])


def test_toctou_vendor_change_rejected(host4):
    cfg, registry = setup(host4)
    with open(os.path.join(cfg.pci_base_path, "0000:00:04.0", "vendor"), "w") as f:
        f.write("0x10de\n")
    with pytest.raises(allocate.AllocationError, match="not a TPU"):
        allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])


def test_iommufd_path_ordering(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", vfio_dev="vfio3"))
    host.enable_iommufd()
    cfg, registry = setup(host)
    plan = allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])
    host_paths = [s.host_path for s in plan.device_specs]
    assert host_paths == [
        cfg.dev_path("dev/vfio/vfio"),
        cfg.dev_path("dev/vfio", "11"),
        cfg.dev_path("dev/vfio/devices", "vfio3"),
        cfg.dev_path("dev/iommu"),
    ]
    container_paths = [s.container_path for s in plan.device_specs]
    assert container_paths == [
        "/dev/vfio/vfio", "/dev/vfio/11", "/dev/vfio/devices/vfio3", "/dev/iommu"]


def test_shared_device_all_or_nothing(host4):
    # shared device spans both chips of group 11
    host4.add_shared_device("egm0", ["0000:00:04.0", "0000:00:05.0"])
    cfg, registry = setup(host4)
    full = allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])
    assert any(s.host_path.endswith("/egm0") for s in full.device_specs)
    # an allocation that covers only group 12 must NOT get egm0
    partial = allocate.plan_allocation(cfg, registry, "v4", ["0000:00:06.0"])
    assert not any(s.host_path.endswith("/egm0") for s in partial.device_specs)


def test_shared_device_spanning_sockets(host4):
    # shared device spans chips in different groups: only a both-group
    # allocation may receive it (reference multi-socket EGM test analogue)
    host4.add_shared_device("egm1", ["0000:00:04.0", "0000:00:06.0"])
    cfg, registry = setup(host4)
    both = allocate.plan_allocation(
        cfg, registry, "v4", ["0000:00:04.0", "0000:00:06.0"])
    assert any(s.host_path.endswith("/egm1") for s in both.device_specs)
    one = allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])
    assert not any(s.host_path.endswith("/egm1") for s in one.device_specs)


def test_shared_device_missing_dev_node_tolerated(host4):
    host4.add_shared_device("egm2", ["0000:00:04.0", "0000:00:05.0"])
    os.unlink(os.path.join(host4.devfs, "egm2"))
    cfg, registry = setup(host4)
    plan = allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])
    assert not any("egm2" in s.host_path for s in plan.device_specs)


def test_gpu_devices_member_file_accepted(host4, tmp_path):
    # Grace-Hopper-style EGM trees name the membership file gpu_devices
    base = os.path.join(host4.root, "sys/class/egm/egm3")
    os.makedirs(base)
    with open(os.path.join(base, "gpu_devices"), "w") as f:
        f.write("0000:00:04.0\n0000:00:05.0\n")
    with open(os.path.join(host4.devfs, "egm3"), "w") as f:
        f.write("")
    cfg, registry = setup(host4)
    plan = allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])
    assert any(s.host_path.endswith("/egm3") for s in plan.device_specs)


def test_allocate_response_multi_container(host4):
    cfg, registry = setup(host4)
    req = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"]),
        pb.ContainerAllocateRequest(devices_ids=["0000:00:06.0"]),
    ])
    resp = allocate.allocate_response(cfg, registry, "v4", req)
    assert len(resp.container_responses) == 2
    assert resp.container_responses[1].envs[
        "PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4"] == "0000:00:06.0,0000:00:07.0"


def test_allocate_scoped_to_plugin_devices(host4):
    """A plugin must reject BDFs of another model (beats the reference's
    global-map lookup, generic_device_plugin.go:376-380)."""
    cfg, registry = setup(host4)
    # pretend this plugin only manages group 12's chips (the "v5e" set)
    with pytest.raises(allocate.AllocationError, match="not managed by resource"):
        allocate.plan_allocation(
            cfg, registry, "v5e", ["0000:00:04.0"],
            allowed_bdfs=frozenset({"0000:00:06.0", "0000:00:07.0"}))


def test_allocate_scope_allows_own_devices(host4):
    cfg, registry = setup(host4)
    plan = allocate.plan_allocation(
        cfg, registry, "v4", ["0000:00:04.0"],
        allowed_bdfs=frozenset({"0000:00:04.0", "0000:00:05.0"}))
    assert plan.expanded_bdfs == ["0000:00:04.0", "0000:00:05.0"]


def test_iommufd_missing_cdev_fails_fast(tmp_path):
    """iommufd host + unreadable vfio-dev entry: fail the whole Allocate
    rather than boot the VM with an incomplete device set."""
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))  # no vfio_dev
    host.enable_iommufd()
    cfg, registry = setup(host)
    with pytest.raises(allocate.AllocationError, match="no vfio-dev cdev"):
        allocate.plan_allocation(cfg, registry, "v4", ["0000:00:04.0"])


# ------------------------------------------------------- LiveAttrReader


def test_live_attr_reader_rereads_in_place_writes(tmp_path):
    """pread on the kept fd sees content rewritten IN PLACE (same inode)
    — the live-read property the TOCTOU guards rely on."""
    p = str(tmp_path / "vendor")
    with open(p, "w") as f:
        f.write("0x1ae0\n")
    r = allocate.LiveAttrReader()
    assert r.read("k", p) == b"0x1ae0\n"
    with open(p, "w") as f:           # truncate+write: same inode
        f.write("0xdead\n")
    assert r.read("k", p) == b"0xdead\n"
    assert len(r._fds) == 1           # still the cached fd


def test_live_attr_reader_detects_unlink_recreate(tmp_path):
    """unlink does not invalidate an open fd on a regular filesystem; the
    st_nlink==0 check must force a fresh open so the NEW inode is read."""
    p = str(tmp_path / "vendor")
    with open(p, "w") as f:
        f.write("old\n")
    r = allocate.LiveAttrReader()
    assert r.read("k", p) == b"old\n"
    os.unlink(p)
    with open(p, "w") as f:
        f.write("new\n")
    assert r.read("k", p) == b"new\n"


def test_live_attr_reader_gone_and_empty_are_none(tmp_path):
    p = str(tmp_path / "vendor")
    r = allocate.LiveAttrReader()
    assert r.read("k", p) is None     # absent
    with open(p, "w"):
        pass
    assert r.read("k", p) is None     # empty: None, never cached
    assert r._fds == {}
    with open(p, "w") as f:
        f.write("now\n")
    assert r.read("k", p) == b"now\n"
    os.unlink(p)
    assert r.read("k", p) is None     # gone again after being cached


# ------------------------------------------------- precompiled fragments


def iommufd_host8(tmp_path):
    """8 single-chip groups on an iommufd host (cdev per chip)."""
    host = FakeHost(tmp_path)
    for i in range(8):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i),
                               vfio_dev=f"vfio{i}"))
    host.enable_iommufd()
    return host


def test_fragment_cache_hits_after_first_plan(tmp_path):
    host = iommufd_host8(tmp_path)
    cfg, registry = setup(host)
    planner = allocate.AllocationPlanner(cfg, registry, "v4")
    bdfs = [f"0000:00:{4 + i:02x}.0" for i in range(8)]
    first = planner.plan(bdfs)
    stats = planner.fragment_stats()
    assert stats == {"hits": 0, "misses": 8, "size": 8}
    second = planner.plan(bdfs)
    assert planner.fragment_stats()["hits"] == 8
    # identical response either way (specs, order, env, CDI names)
    assert [s.host_path for s in second.device_specs] == \
        [s.host_path for s in first.device_specs]
    assert second.envs == first.envs
    assert second.cdi_names == first.cdi_names
    assert second.cdi_names[0].endswith("=0000:00:04.0")


def test_fragment_hit_skips_cdev_listdir_but_never_revalidation(tmp_path):
    """The warm plan must do ZERO vfio-dev listdirs (the fragment carries
    the cdev specs) while the per-member TOCTOU reads — group link +
    vendor — appear in BOTH plans in equal number (never cached)."""
    host = iommufd_host8(tmp_path)
    cfg, registry = setup(host)
    planner = allocate.AllocationPlanner(cfg, registry, "v4")
    bdfs = [f"0000:00:{4 + i:02x}.0" for i in range(8)]

    def split(paths):
        cdev = [p for p in paths if "vfio-dev" in p]
        reval = [p for p in paths
                 if p.endswith("iommu_group") or p.endswith("vendor")]
        return cdev, reval

    with allocate.count_plan_reads() as cold:
        planner.plan(bdfs)
    with allocate.count_plan_reads() as warm:
        planner.plan(bdfs)
    cold_cdev, cold_reval = split(cold.paths)
    warm_cdev, warm_reval = split(warm.paths)
    assert len(cold_cdev) == 8
    assert warm_cdev == []
    assert len(cold_reval) == len(warm_reval) == 16   # 2 live reads/member
    assert warm.reads < cold.reads


def test_fragment_invalidation_recompiles_renamed_cdev(tmp_path):
    """A health flap drops the group's fragment; the next plan re-lists the
    cdev and serves the NEW name (the blind spot is only a rename with no
    flap — docs/perf.md)."""
    import shutil

    host = iommufd_host8(tmp_path)
    cfg, registry = setup(host)
    planner = allocate.AllocationPlanner(cfg, registry, "v4")
    bdf = "0000:00:04.0"
    plan = planner.plan([bdf])
    assert any(s.host_path.endswith("vfio0") for s in plan.device_specs)
    # the kernel re-enumerates the cdev (unbind/rebind)
    base = os.path.join(host.pci, bdf, "vfio-dev")
    shutil.rmtree(base)
    os.makedirs(os.path.join(base, "vfio9"))
    with open(os.path.join(host.devfs, "vfio", "devices", "vfio9"), "w"):
        pass
    # without invalidation the stale fragment still serves vfio0
    stale = planner.plan([bdf])
    assert any(s.host_path.endswith("vfio0") for s in stale.device_specs)
    planner.invalidate_fragments()
    fresh = planner.plan([bdf])
    assert any(s.host_path.endswith("vfio9") for s in fresh.device_specs)
    assert not any(s.host_path.endswith("vfio0") for s in fresh.device_specs)


def test_fragment_iommufd_flip_misses(tmp_path):
    """/dev/iommu appearing (or vanishing) must rebuild fragments — the
    iommufd state is part of the fragment identity, with shared_scan_ttl_s
    0 keeping the reference's per-RPC /dev/iommu stat."""
    host = iommufd_host8(tmp_path)
    cfg, registry = setup(host, shared_scan_ttl_s=0.0)
    planner = allocate.AllocationPlanner(cfg, registry, "v4")
    bdf = "0000:00:04.0"
    plan = planner.plan([bdf])
    assert any("vfio-dev" not in s.host_path
               and s.host_path.endswith("iommu")
               for s in plan.device_specs)
    os.unlink(os.path.join(host.devfs, "iommu"))
    downgraded = planner.plan([bdf])
    paths = [s.host_path for s in downgraded.device_specs]
    assert not any(p.endswith("/iommu") or "/devices/" in p for p in paths)
    assert planner.fragment_stats()["misses"] == 2


def test_fragment_failure_never_cached(tmp_path):
    """An iommufd host with a missing cdev fails the plan — and the next
    plan after the cdev appears succeeds (failures are not cached)."""
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))  # no vfio_dev
    host.enable_iommufd()
    cfg, registry = setup(host)
    planner = allocate.AllocationPlanner(cfg, registry, "v4")
    with pytest.raises(allocate.AllocationError, match="no vfio-dev cdev"):
        planner.plan(["0000:00:04.0"])
    os.makedirs(os.path.join(host.pci, "0000:00:04.0", "vfio-dev", "vfio7"))
    os.makedirs(os.path.join(host.devfs, "vfio", "devices"), exist_ok=True)
    with open(os.path.join(host.devfs, "vfio", "devices", "vfio7"), "w"):
        pass
    plan = planner.plan(["0000:00:04.0"])
    assert any(s.host_path.endswith("vfio7") for s in plan.device_specs)
