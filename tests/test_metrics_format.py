"""Prometheus text-format conformance for the /metrics scrape.

The exposition had never been validated against a parser (ISSUE 8
satellite): every series must carry # HELP and # TYPE lines, label
values must be escaped per the spec, families must be contiguous, no
series may repeat, and histogram families must be internally consistent
(_bucket cumulative, +Inf == _count, _sum present). The parser here is a
strict line grammar — any line that is not a well-formed HELP, TYPE or
sample line fails the test.
"""

import os
import re

import pytest

from tests.fakehost import FakeChip, FakeHost
from tests.test_dra import FakeApiServer, make_driver
from tpu_device_plugin import faults, fleetplace, fleetsim, lockdep, trace
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.lifecycle import PluginManager
from tpu_device_plugin.remediation import RemediationEngine
from tpu_device_plugin.server import TpuDevicePlugin
from tpu_device_plugin.status import StatusServer, _esc

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"'
_HELP_RE = re.compile(rf"^# HELP ({_NAME}) (.+)$")
_TYPE_RE = re.compile(
    rf"^# TYPE ({_NAME}) (counter|gauge|histogram|summary|untyped)$")
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(?:\{{({_LABEL}(?:,{_LABEL})*)?\}})?"
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|\+Inf|-Inf|NaN)$")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\\\|\\"|\\n)*)"')

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def parse_scrape(text):
    """Strict parse → (types, helps, samples). Raises AssertionError on
    any malformed line. samples = [(family, name, labels-dict, value)]."""
    assert text.endswith("\n"), "scrape must end with a newline"
    types, helps, samples = {}, {}, []
    for line in text[:-1].split("\n"):
        m = _HELP_RE.match(line)
        if m:
            assert m.group(1) not in helps, f"duplicate HELP: {line}"
            helps[m.group(1)] = m.group(2)
            continue
        m = _TYPE_RE.match(line)
        if m:
            assert m.group(1) not in types, f"duplicate TYPE: {line}"
            types[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        name, label_blob, value = m.group(1), m.group(2), m.group(3)
        family = name
        for suffix in _HIST_SUFFIXES:
            base = name.removesuffix(suffix)
            if name.endswith(suffix) and types.get(base) == "histogram":
                family = base
        labels = dict(_LABEL_RE.findall(label_blob or ""))
        samples.append((family, name, labels, value))
    return types, helps, samples


@pytest.fixture()
def full_scrape(short_root):
    """A fully-populated daemon: plugin + DRA (with apiserver) + health
    hub + lifecycle FSM + discovery snapshot + a fired fault + trace
    histograms + lockdep read-path counters — every /metrics family the
    daemon can emit is present in one scrape."""
    with lockdep.scoped():
        host = FakeHost(short_root)
        host.add_chip(FakeChip("0000:00:04.0", device_id="0063",
                               iommu_group="11"))
        cfg = Config().with_root(host.root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        apiserver = FakeApiServer()
        manager = PluginManager(cfg)
        registry, _ = discover_passthrough(cfg)
        manager.plugins = [TpuDevicePlugin(
            cfg, "v5e", registry, registry.devices_by_model["0063"])]
        manager._rediscover()                    # discovery stats exist
        manager.device_lifecycle.sync_inventory({"0000:00:04.0": None})
        driver = make_driver(cfg, apiserver)
        driver.publish_resource_slices()
        # self-heal plane attached: the tpu_plugin_remediation_*
        # families and the /status remediation section are in the scrape
        manager.remediation_engine = RemediationEngine(pacer=driver.pacer)
        manager.remediation_engine.on_transition(
            {"slo": "attach-p99", "kind": "breach",
             "histogram": "tdp_attach_wall_ms",
             "exemplar": {"trace_id": "ab" * 16}})
        manager.remediation_engine.tick()        # remediation counters move
        faults.arm("dra.publish", kind="drop", count=1)
        faults.fire("dra.publish")               # fault stats exist
        trace.observe("tdp_attach_wall_ms", 1.25)
        trace.observe("tdp_kubeapi_rtt_ms", 42.0)
        # sharded scheduler plane (ISSUE 17): a cache-mode
        # FleetScheduler fed one synthetic sync + one advisory wave, so
        # the tpu_plugin_fleet_* families and the /status "fleet"
        # section are in the scrape
        objs, pod_dims = fleetsim.synthetic_slice_objects(
            2, devices_per_node=4)
        fleet_cache = fleetplace.SliceCache(pod_dims=pod_dims)
        fleet_cache.on_sync(objs)
        fleet_sched = fleetplace.FleetScheduler(
            cache=fleet_cache, pod_dims=pod_dims)
        fleet_sched.submit("1x2", "scrape-claim")
        fleet_sched.pump(force=True)             # fleet counters move
        server = StatusServer(manager, port=0, dra_driver=driver,
                              fleet_scheduler=fleet_sched)
        try:
            server.status()                      # warm read_path counters
            yield server.metrics(), server
        finally:
            server._httpd.server_close()
            apiserver.stop()
            faults.reset()
            trace.reset()


def test_every_series_has_help_and_type_and_parses(full_scrape):
    text, _ = full_scrape
    types, helps, samples = parse_scrape(text)
    assert samples, "empty scrape"
    for family, name, labels, _value in samples:
        assert family in types, f"sample {name} has no # TYPE"
        assert family in helps, f"sample {name} has no # HELP"
    # the rig exercises every subsystem: spot-check the families that
    # have drifted or were added by this PR
    for family in ("tpu_plugin_devices", "tpu_plugin_epoch",
                   "lifecycle_transitions_total", "claims_orphaned_total",
                   "tpu_plugin_dra_attach_active",
                   "tpu_plugin_health_existence_scans_total",
                   "tpu_plugin_lifecycle_invalid_transitions_total",
                   "tdp_fault_fires_total", "tdp_trace_spans_total",
                   "tdp_read_path_lock_acquisitions_total",
                   "tdp_attach_wall_ms",
                   "tpu_plugin_remediation_actions_total",
                   "tpu_plugin_kubeapi_breaker_half_open_rejected_total"):
        assert family in types, f"family {family} missing from scrape"


def test_families_are_contiguous_and_series_unique(full_scrape):
    text, _ = full_scrape
    _types, _helps, samples = parse_scrape(text)
    seen_series = set()
    family_order, closed = [], set()
    for family, name, labels, _value in samples:
        series = (name, tuple(sorted(labels.items())))
        assert series not in seen_series, f"duplicate series {series}"
        seen_series.add(series)
        if not family_order or family_order[-1] != family:
            assert family not in closed, \
                f"family {family} reappears after other samples"
            if family_order:
                closed.add(family_order[-1])
            family_order.append(family)


def test_histogram_families_are_internally_consistent(full_scrape):
    text, _ = full_scrape
    types, _helps, samples = parse_scrape(text)
    hist_families = [f for f, t in types.items() if t == "histogram"]
    assert "tdp_attach_wall_ms" in hist_families
    for family in hist_families:
        buckets = [(labels["le"], float(value))
                   for f, name, labels, value in samples
                   if f == family and name == f"{family}_bucket"]
        counts = {name: float(value) for f, name, _l, value in samples
                  if f == family and name in (f"{family}_count",
                                              f"{family}_sum")}
        assert buckets and buckets[-1][0] == "+Inf", family
        cum = [n for _le, n in buckets]
        assert cum == sorted(cum), f"{family} buckets not cumulative"
        les = [float(le) for le, _n in buckets[:-1]]
        assert les == sorted(les), f"{family} le bounds unsorted"
        assert counts[f"{family}_count"] == cum[-1], family
        assert f"{family}_sum" in counts, family


def test_counter_and_gauge_types_are_declared_correctly(full_scrape):
    text, _ = full_scrape
    types, _helps, _samples = parse_scrape(text)
    # *_total families follow the counter convention
    for family, kind in types.items():
        if kind == "histogram":
            continue
        if family.endswith("_total"):
            assert kind == "counter", (family, kind)


def test_label_values_are_escaped_per_spec():
    assert _esc('plain') == "plain"
    assert _esc('say "hi"') == 'say \\"hi\\"'
    assert _esc("back\\slash") == "back\\\\slash"
    assert _esc("multi\nline") == "multi\\nline"

    # a hostile resource name renders to a parseable sample line
    class Hostile(StatusServer):
        def __init__(self):   # no HTTP server needed
            pass

        def status(self):
            return {"plugins": [{
                "resource": 'tpu"v4\\weird\nname',
                "devices": {"a": "Healthy"}, "serving": True,
                "restarts": 0, "allocations_total": 0, "epoch": 1,
                "degraded_links": {}, "preferred_cache": {},
                "alloc_fragments": {}, "restart_backoff": {},
                "lw_resends": 0,
            }], "pending": [], "native": {}, "draining": False}

    text = Hostile().metrics()
    types, helps, samples = parse_scrape(text)
    resources = {labels.get("resource") for _f, name, labels, _v in samples
                 if name == "tpu_plugin_serving"}
    # the parser returns the ESCAPED form; unescaping restores the name
    assert resources == {'tpu\\"v4\\\\weird\\nname'}
