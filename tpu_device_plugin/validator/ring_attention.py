"""Ring attention — sequence-parallel causal attention over the ICI ring.

The KV-all-gather form of sequence parallelism (workload.py's einsum path)
materializes the full K/V on every chip: O(S) memory per chip. Ring attention
keeps K/V sharded — each of the `sp` shards holds S/sp keys/values — and
rotates the KV block around the mesh axis with `jax.lax.ppermute` while
accumulating attention with the same online-softmax recurrence the Pallas
flash kernel uses. Per-chip residency is O(S/sp) in BOTH directions: the
custom VJP saves only (q, k, v, o, logsumexp) and the backward pass
re-rotates K/V around the ring, recomputing each score tile and rotating the
dK/dV accumulators along with their blocks so every gradient arrives back at
its origin shard after a full cycle. Every hop is a nearest-neighbor ICI
transfer, which is exactly what the torus is for.

Causality at block granularity: shard i's queries attend fully to KV blocks
j < i, causally to block j == i, and not at all to j > i. The rotation
schedule visits the local block first, so the running max is finite from
step 0.

Runs inside `jax.shard_map`; the loop over ring steps is a static Python
unroll (mesh size is static), XLA-friendly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _rotate(t: jax.Array, axis_name: str, n: int) -> jax.Array:
    return jax.lax.ppermute(t, axis_name, [(i, (i + 1) % n) for i in range(n)])


def _block_mask(src, my_idx, tril):
    """Allowed positions for the KV block that originated at shard `src`."""
    return (src < my_idx) | ((src == my_idx) & tril)


def _ring_forward(q, k, v, sm_scale: float, axis_name: str):
    """Online-softmax ring pass; returns (output, logsumexp)."""
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    bh, s_local, d = q.shape
    qf = q.astype(jnp.float32)

    m = jnp.full((bh, s_local, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bh, s_local, 1), jnp.float32)
    acc = jnp.zeros((bh, s_local, d), jnp.float32)
    tril = jnp.tril(jnp.ones((s_local, s_local), jnp.bool_))[None]

    k_cur, v_cur = k, v
    for step in range(n):
        # the KV block now held locally originated at shard (my_idx - step)
        src = (my_idx - step) % n
        s = jnp.einsum("bqd,bkd->bqk", qf, k_cur.astype(jnp.float32)) * sm_scale
        s = jnp.where(_block_mask(src, my_idx, tril), s, NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bqk,bkd->bqd", p, v_cur.astype(jnp.float32))
        m = m_new
        if step != n - 1:
            k_cur = _rotate(k_cur, axis_name, n)
            v_cur = _rotate(v_cur, axis_name, n)
    lse = m + jnp.log(l)
    return (acc / l).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   sm_scale: float, axis_name: str = "sp") -> jax.Array:
    """Causal attention with KV rotating around `axis_name`.

    Local shapes: q, k, v are (heads_batch, seq_local, head_dim); the global
    sequence is the concatenation of shards along `axis_name` in axis order.
    """
    out, _ = _ring_forward(q, k, v, sm_scale, axis_name)
    return out


def _ring_fwd(q, k, v, sm_scale, axis_name):
    out, lse = _ring_forward(q, k, v, sm_scale, axis_name)
    return out, (q, k, v, out, lse)


def _ring_bwd(sm_scale, axis_name, residuals, d_out):
    """Rematerialized backward: re-rotate KV, recompute each tile's
    probabilities from the saved logsumexp, and carry dK/dV accumulators
    around the ring with their blocks (n rotations = home again)."""
    q, k, v, out, lse = residuals
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    bh, s_local, d = q.shape
    qf = q.astype(jnp.float32)
    dof = d_out.astype(jnp.float32)
    tril = jnp.tril(jnp.ones((s_local, s_local), jnp.bool_))[None]
    # D_i = sum_j dO_ij * O_ij (the softmax-jacobian diagonal term)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1, keepdims=True)

    dq = jnp.zeros((bh, s_local, d), jnp.float32)
    k_cur, v_cur = k, v
    dk_cur = jnp.zeros((bh, s_local, d), jnp.float32)
    dv_cur = jnp.zeros((bh, s_local, d), jnp.float32)
    for step in range(n):
        src = (my_idx - step) % n
        kf = k_cur.astype(jnp.float32)
        vf = v_cur.astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
        s = jnp.where(_block_mask(src, my_idx, tril), s, NEG_INF)
        p = jnp.exp(s - lse)                       # masked entries -> 0
        dv_cur = dv_cur + jnp.einsum("bqk,bqd->bkd", p, dof)
        dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
        ds = p * (dp - delta) * sm_scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kf)
        dk_cur = dk_cur + jnp.einsum("bqk,bqd->bkd", ds, qf)
        # rotate the block AND its gradient accumulators; after the n-th
        # rotation each accumulator is back at its block's origin shard.
        # K/V themselves are dead after the last tile — only the
        # accumulators need the final homing hop.
        if step != n - 1:
            k_cur = _rotate(k_cur, axis_name, n)
            v_cur = _rotate(v_cur, axis_name, n)
        dk_cur = _rotate(dk_cur, axis_name, n)
        dv_cur = _rotate(dv_cur, axis_name, n)
    return dq.astype(q.dtype), dk_cur.astype(k.dtype), dv_cur.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


# --- ring flash attention: the Pallas kernel inside each ring step ----------
#
# ring_attention above computes every step's (s_local, s_local) score matrix
# with einsums — simple, but it materializes O(S_local^2) f32 in HBM per
# step, the exact pattern the flash kernel exists to avoid. ring_flash runs
# the blockwise Pallas kernel on each step's LOCAL KV block instead: scores
# never leave VMEM, the MXU sees the same two matmuls per tile as
# single-device flash, and the ring merge happens at block granularity on
# the kernel's (out, logsumexp) pair. The causality mode of a step depends
# on the block's origin shard (full for src < my, causal for src == my,
# skipped for src > my), which is a traced value under shard_map — so the
# three statically-compiled kernel variants sit behind a lax.switch.
#
# Merging a finished block into the running state uses the block's
# logsumexp directly (no separate max needed): a block with normalized
# output o_b and logsumexp lse_b contributes exp(lse_b) total weight and
# o_b * exp(lse_b) weighted sum, so
#   m'   = max(m, lse_b)
#   l'   = l * exp(m - m') + exp(lse_b - m')
#   acc' = acc * exp(m - m') + o_b * exp(lse_b - m')
# The schedule visits the local (causal) block first, so m is finite from
# step 0 and every row has at least its diagonal key; skipped steps carry
# lse_b = NEG_INF and contribute exactly zero.


# Forward block size for the Pallas kernel inside each ring step. 128x128
# matches the single-device flash forward default (the hardware sweep found
# forward insensitive to 128-vs-256 at these shapes, bwd tuned separately
# via flash_attention.DEFAULT_BWD_BLOCK); ring-bench (--mode ring-bench)
# re-measures this cell so the choice stays evidence-backed per round.
RING_STEP_BLOCK = (128, 128)


def _step_mode(src, my_idx):
    """0 = skip (future block), 1 = causal (own block), 2 = full (past)."""
    return jnp.where(src > my_idx, 0, jnp.where(src == my_idx, 1, 2))


def _flash_block(q, k, v, sm_scale, mode, block_q, block_k, interpret):
    """(o_b f32, lse_b (bh, s, 1) f32) for one ring step via lax.switch."""
    from .flash_attention import _flash_3d

    def _run(causal):
        def branch(q, k, v):
            o, lse = _flash_3d(q, k, v, sm_scale, causal, block_q, block_k,
                               interpret, return_lse=True)
            return o.astype(jnp.float32), lse[:, :, :1]
        return branch

    def _skip(q, k, v):
        bh, s, d = q.shape
        return (jnp.zeros((bh, s, d), jnp.float32),
                jnp.full((bh, s, 1), NEG_INF, jnp.float32))

    return jax.lax.switch(mode, (_skip, _run(True), _run(False)), q, k, v)


def _ring_flash_forward(q, k, v, sm_scale, axis_name,
                        block_q, block_k, interpret):
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    bh, s_local, d = q.shape

    m = jnp.full((bh, s_local, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bh, s_local, 1), jnp.float32)
    acc = jnp.zeros((bh, s_local, d), jnp.float32)

    k_cur, v_cur = k, v
    for step in range(n):
        src = (my_idx - step) % n
        o_b, lse_b = _flash_block(q, k_cur, v_cur, sm_scale,
                                  _step_mode(src, my_idx),
                                  block_q, block_k, interpret)
        m_new = jnp.maximum(m, lse_b)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(lse_b - m_new)
        l = alpha * l + beta
        acc = acc * alpha + o_b * beta
        m = m_new
        if step != n - 1:
            k_cur = _rotate(k_cur, axis_name, n)
            v_cur = _rotate(v_cur, axis_name, n)
    lse = m + jnp.log(l)
    return (acc / l).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         sm_scale: float, axis_name: str = "sp",
                         block_q: int = 128, block_k: int = 128,
                         interpret: bool = False,
                         bwd_block_q=None, bwd_block_k=None) -> jax.Array:
    """ring_attention with the Pallas flash kernel inside each step.

    Same contract and residency (O(S/sp) per chip, both directions) as
    ring_attention; the per-step score matrix never exists in HBM. Backward
    reuses the FA-2 dkv/dq Pallas kernel pair per step against the saved
    GLOBAL logsumexp (p = exp(s - lse_global) is each tile's true global
    probability, so per-step partial grads sum exactly like the einsum
    path's).
    """
    out, _ = _ring_flash_forward(q, k, v, sm_scale, axis_name,
                                 block_q, block_k, interpret)
    return out


def _ring_flash_fwd(q, k, v, sm_scale, axis_name, block_q, block_k,
                    interpret, bwd_block_q, bwd_block_k):
    out, lse = _ring_flash_forward(q, k, v, sm_scale, axis_name,
                                   block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(sm_scale, axis_name, block_q, block_k, interpret,
                    bwd_block_q, bwd_block_k, residuals, d_out):
    from .flash_attention import DEFAULT_BWD_BLOCK, LANES, _flash_bwd_3d
    bq = bwd_block_q or DEFAULT_BWD_BLOCK
    bk = bwd_block_k or DEFAULT_BWD_BLOCK
    q, k, v, out, lse = residuals
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    bh, s_local, d = q.shape
    lse_l = jnp.broadcast_to(lse, (bh, s_local, LANES))

    def _run(causal):
        def branch(q, k, v):
            # f32 outputs: each step's partials join a cross-step f32 sum;
            # rounding them to bf16 first would grow gradient noise with
            # ring size (the einsum _ring_bwd accumulates in f32 too)
            return _flash_bwd_3d(q, k, v, out, lse_l, d_out, sm_scale,
                                 causal, bq, bk, interpret,
                                 out_dtype=jnp.float32)
        return branch

    def _skip(q, k, v):
        zeros = jnp.zeros(q.shape, jnp.float32)
        return (zeros, zeros, zeros)

    dq = jnp.zeros((bh, s_local, d), jnp.float32)
    k_cur, v_cur = k, v
    dk_cur = jnp.zeros((bh, s_local, d), jnp.float32)
    dv_cur = jnp.zeros((bh, s_local, d), jnp.float32)
    for step in range(n):
        src = (my_idx - step) % n
        dq_b, dk_b, dv_b = jax.lax.switch(
            _step_mode(src, my_idx), (_skip, _run(True), _run(False)),
            q, k_cur, v_cur)
        dq = dq + dq_b.astype(jnp.float32)
        dk_cur = dk_cur + dk_b.astype(jnp.float32)
        dv_cur = dv_cur + dv_b.astype(jnp.float32)
        # same homing schedule as _ring_bwd: blocks die after the last
        # tile, accumulators take the final hop back to their origin
        if step != n - 1:
            k_cur = _rotate(k_cur, axis_name, n)
            v_cur = _rotate(v_cur, axis_name, n)
        dk_cur = _rotate(dk_cur, axis_name, n)
        dv_cur = _rotate(dv_cur, axis_name, n)
    return dq.astype(q.dtype), dk_cur.astype(k.dtype), dv_cur.astype(v.dtype)


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)
