"""HealthHub: shared inotify plane, probe dedup, deadlines, fallback poller."""

import os
import threading
import time

import pytest

from tpu_device_plugin import faults, healthhub
from tpu_device_plugin.healthhub import HealthHub, HubSubscription


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(autouse=True)
def clean_faults():
    faults.reset()
    yield
    faults.reset()


def _hub(**kw):
    kw.setdefault("poll_interval_s", 0.1)
    kw.setdefault("probe_workers", 4)
    kw.setdefault("probe_deadline_s", 1.0)
    return HealthHub(**kw)


def test_fs_event_fans_out_to_every_subscription(tmp_path):
    """Two resources watching the same node (a chip exposed through two
    plugin servers) must BOTH hear its removal from the one shared fd."""
    node = tmp_path / "vfio" / "11"
    node.parent.mkdir()
    node.write_text("")
    hub = _hub(poll_interval_s=60)  # inotify only: no existence-scan assist
    hits_a, hits_b = [], []
    try:
        hub.subscribe(HubSubscription(
            name="a", group_paths={"ga": str(node)},
            on_device_health=lambda k, ok, src: hits_a.append((k, ok, src))))
        hub.subscribe(HubSubscription(
            name="b", group_paths={"gb": str(node)},
            on_device_health=lambda k, ok, src: hits_b.append((k, ok, src))))
        assert hub.stats()["inotify_fds"] == 1
        node.unlink()
        assert _wait(lambda: ("ga", False, "fs") in hits_a)
        assert _wait(lambda: ("gb", False, "fs") in hits_b)
        node.write_text("")
        assert _wait(lambda: ("ga", True, "fs") in hits_a)
        assert _wait(lambda: ("gb", True, "fs") in hits_b)
    finally:
        hub.stop()


def test_one_inotify_fd_regardless_of_subscription_count(tmp_path):
    (tmp_path / "vfio").mkdir()
    hub = _hub()
    try:
        for i in range(32):
            p = tmp_path / "vfio" / f"n{i}"
            p.write_text("")
            hub.subscribe(HubSubscription(
                name=f"r{i}", group_paths={f"g{i}": str(p)},
                on_device_health=lambda *a: None))
        stats = hub.stats()
        assert stats["subscriptions"] == 32
        assert stats["inotify_fds"] == 1
    finally:
        hub.stop()


def test_probe_dedup_across_subscriptions():
    """A BDF exposed through two resources (chip advertised as passthrough
    AND parent of partitions) is probed ONCE per cycle; both subscribers
    still get their own keyed verdicts."""
    probed = []
    hub = _hub(poll_interval_s=3600)
    verdicts_a, verdicts_b = [], []
    try:
        hub.subscribe(HubSubscription(
            name="pt", group_bdfs={"g1": ["bdf0", "bdf1"]},
            on_device_health=lambda k, ok, src: verdicts_a.append((k, ok, src)),
            probe=lambda b, n: probed.append(b) or True))
        hub.subscribe(HubSubscription(
            name="vtpu", group_bdfs={"bdf0": ["bdf0"]},
            on_device_health=lambda k, ok, src: verdicts_b.append((k, ok, src)),
            probe=lambda b, n: probed.append(b) or True))
        verdicts = hub.probe_cycle()
        assert sorted(probed) == ["bdf0", "bdf1"]  # bdf0 NOT probed twice
        assert verdicts == {"bdf0": True, "bdf1": True}
        assert ("g1", True, "probe") in verdicts_a
        assert ("bdf0", True, "probe") in verdicts_b
        stats = hub.stats()
        assert stats["probes_last_cycle"] == 2
        assert stats["probes_deduped_last_cycle"] == 1
    finally:
        hub.stop()


def test_probe_deadline_bounds_cycle_and_scores_timeout_dead():
    """One hung probe must cost ~the deadline, not its full hang — and the
    hung chip's group scores Unhealthy (counted) while every other chip's
    verdict lands on time."""
    release = threading.Event()

    def probe(bdf, node):
        if bdf == "bdf-slow":
            release.wait(5.0)
        return True

    hub = _hub(poll_interval_s=3600, probe_deadline_s=0.2)
    hits = []
    try:
        hub.subscribe(HubSubscription(
            name="r",
            group_bdfs={"fast": ["bdf0", "bdf1"], "slow": ["bdf-slow"]},
            on_device_health=lambda k, ok, src: hits.append((k, ok)),
            probe=probe))
        t0 = time.monotonic()
        verdicts = hub.probe_cycle()
        wall = time.monotonic() - t0
        assert wall < 2.0, wall        # nowhere near the 5 s hang
        assert verdicts == {"bdf0": True, "bdf1": True, "bdf-slow": False}
        assert ("fast", True) in hits
        assert ("slow", False) in hits
        assert hub.stats()["probe_timeouts_total"] == 1
        # the chip answers next cycle -> recovers
        release.set()
        time.sleep(0.1)
        hub.probe_cycle()
        assert _wait(lambda: ("slow", True) in hits)
    finally:
        release.set()
        hub.stop()


def test_stuck_probe_not_resubmitted_every_cycle():
    """A probe hung past its deadline must NOT be resubmitted while still
    running — each resubmission would strand one more pool worker until the
    shared pool is exhausted and EVERY chip on the host times out. The
    hung chip keeps its dead verdict; fast chips keep probing on time; once
    the read returns the chip is probed fresh and recovers."""
    release = threading.Event()
    calls = {"slow": 0, "fast": 0}

    def probe(bdf, node):
        if bdf == "bdf-slow":
            calls["slow"] += 1
            release.wait(30.0)
        else:
            calls["fast"] += 1
        return True

    hub = _hub(poll_interval_s=3600, probe_workers=2, probe_deadline_s=0.1)
    try:
        hub.subscribe(HubSubscription(
            name="r", group_bdfs={"fast": ["bdf-fast"],
                                  "slow": ["bdf-slow"]},
            on_device_health=lambda *a: None, probe=probe))
        for cycle in range(4):
            verdicts = hub.probe_cycle()
            assert verdicts["bdf-fast"] is True, \
                f"cycle {cycle}: pool exhausted by the hung probe"
            assert verdicts["bdf-slow"] is False
        assert calls["slow"] == 1, \
            f"hung probe resubmitted {calls['slow']} times"
        assert calls["fast"] == 4
        assert hub.stats()["probe_timeouts_total"] == 1
        assert hub.stats()["stuck_probes"] == 1
        # the read returns -> next cycle probes fresh and recovers
        release.set()
        time.sleep(0.1)
        assert hub.probe_cycle()["bdf-slow"] is True
        assert calls["slow"] == 2
        assert hub.stats()["stuck_probes"] == 0
    finally:
        release.set()
        hub.stop()


def test_probe_exception_scores_dead_and_counts_not_kills_hub():
    """Satellite: a raising probe must score its group Unhealthy and bump
    tdp_probe_errors_total — the health plane keeps running."""
    hub = _hub(poll_interval_s=3600)
    hits = []
    try:
        hub.subscribe(HubSubscription(
            name="r", group_bdfs={"g": ["bdf0"]},
            on_device_health=lambda k, ok, src: hits.append((k, ok, src)),
            probe=lambda b, n: (_ for _ in ()).throw(RuntimeError("boom"))))
        verdicts = hub.probe_cycle()
        assert verdicts == {"bdf0": False}
        assert ("g", False, "probe") in hits
        assert hub.stats()["probe_errors_total"] == 1
        # the hub thread survived and still serves cycles
        assert hub.probe_cycle() == {"bdf0": False}
        assert hub._thread.is_alive()
    finally:
        hub.stop()


def test_native_probe_fault_fires_inside_hub():
    """docs/fault-injection.md: native.probe's consultation point is the
    hub's probe runner."""
    hub = _hub(poll_interval_s=3600)
    try:
        hub.subscribe(HubSubscription(
            name="r", group_bdfs={"g": ["bdf0"]},
            on_device_health=lambda *a: None,
            probe=lambda b, n: True))
        with faults.injected("native.probe", kind="false", count=1):
            assert hub.probe_cycle() == {"bdf0": False}
        assert faults.stats().get("native.probe") == 1
        assert hub.probe_cycle() == {"bdf0": True}  # budget exhausted
    finally:
        hub.stop()


def test_inotify_unavailable_degrades_to_one_shared_poller(
        tmp_path, monkeypatch):
    """Satellite: with inotify unavailable and MANY resources subscribed,
    the hub degrades to ONE shared existence poller — one hub thread total,
    zero inotify fds, and every resource still gets its events."""
    def broken_watcher():
        raise OSError(24, "inotify_init1 failed (EMFILE)")

    monkeypatch.setattr(healthhub, "InotifyWatcher", broken_watcher)
    nodes_dir = tmp_path / "nodes"
    nodes_dir.mkdir()
    before = {t for t in threading.enumerate()}
    hub = _hub(poll_interval_s=0.1)
    hits = []
    n_resources = 16
    try:
        for i in range(n_resources):
            p = nodes_dir / f"n{i}"
            p.write_text("")
            hub.subscribe(HubSubscription(
                name=f"r{i}", group_paths={f"g{i}": str(p)},
                on_device_health=(
                    lambda k, ok, src: hits.append((k, ok, src)))))
        stats = hub.stats()
        assert stats["fallback_polling"] is True
        assert stats["inotify_fds"] == 0
        assert stats["subscriptions"] == n_resources
        # exactly ONE poller/loop thread for all 16 resources (probe-pool
        # workers spawn lazily and none are needed here) — the old shape
        # was one monitor thread per resource
        new_threads = [t for t in set(threading.enumerate()) - before
                       if t.name.startswith("healthhub")]
        assert len(new_threads) == 1, [t.name for t in new_threads]
        # existence polling is the event source for EVERY resource
        (nodes_dir / "n0").unlink()
        (nodes_dir / "n15").unlink()
        assert _wait(lambda: ("g0", False, "fs") in hits)
        assert _wait(lambda: ("g15", False, "fs") in hits)
        (nodes_dir / "n0").write_text("")
        assert _wait(lambda: ("g0", True, "fs") in hits)
    finally:
        hub.stop()


def test_socket_removal_fires_once_and_respects_unsubscribe(tmp_path):
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    hub = _hub(poll_interval_s=0.1)
    removed = []
    try:
        sub = hub.subscribe(HubSubscription(
            name="p", socket_path=str(sock),
            on_socket_removed=lambda: removed.append(1)))
        sock.unlink()
        assert _wait(lambda: removed == [1])
        time.sleep(0.3)
        assert removed == [1]  # reported once, not per scan tick
        # a fresh subscription (plugin restart) re-arms the watch
        hub.unsubscribe(sub)
        sock.write_text("")
        hub.subscribe(HubSubscription(
            name="p2", socket_path=str(sock),
            on_socket_removed=lambda: removed.append(2)))
        sock.unlink()
        assert _wait(lambda: removed == [1, 2])
    finally:
        hub.stop()


def test_missing_socket_at_subscribe_time_is_reported(tmp_path):
    """The bind-to-watch race: a socket wiped before subscribe() must be
    reported by the initial scan, not lost (no future inotify event)."""
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    hub = _hub(poll_interval_s=60)
    removed = []
    try:
        hub.subscribe(HubSubscription(
            name="p", socket_path=str(sock_dir / "gone.sock"),
            on_socket_removed=lambda: removed.append(1)))
        assert removed == [1]
    finally:
        hub.stop()


def test_unsubscribed_subscription_gets_no_callbacks(tmp_path):
    node = tmp_path / "n"
    node.write_text("")
    hub = _hub(poll_interval_s=0.1)
    hits = []
    try:
        sub = hub.subscribe(HubSubscription(
            name="r", group_paths={"g": str(node)},
            on_device_health=lambda k, ok, src: hits.append((k, ok))))
        hub.unsubscribe(sub)
        node.unlink()
        time.sleep(0.4)
        assert hits == []
    finally:
        hub.stop()


def test_hub_restartable_after_stop(tmp_path):
    node = tmp_path / "n"
    node.write_text("")
    hub = _hub(poll_interval_s=0.1)
    hits = []
    hub.subscribe(HubSubscription(
        name="r", group_paths={"g": str(node)},
        on_device_health=lambda k, ok, src: hits.append((k, ok))))
    hub.stop()
    try:
        hub.subscribe(HubSubscription(
            name="r2", group_paths={"g2": str(node)},
            on_device_health=lambda k, ok, src: hits.append((k, ok))))
        node.unlink()
        assert _wait(lambda: ("g2", False) in hits)
    finally:
        hub.stop()


def test_constructor_validates_knobs():
    for bad_workers in (0, -1, 1.5):
        with pytest.raises(ValueError, match="probe_workers"):
            HealthHub(probe_workers=bad_workers)
    for bad_deadline in (0, -1.0, float("nan"), float("inf")):
        with pytest.raises(ValueError, match="probe_deadline_s"):
            HealthHub(probe_deadline_s=bad_deadline)
