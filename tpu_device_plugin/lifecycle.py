"""PluginManager — discovery → one plugin server per resource → run loop.

Analogue of `InitiateDevicePlugin`/`createDevicePlugins`
(device_plugin.go:89-176): run discovery once, spin up one TpuDevicePlugin
per TPU model/generation and one VtpuDevicePlugin per partition type —
started and registered CONCURRENTLY (the reference's serial loop made
cold start O(resources) in registration round-trips) — then
block until stopped. All plugin servers share the manager's one
healthhub.HealthHub (one inotify fd + one probe scheduler per host). A plugin that fails to start is logged and skipped, not
fatal (the reference tolerates per-plugin start errors the same way,
device_plugin_test.go:102-107). Optional periodic re-discovery (off by
default, matching the reference's no-hotplug stance) restarts the plugin set
when the host inventory changes.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent import futures
from typing import List, Optional

from . import broker as broker_mod
from . import lockdep
from . import trace
from .config import Config
from .log import get_logger
from .discovery import HostSnapshot, discover, read_serial
from .healthhub import HealthHub, HubSubscription
from .lifecycle_fsm import DeviceLifecycle
from .naming import resource_name_for
from .registry import Registry
from .resilience import BackoffPolicy
from .server import (KubeletUnavailable, RegistrationRejected,
                     TpuDevicePlugin)
from .vtpu import VtpuDevicePlugin

# concurrent plugin startup (see _try_start_pending): bound on how many
# plugin servers start/register at once — enough to collapse a many-resource
# cold start into ~one round-trip, small enough not to thundering-herd the
# kubelet's Registration socket
START_WORKERS = 8

log = get_logger(__name__)


class PluginManager:
    def __init__(self, cfg: Config, on_inventory=None,
                 health_listener=None, policy_engine=None,
                 remediation_engine=None) -> None:
        self.cfg = cfg
        # Optional policy.PolicyEngine, threaded into every plugin server
        # (scoring/health/admission hooks) and surfaced on /status +
        # /debug/policy by status.py. None = builtin behavior everywhere.
        self.policy_engine = policy_engine
        # Optional remediation.RemediationEngine (the self-heal plane):
        # threaded into every plugin server as the Allocate-path
        # admission throttle, surfaced on /status + /debug/remediation.
        self.remediation_engine = remediation_engine
        # called with (registry, generations) after every (re)discovery —
        # the node labeler publishes per-node facts through this seam; a
        # False return (e.g. API server unreachable at node boot) is retried
        # from the run loop even when inventory never changes
        self.on_inventory = on_inventory
        # Every plugin server gets _observe_health as its listener: it feeds
        # flap events into the dirty-rescan hint set, then forwards to the
        # caller-provided listener (the DRA driver prunes dead devices from
        # its ResourceSlice through this).
        self._downstream_health_listener = health_listener
        self.health_listener = self._observe_health
        # Dirty-set incremental rediscovery (discovery.HostSnapshot):
        # device ids whose health CHANGED since the last tick are re-read
        # from sysfs on the next rescan; everything else rides the cache.
        # _health_baseline filters the listener's unconditional snapshot
        # deliveries (every probe poll re-delivers every id) down to real
        # transitions so steady-state polls never dirty anything.
        self.snapshot: Optional[HostSnapshot] = None
        self._dirty: set = set()
        self._dirty_lock = lockdep.instrument(
            "lifecycle.PluginManager._dirty_lock", threading.Lock())
        self._health_baseline: dict = {}
        self._last_inventory = None
        self._inventory_published = True
        self._next_publish_retry = 0.0
        # jittered inventory-publish retry (was a flat 30 s re-arm): every
        # node in a cluster hits "apiserver unreachable at boot" together,
        # so the retries must decorrelate. Reset on success; surfaced via
        # status.py so operators can see publish-retry pressure.
        self.publish_backoff = BackoffPolicy(base_s=5.0, cap_s=60.0)
        self.plugins: List[TpuDevicePlugin] = []
        self.pending: List[TpuDevicePlugin] = []
        self.registry: Optional[Registry] = None
        self._sigs: dict = {}
        # drain: an administrative health source ANDed with the observed
        # ones; kubelet stops placing new VMIs while existing ones keep
        # their devices (the Device Plugin API cannot revoke grants)
        self.draining = False
        # set from signal handlers (plain assignment only — drain() itself
        # takes locks the interrupted main thread may hold); the run loop
        # applies it on the next tick
        self._drain_request: Optional[bool] = None
        # flight-recorder dump request (SIGHUP): flag-set only, like the
        # drain request — trace.dump() logs and writes a file, and doing
        # either from a signal handler can hit a reentrant-stream
        # RuntimeError if the interrupt lands mid-write on this thread
        self._dump_request = False
        self.running = threading.Event()  # run() loop is alive (liveness)
        # the probe implementation for this process, via the privilege
        # seam (broker.health_shim): the plain native shim in-process, a
        # BrokeredHealth forwarding config-space/node probes through the
        # broker IPC in spawn mode — the hub's probe closures cross the
        # boundary without knowing it
        self._shim = broker_mod.health_shim(cfg.native_lib_path)
        # The host-level shared health plane: ONE inotify fd, ONE existence
        # reconciler and ONE deduped deadline-bounded probe scheduler for
        # every plugin server (and the DRA driver's socket watch), however
        # many resources the host advertises. Started lazily on first
        # subscription; plugin rebuilds across rediscovery re-subscribe
        # against the same hub.
        self.health_hub = HealthHub(
            poll_interval_s=cfg.health_poll_s,
            probe_workers=cfg.health_probe_workers,
            probe_deadline_s=cfg.health_probe_deadline_s)
        # Per-device lifecycle FSM (lifecycle_fsm.py): present → bound →
        # allocated → detaching → gone → replugged. Driven by the hub's
        # fs events (the dedicated subscription below, fast path) and by
        # rediscovery's sysfs ground truth (_sync_lifecycle); the DRA
        # driver attaches its claim marks + orphan hook via
        # DraDriver.attach_lifecycle (cli.py).
        self.device_lifecycle = DeviceLifecycle(
            serial_reader=self._read_serial,
            # corroboration: a /dev/vfio node flap with the device still
            # enumerated in sysfs is a recoverable health event, not a
            # hot-unplug — only a missing sysfs dir declares `gone`.
            # Partition raw ids (uuids) have no PCI dir of their own:
            # their presence is their PARENT chip's (map maintained by
            # _sync_lifecycle), so an orderly vTPU reconfiguration is
            # never misreported as a surprise removal.
            presence_reader=self._device_present)
        # partition uuid -> parent BDF for the presence corroboration;
        # swapped wholesale (atomic assignment) by _sync_lifecycle
        self._lifecycle_parents: dict = {}
        self._lifecycle_sub: Optional[HubSubscription] = None
        # Boot telemetry (status.py /status + bench.py --restart): wall
        # times from start() entry, the snapshot-cache outcome, and the
        # two readiness edges of the warm boot's wave pipeline.
        # first_resource_ready_ms ≤ all_resources_ready_ms always; on the
        # cold path (or a fully-invalidated warm boot) they coincide.
        self.boot_stats: dict = {}
        # Queried once at startup: whether the host can dlopen libtpu.so.
        # Purely informational on a passthrough host (chips are vfio-bound,
        # the guest owns libtpu), but a useful deployment sanity signal.
        self.native_info = {
            "native_shim": self._shim.is_native,
            "libtpu_available": self._shim.libtpu_available(),
        }
        log.info("native health shim: loaded=%s libtpu_available=%s",
                 self.native_info["native_shim"],
                 self.native_info["libtpu_available"])

    def _observe_health(self, transitions) -> None:
        """Plugin-server health listener: record real transitions as dirty
        rescan hints, then forward to the external listener (if any)."""
        with self._dirty_lock:
            for dev_id, healthy in transitions.items():
                if self._health_baseline.get(dev_id) != healthy:
                    self._health_baseline[dev_id] = healthy
                    self._dirty.add(dev_id)
        if self._downstream_health_listener is not None:
            self._downstream_health_listener(transitions)

    def _seed_health_baseline(self, registry: Registry) -> None:
        """Plugins are (re)built all-Healthy: align the baseline so the
        first unconditional listener snapshot after a rebuild does not mark
        every unchanged device dirty; ids that left the inventory drop out."""
        ids = {d.bdf for devs in registry.devices_by_model.values()
               for d in devs}
        ids |= {p.uuid for ps in registry.partitions_by_type.values()
                for p in ps}
        with self._dirty_lock:
            self._health_baseline = {
                i: self._health_baseline.get(i, True) for i in ids}

    def _rediscover(self):
        """The run loop's discovery: dirty-set rescan through the
        HostSnapshot when enabled, the classic full walk otherwise."""
        if not self.cfg.incremental_rediscovery:
            return discover(self.cfg)
        if self.snapshot is None:
            self.snapshot = HostSnapshot(self.cfg)
        with self._dirty_lock:
            dirty, self._dirty = self._dirty, set()
        return self.snapshot.rescan(dirty=dirty)

    def discovery_stats(self) -> dict:
        """Snapshot scan counters for /status + /metrics."""
        out = {"incremental": self.cfg.incremental_rediscovery}
        if self.snapshot is not None:
            out.update(self.snapshot.stats)
        return out

    def build_plugins(self, inventory=None,
                      skip_keys=frozenset()) -> List[TpuDevicePlugin]:
        """Build plugin servers for the inventory, returning only those
        whose key is NOT in `skip_keys` (resources whose running plugin
        survives a rediscovery unchanged — they keep their device tables,
        AllocationIndex and planner; their already-written CDI specs are
        merely kept off the prune list). CDI publication and fact
        publication still cover the complete resource set."""
        registry, generations = inventory if inventory else discover(self.cfg)
        self.registry = registry
        if self.on_inventory is not None:
            self._last_inventory = (registry, generations)
            self._publish_inventory()
        plugins: List[TpuDevicePlugin] = []
        cdi_paths: List[str] = []
        for model, devs in sorted(registry.devices_by_model.items()):
            suffix = resource_name_for(model, generations, self.cfg.pci_ids_path)
            info = generations.get(model)
            if ("pt", suffix) in skip_keys:
                # unchanged signature: the running plugin survives with
                # zero table rebuilds, but its spec file is still
                # re-written (identical content, atomic replace) so
                # on-disk drift/corruption heals exactly as the old full
                # rebuild did
                if self.cfg.cdi_spec_dir:
                    from . import cdi
                    path = cdi.write_spec(
                        self.cfg, cdi.device_entries(self.cfg, devs),
                        suffix)
                    # a failed re-write must not let prune_specs delete the
                    # still-valid existing file the surviving plugin's CDI
                    # annotations reference
                    cdi_paths.append(path or cdi.spec_path(self.cfg, suffix))
                continue
            cdi_enabled = False
            if self.cfg.cdi_spec_dir:
                from . import cdi
                path = cdi.write_spec(
                    self.cfg, cdi.device_entries(self.cfg, devs), suffix)
                cdi_enabled = path is not None
                if path:
                    cdi_paths.append(path)
            plugins.append(TpuDevicePlugin(
                self.cfg, suffix, registry, devs,
                torus_dims=info.host_topology if info else None,
                health_shim=self._shim, cdi_enabled=cdi_enabled,
                health_listener=self.health_listener,
                health_hub=self.health_hub,
                lifecycle=self.device_lifecycle,
                policy=self.policy_engine,
                remediation=self.remediation_engine,
            ))
            log.info("plugin for %s: %d chips (model %s, torus %s)",
                     suffix, len(devs), model,
                     info.host_topology if info else None)
        # colliding partition types never reach here: discovery.discover is
        # the single authority that drops them (with the parent chips kept
        # as passthrough)
        for type_name, parts in sorted(registry.partitions_by_type.items()):
            if ("vtpu", type_name) in skip_keys:
                if self.cfg.cdi_spec_dir:
                    from . import cdi
                    path = cdi.write_spec(
                        self.cfg,
                        cdi.partition_entries(self.cfg, parts,
                                              registry.bdf_to_group),
                        f"vtpu-{type_name}")
                    cdi_paths.append(
                        path or cdi.spec_path(self.cfg, f"vtpu-{type_name}"))
                continue
            cdi_enabled = False
            cdi_uuids: frozenset = frozenset()
            if self.cfg.cdi_spec_dir:
                from . import cdi
                entries = cdi.partition_entries(
                    self.cfg, parts, registry.bdf_to_group)
                # spec files are namespaced like the vtpu socket so a type
                # named after a generation can never clobber the passthrough
                # resource's spec file
                path = cdi.write_spec(self.cfg, entries, f"vtpu-{type_name}")
                cdi_enabled = path is not None
                if path:
                    cdi_paths.append(path)
                    cdi_uuids = frozenset(e["name"] for e in entries)
            plugins.append(VtpuDevicePlugin(
                self.cfg, type_name, registry, parts, health_shim=self._shim,
                cdi_enabled=cdi_enabled, cdi_uuids=cdi_uuids,
                health_listener=self.health_listener,
                health_hub=self.health_hub,
                lifecycle=self.device_lifecycle,
                policy=self.policy_engine,
                remediation=self.remediation_engine))
            log.info("vTPU plugin for %s: %d partitions", type_name, len(parts))
        if self.cfg.cdi_spec_dir:
            from . import cdi
            cdi.prune_specs(self.cfg, cdi_paths)
        return plugins

    def _publish_inventory(self) -> None:
        registry, generations = self._last_inventory
        try:
            ok = self.on_inventory(registry, generations)
        except Exception as exc:
            log.error("inventory callback failed: %s", exc)
            ok = False
        self._inventory_published = ok is not False
        if self._inventory_published:
            self.publish_backoff.reset()
        else:
            self._next_publish_retry = (
                time.monotonic() + self.publish_backoff.next_delay())

    @staticmethod
    def _plugin_key(plugin) -> tuple:
        kind = "vtpu" if isinstance(plugin, VtpuDevicePlugin) else "pt"
        return (kind, plugin.resource_suffix)

    def _signatures(self, registry: Registry, generations) -> dict:
        """Per-resource identity: a plugin only needs a restart when ITS
        devices/partitions changed — including the FULL membership of every
        IOMMU group it allocates (a chip of another model joining/leaving a
        shared group changes this plugin's group expansion, so it must not
        survive on a stale registry)."""
        def group_members(groups):
            return tuple(sorted(
                (g, tuple(d.bdf for d in registry.iommu_map.get(g, ())))
                for g in groups if g is not None))

        sigs = {}
        for model, devs in registry.devices_by_model.items():
            suffix = resource_name_for(model, generations, self.cfg.pci_ids_path)
            sigs[("pt", suffix)] = (
                devs, group_members({d.iommu_group for d in devs}))
        for type_name, parts in registry.partitions_by_type.items():
            parent_groups = tuple(sorted(
                {(p.parent_bdf, registry.bdf_to_group.get(p.parent_bdf))
                 for p in parts}))
            sigs[("vtpu", type_name)] = (
                parts, parent_groups,
                group_members({g for _, g in parent_groups}))
        return sigs

    def _sync_lifecycle(self, registry: Registry) -> None:
        """Feed the lifecycle FSM the sysfs ground truth and re-point its
        hub fast path at the current inventory.

        The sync admits new devices, marks departures GONE (orphaning any
        attached claims), and runs replug identity reconciliation for
        returners; the dedicated hub subscription then delivers per-BDF
        vfio-node events between rediscovery ticks so a surprise removal
        is observed at inotify latency, not at the rediscovery interval.
        """
        fsm = self.device_lifecycle
        present = {}
        for devs in registry.devices_by_model.values():
            for d in devs:
                # LAZY identity read: only admission and replug
                # reconciliation compare serials, so a warm rediscovery
                # tick adds zero sysfs reads here (the incremental-
                # discovery read-count guards pin per-tick cost)
                present[d.bdf] = (
                    self._read_serial(d.bdf)
                    if fsm.needs_identity(d.bdf) else None)
        parents = {}
        for parts in registry.partitions_by_type.values():
            for p in parts:
                present[p.uuid] = None   # partitions: uuid IS the identity
                parents[p.uuid] = p.parent_bdf
        self._lifecycle_parents = parents   # atomic swap; reader copies
        self.device_lifecycle.sync_inventory(present)
        paths = {d.bdf: self.cfg.dev_path("dev/vfio", d.iommu_group)
                 for devs in registry.devices_by_model.values()
                 for d in devs}
        if self._lifecycle_sub is not None \
                and self._lifecycle_sub.group_paths == paths:
            return   # watch set unchanged: no subscription churn per tick
        sub = HubSubscription(name="lifecycle", group_paths=paths,
                              on_device_health=self._lifecycle_fs_event)
        old, self._lifecycle_sub = self._lifecycle_sub, sub
        if old is not None:
            self.health_hub.unsubscribe(old)
        self.health_hub.subscribe(sub)

    def _read_serial(self, bdf: str) -> Optional[str]:
        """Device identity read, routed through the snapshot's serial
        cache when one exists: a snapshot-warm boot restores every serial
        from the persisted cache, so replug/admission identity checks add
        zero counted sysfs reads. No snapshot (--full-rescan) keeps the
        classic per-read fallback chain."""
        snap = self.snapshot
        if snap is not None:
            return snap.serial_of(bdf)
        return read_serial(self.cfg.pci_base_path, bdf)

    def _device_present(self, raw: str) -> bool:
        """Sysfs presence for the lifecycle corroboration: chips by their
        own PCI dir; partitions by their parent chip's (a partition
        'hot-unplugs' exactly when its parent silicon does)."""
        target = self._lifecycle_parents.get(raw, raw)
        return os.path.isdir(os.path.join(self.cfg.pci_base_path, target))

    def _lifecycle_fs_event(self, key: str, healthy: bool,
                            source: str) -> None:
        # only the fs watcher's presence evidence drives the FSM here; a
        # probe verdict is a health signal, not a removal
        if source == "fs":
            self.device_lifecycle.note_fs_event(key, healthy)

    def lifecycle_stats(self) -> dict:
        """FSM counters for /status + /metrics (lock-free read side)."""
        return self.device_lifecycle.stats()

    def start(self, inventory=None) -> None:
        """Boot to ready.

        With no explicit inventory, the restart fast path tries the
        persisted discovery snapshot first: load, revalidate by one
        batched stat pass, then start in two waves — wave 1 registers
        every resource whose devices all validated straight from the
        cache (first-resource-ready), wave 2 cold-reads only the
        invalidated devices and converges the affected resources
        (all-resources-ready). A missing/corrupt/version-refused cache is
        never trusted: boot degrades to the classic counted cold walk.
        """
        t0 = time.monotonic()
        self.boot_stats = {
            "boot_path": "cold",
            "snapshot_outcome": None,
            "invalidated": 0,
            "first_resource_ready_ms": None,
            "all_resources_ready_ms": None,
            "restart_ready_ms": None,
        }
        with trace.span("boot.total", histogram="tdp_restart_ready_ms"):
            if inventory is not None:
                self._start_with(inventory, t0)
            elif not self._start_warm(t0):
                # first cold boot (or untrusted cache): the one full walk;
                # subsequent timer ticks go through the dirty-set path
                self._start_with(self._rediscover(), t0)
        self.boot_stats["restart_ready_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3)
        if self.boot_stats["first_resource_ready_ms"] is None:
            self.boot_stats["first_resource_ready_ms"] = \
                self.boot_stats["all_resources_ready_ms"]
        if self.boot_stats["boot_path"] == "snapshot" \
                and not self.boot_stats["invalidated"]:
            # clean warm boot: the on-disk cache just validated against
            # sysfs unchanged — re-serializing thousands of records would
            # only delay run-loop entry (a wave-2 boot re-saves through
            # _apply_inventory; a cold boot seeds the cache below)
            return
        self._save_snapshot_cache()

    def _boot_inventory(self, inventory, **register_attrs) -> None:
        """Boot body on a complete inventory, with the two independent
        stages overlapped: the FSM inventory sync (admissions, hub watch
        re-point — pure bookkeeping behind the FSM lock) runs alongside
        plugin table construction, and the pipeline JOINS before
        registration so the kubelet never sees a resource whose
        lifecycle truth is still syncing."""
        self._sigs = self._signatures(*inventory)
        self._seed_health_baseline(inventory[0])
        with futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="boot-fsm-sync") as pool:
            sync = pool.submit(self._sync_lifecycle, inventory[0])
            self.plugins = self.build_plugins(inventory)
        sync.result()   # the pool exit joined; surface any sync error
        self.pending = list(self.plugins)
        with trace.span("boot.register", resources=len(self.plugins),
                        **register_attrs):
            self._try_start_pending()

    def _start_with(self, inventory, t0: float) -> None:
        """Classic single-wave boot body on a complete inventory."""
        self._boot_inventory(inventory)
        self.boot_stats["all_resources_ready_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3)

    def _start_warm(self, t0: float) -> bool:
        """Snapshot-cache fast path. Returns False when the cache cannot
        be trusted (disabled, missing, corrupt, version-refused, armed
        fault) — the caller then pays the counted cold walk; stale data
        never reaches a plugin table."""
        path = self.cfg.discovery_snapshot_path
        if not path or not self.cfg.incremental_rediscovery:
            return False
        if self.snapshot is None:
            self.snapshot = HostSnapshot(self.cfg)
        with trace.span("boot.snapshot.load"):
            outcome = self.snapshot.load_cache(path)
        self.boot_stats["snapshot_outcome"] = outcome
        if outcome != "loaded":
            return False
        with trace.span("boot.revalidate"):
            invalidated = self.snapshot.revalidate()
            # resource-level trust: one invalidated chip taints every
            # sibling of its model (the resource's device table and IOMMU
            # group expansion are built jointly), so wave 1 only ships
            # resources whose FULL membership validated
            tainted = self.snapshot.taint_groups(invalidated)
        self.boot_stats["boot_path"] = "snapshot"
        self.boot_stats["invalidated"] = len(invalidated)
        inventory = self.snapshot.build_excluding(tainted)
        self._boot_inventory(inventory, wave=1)
        if self.plugins:
            self.boot_stats["first_resource_ready_ms"] = round(
                (time.monotonic() - t0) * 1e3, 3)
        if tainted:
            # wave 2: only the invalidated devices pay cold sysfs reads;
            # the signature diff restarts exactly the resources they
            # belong to while wave-1 survivors keep serving
            with self._dirty_lock:
                dirty = self._dirty | set(tainted)
                self._dirty = set()
            with trace.span("boot.register", wave=2):
                self._apply_inventory(self.snapshot.rescan(dirty=dirty))
        self.boot_stats["all_resources_ready_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3)
        return True

    def _save_snapshot_cache(self) -> None:
        """Persist the snapshot beside the DRA checkpoint (atomic
        temp+rename inside save_cache); a write failure only costs the
        NEXT boot its warm path, so it is logged there and absorbed."""
        path = self.cfg.discovery_snapshot_path
        if path and self.snapshot is not None:
            self.snapshot.save_cache(path)

    def _apply_inventory(self, inventory) -> None:
        """Incremental rediscovery: restart only resources whose signature
        changed; unchanged plugins keep serving without an advertisement
        blip (their registry snapshot stays valid for their own devices —
        the whole-set restart the naive approach does would zero every
        resource's allocatable count on any hotplug)."""
        registry, generations = inventory
        new_sigs = self._signatures(registry, generations)
        self._seed_health_baseline(registry)
        # the FSM sees every rediscovery outcome, signature change or not:
        # an unchanged inventory still drains classic-path allocation
        # marks and reconciles GONE records whose device returned
        self._sync_lifecycle(registry)
        if new_sigs == self._sigs:
            return
        trace.event("lifecycle.inventory_changed",
                    resources=len(new_sigs))
        # only a RUNNING plugin may survive on an unchanged signature; a
        # pending one is torn down and rebuilt fresh so it is never lost
        running_keys = {self._plugin_key(p) for p in self.plugins
                        if p not in self.pending}
        unchanged = {k for k, v in new_sigs.items()
                     if self._sigs.get(k) == v and k in running_keys}
        changed_keys = (set(new_sigs) | set(self._sigs)) - unchanged
        log.info("host inventory changed; restarting %s",
                 ", ".join("/".join(k) for k in sorted(changed_keys)))
        survivors: List[TpuDevicePlugin] = []
        casualties: List[TpuDevicePlugin] = list(self.pending)
        for plugin in self.plugins:
            if plugin in self.pending:
                continue  # already a casualty; rebuilt below if still present
            if self._plugin_key(plugin) in unchanged:
                survivors.append(plugin)
            else:
                casualties.append(plugin)
        for plugin in casualties:
            try:
                plugin.stop()
            except Exception as exc:
                log.error("plugin %s failed to stop cleanly: %s",
                          plugin.resource_name, exc)
        # CDI prune bookkeeping and fact publication cover the complete
        # resource set, but ONLY changed keys get their tables rebuilt —
        # an unchanged resource costs zero plugin/index construction
        fresh = self.build_plugins(inventory, skip_keys=unchanged)
        self.plugins = survivors + fresh
        self.pending = list(fresh)
        self._try_start_pending()
        self._sigs = new_sigs
        # the inventory changed: refresh the persisted snapshot so the
        # next restart's warm path revalidates against current truth
        self._save_snapshot_cache()

    def _start_one(self, plugin) -> None:
        if self.draining:
            # BEFORE start(): the kubelet must never see an initial
            # Healthy snapshot from a plugin born during a drain
            plugin.set_all_health(False, "drain")
        plugin.start()

    def _try_start_pending(self) -> None:
        """Start plugins that are not serving yet; keep failures for retry.

        At node boot the plugin pod regularly comes up before the kubelet's
        socket exists — registration then fails and must be retried, not
        abandoned (one bad plugin must also not sink the rest).

        Starts run CONCURRENTLY: each start() pays a self-dial readiness
        wait plus a kubelet Register round-trip, so the old serial loop made
        many-resource cold starts O(resources) in those latencies. Plugins
        are independent servers on independent sockets — overlapping them
        collapses cold start to ~the slowest single registration.
        """
        pending = self.pending
        if not pending:
            return
        still_pending: List[TpuDevicePlugin] = []
        t0 = time.monotonic()
        with futures.ThreadPoolExecutor(
                max_workers=min(START_WORKERS, len(pending)),
                thread_name_prefix="plugin-start") as pool:
            outcomes = [(plugin, pool.submit(self._start_one, plugin))
                        for plugin in pending]
            for plugin, fut in outcomes:
                try:
                    fut.result()
                except KubeletUnavailable as exc:
                    # the expected boot race: the pod came up before the
                    # kubelet's socket — routine, not an error
                    log.info("plugin %s: kubelet not ready (%s); will retry",
                             plugin.resource_name, exc)
                    still_pending.append(plugin)
                except RegistrationRejected as exc:
                    # the kubelet answered and said no (version mismatch, bad
                    # resource name): retrying without a fix is futile — make
                    # the log say what actually needs fixing
                    log.error("plugin %s: kubelet REJECTED registration (%s); "
                              "will retry, but this needs operator attention",
                              plugin.resource_name, exc)
                    still_pending.append(plugin)
                except Exception as exc:
                    log.error("plugin %s failed to start (%s); will retry",
                              plugin.resource_name, exc)
                    still_pending.append(plugin)
        started = len(pending) - len(still_pending)
        if started:
            log.info("started %d plugin(s) concurrently in %.2fs "
                     "(%d still pending)", started,
                     time.monotonic() - t0, len(still_pending))
        self.pending = still_pending

    def request_drain(self, draining: bool) -> None:
        """Async-signal-safe drain request: just records the wish; the run
        loop performs the actual (lock-taking) drain on its next tick."""
        self._drain_request = draining

    def request_flight_dump(self) -> None:
        """Async-signal-safe flight-recorder dump request (SIGHUP); the
        run loop performs the actual dump (logging + file I/O) on its
        next tick, within ~1s."""
        self._dump_request = True

    def drain(self, draining: bool) -> None:
        """Administratively mark every device (un)healthy for maintenance.

        The reference has no drain story; here SIGUSR1/SIGUSR2 (cli.py)
        toggle it at runtime. Implemented as one more ANDed health source,
        so undraining never masks a genuinely dead chip."""
        self.draining = draining
        log.warning("node %s", "DRAINING: all devices -> Unhealthy"
                    if draining else "undrained: device health restored")
        for plugin in self.plugins:
            plugin.set_all_health(not draining, "drain")

    def health_stats(self) -> dict:
        """Shared-health-plane counters for /status + /metrics."""
        return self.health_hub.stats()

    def stop(self) -> None:
        for plugin in self.plugins:
            try:
                plugin.stop()
            except Exception as exc:
                log.error("plugin %s failed to stop cleanly: %s",
                          plugin.resource_name, exc)
        self.plugins = []
        self.pending = []
        if self._lifecycle_sub is not None:
            self.health_hub.unsubscribe(self._lifecycle_sub)
            self._lifecycle_sub = None
        self.health_hub.stop()

    def run(self, stop_event: threading.Event) -> None:
        """Start everything and block until `stop_event` (reference :166-175).

        Pending-plugin start retries run on their own short cadence: a plugin
        that raced the kubelet socket at boot must not wait out a long
        rediscovery interval before registering.
        """
        self.running.set()
        self.start()
        interval = self.cfg.rediscovery_interval_s
        next_rediscovery = time.monotonic() + interval if interval > 0 else None
        try:
            while True:
                tick = interval if interval > 0 else 1.0
                if self.pending:
                    tick = min(tick, 2.0)
                # sleep in ≤1s slices so a signal-set drain request (which
                # cannot wake an Event the handler's own thread is waiting
                # on) is applied within ~1s even under long rediscovery
                # intervals
                stopped = False
                waited = 0.0
                while waited < tick:
                    step_s = min(1.0, tick - waited)
                    if stop_event.wait(timeout=step_s):
                        stopped = True
                        break
                    waited += step_s
                    if self._drain_request is not None \
                            and self._drain_request != self.draining:
                        break
                    if self._dump_request:
                        break   # dump within ~1s, not a rediscovery tick
                if stopped:
                    break
                if self.pending:
                    self._try_start_pending()
                if self._drain_request is not None \
                        and self._drain_request != self.draining:
                    self.drain(self._drain_request)
                if self._dump_request:
                    self._dump_request = False
                    trace.dump("SIGHUP")
                if self.on_inventory is not None \
                        and not self._inventory_published \
                        and self._last_inventory is not None \
                        and time.monotonic() >= self._next_publish_retry:
                    log.info("retrying node fact publication")
                    self._publish_inventory()
                if next_rediscovery is not None \
                        and time.monotonic() >= next_rediscovery:
                    next_rediscovery = time.monotonic() + interval
                    self._apply_inventory(self._rediscover())
        finally:
            self.running.clear()
            self.stop()
