"""Discovery over fake sysfs trees (reference: device_plugin_test.go:279-323)."""

import json
import os

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin.config import Config
from tpu_device_plugin import discovery


def make_cfg(host, **overrides):
    cfg = Config().with_root(host.root)
    if overrides:
        from dataclasses import replace
        cfg = replace(cfg, **overrides)
    return cfg


def test_helpers(tmp_path):
    p = tmp_path / "vendor"
    p.write_text("0x1ae0\n")
    assert discovery.read_id_from_file(str(p)) == "1ae0"
    p.write_text("1ae0\n")  # fixture without 0x prefix still parses
    assert discovery.read_id_from_file(str(p)) == "1ae0"
    assert discovery.read_id_from_file(str(tmp_path / "missing")) is None

    n = tmp_path / "numa_node"
    n.write_text("-1\n")
    assert discovery.read_numa_node(str(n)) == 0  # negative clamps to 0
    n.write_text("1\n")
    assert discovery.read_numa_node(str(n)) == 1
    assert discovery.read_numa_node(str(tmp_path / "missing")) == 0

    target = tmp_path / "tgt"
    target.mkdir()
    link = tmp_path / "lnk"
    os.symlink(str(target), str(link))
    assert discovery.read_link_basename(str(link)) == "tgt"
    assert discovery.read_link_basename(str(tmp_path / "none")) is None


def test_passthrough_discovery_filters(tmp_path):
    host = FakeHost(tmp_path)
    # 4 valid v4 chips in 2 iommu groups across 2 numa nodes
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:06.0", iommu_group="12", numa_node=1))
    host.add_chip(FakeChip("0000:00:07.0", iommu_group="12", numa_node=1))
    # filtered out: wrong vendor, wrong driver, no driver
    host.add_chip(FakeChip("0000:00:08.0", vendor="0x10de", iommu_group="13"))
    host.add_chip(FakeChip("0000:00:09.0", driver="gvnic", iommu_group="14"))
    host.add_chip(FakeChip("0000:00:0a.0", driver=None, iommu_group="15"))

    registry, generations = discovery.discover_passthrough(make_cfg(host))

    devs = registry.devices_by_model["0062"]
    assert len(devs) == 4
    assert set(registry.bdf_to_group) == {
        "0000:00:04.0", "0000:00:05.0", "0000:00:06.0", "0000:00:07.0"}
    assert registry.bdf_to_group["0000:00:04.0"] == "11"
    assert len(registry.iommu_map["12"]) == 2
    # v4 chips picked up 2x2x1 torus coords in BDF order
    by_bdf = {d.bdf: d for d in devs}
    assert by_bdf["0000:00:04.0"].ici_coords == (0, 0, 0)
    assert by_bdf["0000:00:07.0"].ici_coords == (1, 1, 0)
    assert by_bdf["0000:00:06.0"].numa_node == 1
    assert generations["0062"].name == "v4"


def test_accel_correlation_and_hints(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", accel_index=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12", accel_index=1))
    hints = tmp_path / "topo.json"
    hints.write_text(json.dumps({"0000:00:05.0": [1, 1, 0]}))
    cfg = make_cfg(host, topology_hints_path=str(hints))
    registry, _ = discovery.discover_passthrough(cfg)
    by_bdf = {d.bdf: d for d in registry.devices_by_model["0062"]}
    assert by_bdf["0000:00:04.0"].accel_index == 0
    assert by_bdf["0000:00:05.0"].accel_index == 1
    assert by_bdf["0000:00:05.0"].ici_coords == (1, 1, 0)
    assert by_bdf["0000:00:04.0"].ici_coords == (0, 0, 0)


def test_mdev_partition_discovery(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=1))
    host.add_mdev("uuid-1", "TPU v4 half chip", "0000:00:04.0")
    host.add_mdev("uuid-2", "TPU v4 half chip", "0000:00:04.0")
    registry, _ = discovery.discover(make_cfg(host))
    parts = registry.partitions_by_type["TPU_v4_half_chip"]
    assert {p.uuid for p in parts} == {"uuid-1", "uuid-2"}
    assert parts[0].parent_bdf == "0000:00:04.0"
    assert parts[0].numa_node == 1
    assert parts[0].provider == "mdev"
    assert registry.parent_to_partitions["0000:00:04.0"] == ("uuid-1", "uuid-2")


def test_logical_partition_per_core(tmp_path):
    host = FakeHost(tmp_path)
    # accel-owned chip (not vfio): driver is the accel driver
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"per_core": True}))
    cfg = make_cfg(host, partition_config_path=str(pc))
    registry, _ = discovery.discover(cfg)
    parts = registry.partitions_by_type["v4-core"]
    assert {p.uuid for p in parts} == {"0000:00:04.0-core0", "0000:00:04.0-core1"}
    assert all(p.provider == "logical" and p.accel_index == 0 for p in parts)
    # the vfio passthrough map must NOT include the accel-owned chip
    assert registry.bdf_to_group == {}


def test_explicit_logical_partitions(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=2))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "v4 shared", "parent_bdf": "0000:00:04.0"},
        {"uuid": "bad"},  # missing keys -> skipped
    ]}))
    registry, _ = discovery.discover(make_cfg(host, partition_config_path=str(pc)))
    parts = registry.partitions_by_type["v4_shared"]
    assert parts[0].uuid == "p0"
    assert parts[0].accel_index == 2
    assert len(registry.partitions_by_type) == 1


def test_empty_host(tmp_path):
    host = FakeHost(tmp_path)
    registry, _ = discovery.discover(make_cfg(host))
    assert registry.all_devices() == []
    assert registry.partitions_by_type == {}


def test_per_core_skips_foreign_accel_vendor(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", vendor="0x8086",
                           driver="intel_vpu", accel_index=0))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"per_core": True}))
    registry, _ = discovery.discover(make_cfg(host, partition_config_path=str(pc)))
    assert registry.partitions_by_type == {}


def test_non_dict_config_files_tolerated(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    gm = tmp_path / "gens.json"
    gm.write_text("[1, 2]")
    pc = tmp_path / "parts.json"
    pc.write_text("[]")
    cfg = make_cfg(host, generation_map_path=str(gm),
                   partition_config_path=str(pc))
    registry, generations = discovery.discover(cfg)
    assert len(registry.all_devices()) == 1   # discovery survives bad configs
    assert generations["0062"].name == "v4"   # built-ins retained


def test_logical_partition_parent_excluded_from_passthrough(tmp_path):
    """A vfio-bound chip backing logical partitions must not also be
    advertised as a passthrough resource — the kubelet would otherwise grant
    the same VFIO group to two VMIs."""
    import json
    from dataclasses import replace
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12"))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "vslice", "parent_bdf": "0000:00:04.0"}]}))
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    registry, _ = discovery.discover(cfg)
    # chip 04 is consumed by the vTPU resource; only chip 05 stays passthrough
    assert [d.bdf for d in registry.devices_by_model["0062"]] == ["0000:00:05.0"]
    # lookup maps stay intact: the vTPU plugin resolves the parent through them
    assert registry.bdf_to_group["0000:00:04.0"] == "11"
    assert [p.uuid for p in registry.partitions_by_type["vslice"]] == ["p0"]


def test_colliding_partition_type_dropped_keeps_passthrough(tmp_path):
    """A partition type named after a passthrough suffix is refused at
    discovery so the parent chip stays schedulable as passthrough (rather
    than being consumed by a vTPU plugin that can never register)."""
    import json
    from dataclasses import replace
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "v4", "parent_bdf": "0000:00:04.0"}]}))
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    registry, _ = discovery.discover(cfg)
    assert "v4" not in registry.partitions_by_type
    assert [d.bdf for d in registry.devices_by_model["0062"]] == ["0000:00:04.0"]


def test_vfio_driver_variants_accepted(tmp_path):
    """The vendor-variant driver name works OUT OF THE BOX (reference accepts
    nvgrace_gpu_vfio_pci alongside vfio-pci by default, device_plugin.go:75-78);
    further variants come via the --vfio-drivers CLI flag."""
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="tpu_vfio_pci"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12",
                           driver="future_tpu_vfio"))
    # default config: built-in variant discovered, unknown driver is not
    registry, _ = discovery.discover_passthrough(make_cfg(host))
    assert [d.bdf for d in registry.devices_by_model["0062"]] == ["0000:00:04.0"]
    # extra variant configured -> both discovered
    cfg = make_cfg(host, vfio_drivers=("vfio-pci", "tpu_vfio_pci",
                                       "future_tpu_vfio"))
    registry, _ = discovery.discover_passthrough(cfg)
    assert [d.bdf for d in registry.devices_by_model["0062"]] == [
        "0000:00:04.0", "0000:00:05.0"]
    # CLI flag parses into the tuple
    from tpu_device_plugin.cli import build_config
    parsed, _ = build_config(["--vfio-drivers", "vfio-pci, future_tpu_vfio"])
    assert parsed.vfio_drivers == ("vfio-pci", "future_tpu_vfio")


def test_vfio_parent_backs_at_most_one_partition(tmp_path):
    """A VFIO group attaches to one VM at a time: extra logical partitions
    on a vfio-bound parent are dropped so advertised capacity is usable."""
    import json
    from dataclasses import replace
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "vslice", "parent_bdf": "0000:00:04.0"},
        {"uuid": "p1", "type": "vslice", "parent_bdf": "0000:00:04.0"}]}))
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    registry, _ = discovery.discover(cfg)
    assert [p.uuid for p in registry.partitions_by_type["vslice"]] == ["p0"]


def test_group_mate_of_consumed_parent_excluded_from_passthrough(tmp_path):
    """Passthrough exclusion is by IOMMU group: a kept chip sharing a group
    with a consumed partition parent would group-expand in plan_allocation
    and mount the same /dev/vfio/<group> the vTPU plugin hands out — the
    kubelet could then grant one VFIO group to two VMIs."""
    import json
    from dataclasses import replace
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="11"))  # group mate
    host.add_chip(FakeChip("0000:00:06.0", iommu_group="12"))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "vslice", "parent_bdf": "0000:00:04.0"}]}))
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    registry, _ = discovery.discover(cfg)
    # 04 is consumed AND its group mate 05 must go with it; 06 survives
    assert [d.bdf for d in registry.devices_by_model["0062"]] == ["0000:00:06.0"]
    assert [p.uuid for p in registry.partitions_by_type["vslice"]] == ["p0"]


def test_shared_group_partitions_deduped_across_parents(tmp_path):
    """VFIO exclusivity is per IOMMU group, not per parent chip: two logical
    partitions on different parents that share one group still collide in
    VFIO_GROUP_SET_CONTAINER, so only the first is advertised."""
    import json
    from dataclasses import replace
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="11"))  # same group
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "vslice", "parent_bdf": "0000:00:04.0"},
        {"uuid": "p1", "type": "vslice", "parent_bdf": "0000:00:05.0"}]}))
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    registry, _ = discovery.discover(cfg)
    assert [p.uuid for p in registry.partitions_by_type["vslice"]] == ["p0"]


def test_max_partitions_per_chip_caps_accel_backed(tmp_path):
    """--max-partitions-per-chip bounds the blast radius of unisolated
    accel-node sharing regardless of what the partition config declares;
    mdev partitions (kernel-mediated) are not capped."""
    import json
    from dataclasses import replace
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12", numa_node=0))
    host.add_mdev("m0", "TPU vhalf", "0000:00:05.0")
    host.add_mdev("m1", "TPU vhalf", "0000:00:05.0")
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"per_core": True}))
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc),
                  max_partitions_per_chip=1)
    registry, _ = discovery.discover(cfg)
    # per_core would advertise cores_per_chip=2; the cap keeps only core0
    assert [p.uuid for p in registry.partitions_by_type["v4-core"]] == \
        ["0000:00:04.0-core0"]
    # mdev partitions are untouched by the cap
    assert len(registry.partitions_by_type["TPU_vhalf"]) == 2
    # cap=0 (default) leaves everything advertised
    cfg0 = replace(cfg, max_partitions_per_chip=0)
    registry0, _ = discovery.discover(cfg0)
    assert len(registry0.partitions_by_type["v4-core"]) == 2
    # CLI flags parse into Config
    from tpu_device_plugin.cli import build_config
    parsed, _ = build_config(["--max-partitions-per-chip", "3",
                              "--partition-node-permissions", "r"])
    assert parsed.max_partitions_per_chip == 3
    assert parsed.partition_node_permissions == "r"


def test_accel_parent_still_backs_many_partitions(tmp_path):
    """Accel-driver chips multiplex: per-core partitions all survive."""
    import json
    from dataclasses import replace
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"per_core": True}))
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    registry, _ = discovery.discover(cfg)
    assert len(registry.partitions_by_type["v4-core"]) == 2  # cores_per_chip


def test_explicit_device_plugin_path_wins_over_root():
    """The kind e2e mixes fixture sysfs (--root) with the REAL kubelet
    socket dir: an explicit --device-plugin-path must survive re-rooting."""
    from tpu_device_plugin.cli import build_config
    parsed, _ = build_config(["--root", "/fixture",
                              "--device-plugin-path",
                              "/var/lib/kubelet/device-plugins"])
    assert parsed.device_plugin_path == "/var/lib/kubelet/device-plugins"
    assert parsed.kubelet_socket == \
        "/var/lib/kubelet/device-plugins/kubelet.sock"
    assert parsed.pci_base_path == "/fixture/sys/bus/pci/devices"

    parsed2, _ = build_config(["--root", "/fixture"])
    assert parsed2.device_plugin_path == "/fixture/device-plugins/"


# ------------------------------------------------- HostSnapshot (dirty-set)


def test_snapshot_full_scan_matches_discover(tmp_path):
    """First rescan() is the full walk and must equal discover() exactly —
    same devices, coords, partitions, group maps."""
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12", numa_node=1))
    host.add_mdev("uuid-1", "TPU vhalf", "0000:00:04.0", iommu_group="21")
    cfg = make_cfg(host)
    snap = discovery.HostSnapshot(cfg)
    reg_a, gens_a = snap.rescan()
    reg_b, gens_b = discovery.discover(cfg)
    assert reg_a == reg_b
    assert gens_a.keys() == gens_b.keys()
    assert snap.stats["full_scans"] == 1


def test_snapshot_warm_rescan_reads_no_unchanged_device(tmp_path):
    """A change-free warm rescan costs only the class listdirs — zero
    per-device reads — and returns the identical cached registry object."""
    host = FakeHost(tmp_path)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i)))
    snap = discovery.HostSnapshot(make_cfg(host))
    reg1, _ = snap.rescan()
    with discovery.count_reads() as w:
        reg2, _ = snap.rescan()
    assert reg2 is reg1                      # cached: nothing changed
    assert not [p for p in w.paths if "/devices/0000:" in p]
    assert snap.stats["dirty_rescans"] == 1


def test_snapshot_sees_hotplug_and_remove_via_listdir_diff(tmp_path):
    import shutil
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    snap = discovery.HostSnapshot(make_cfg(host))
    snap.rescan()
    host.add_chip(FakeChip("0000:00:05.0", device_id="0063",
                           iommu_group="12"))
    registry, _ = snap.rescan()               # no dirty hint needed
    assert [d.bdf for d in registry.devices_by_model["0063"]] == \
        ["0000:00:05.0"]
    shutil.rmtree(os.path.join(host.pci, "0000:00:04.0"))
    registry, _ = snap.rescan()
    assert "0062" not in registry.devices_by_model
    assert [d.bdf for d in registry.devices_by_model["0063"]] == \
        ["0000:00:05.0"]


def test_snapshot_dirty_hint_rereads_rebound_driver(tmp_path):
    """A driver rebind changes no listing — only a dirty hint (or full
    rescan) makes the snapshot see it; an unhinted warm rescan must NOT."""
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    snap = discovery.HostSnapshot(make_cfg(host))
    registry, _ = snap.rescan()
    assert len(registry.all_devices()) == 1
    # rebind: vfio-pci -> gvnic (symlink swap, listing unchanged)
    link = os.path.join(host.pci, "0000:00:04.0", "driver")
    os.unlink(link)
    os.symlink(os.path.join(host.drivers, "gvnic"), link)
    registry, _ = snap.rescan()
    assert len(registry.all_devices()) == 1   # cache: documented blindness
    registry, _ = snap.rescan(dirty={"0000:00:04.0"})
    assert registry.all_devices() == []       # dirty re-read saw the rebind
    registry, _ = snap.rescan(full=True)
    assert registry.all_devices() == []
    assert snap.stats["full_scans"] == 2


def test_snapshot_mdev_add_remove_and_dirty(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=1))
    snap = discovery.HostSnapshot(make_cfg(host))
    snap.rescan()
    host.add_mdev("uuid-1", "TPU vhalf", "0000:00:04.0", iommu_group="21")
    registry, _ = snap.rescan()
    parts = registry.partitions_by_type["TPU_vhalf"]
    assert [p.uuid for p in parts] == ["uuid-1"]
    assert parts[0].numa_node == 1            # served from the chip cache
    os.unlink(os.path.join(host.mdev, "uuid-1"))
    registry, _ = snap.rescan()
    assert registry.partitions_by_type == {}


def test_snapshot_partition_spec_mtime_triggers_reload(tmp_path):
    import json as json_mod
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    pc = tmp_path / "partitions.json"
    pc.write_text(json_mod.dumps({}))
    cfg = make_cfg(host, partition_config_path=str(pc))
    snap = discovery.HostSnapshot(cfg)
    registry, _ = snap.rescan()
    assert registry.partitions_by_type == {}
    pc.write_text(json_mod.dumps({"per_core": True}))
    os.utime(pc, ns=(1, 10**15))              # force a visible mtime move
    registry, _ = snap.rescan()
    assert len(registry.partitions_by_type["v4-core"]) == 2
    # logical partition synthesis on the warm path reads no chip files
    with discovery.count_reads() as w:
        snap.rescan()
    assert not [p for p in w.paths if "/devices/0000:" in p]


def test_registry_device_lookup_paths():
    """Registry.device(): hit, group-mismatch miss, and unknown-BDF miss."""
    from tpu_device_plugin.registry import Registry, TpuDevice
    d = TpuDevice(bdf="0000:00:04.0", device_id="0063", iommu_group="11",
                  numa_node=0)
    other = TpuDevice(bdf="0000:00:05.0", device_id="0063", iommu_group="11",
                      numa_node=0)
    reg = Registry(devices_by_model={"0063": (d, other)},
                   iommu_map={"11": (d, other)},
                   bdf_to_group={"0000:00:04.0": "11",
                                 "0000:00:05.0": "11",
                                 "0000:00:06.0": "99"})
    assert reg.device("0000:00:04.0") is d
    assert reg.device("0000:00:07.0") is None        # unknown bdf
    assert reg.device("0000:00:06.0") is None        # group has no entry
    assert {x.bdf for x in reg.all_devices()} == {d.bdf, other.bdf}


def test_logical_partition_flap_dirties_parent_chip(tmp_path):
    """A vtpu health flap carries the partition uuid ("<bdf>-coreN"), not
    the parent BDF: the dirty path must translate it so the parent chip's
    record is re-read (otherwise the dirty mechanism is inert on
    logical-partition hosts)."""
    import json as json_mod
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    pc = tmp_path / "partitions.json"
    pc.write_text(json_mod.dumps({"per_core": True}))
    snap = discovery.HostSnapshot(
        make_cfg(host, partition_config_path=str(pc)))
    registry, _ = snap.rescan()
    uuid = registry.partitions_by_type["v4-core"][0].uuid
    assert uuid == "0000:00:04.0-core0"
    with discovery.count_reads() as w:
        snap.rescan(dirty={uuid})
    assert [p for p in w.paths if "/devices/0000:00:04.0/" in p], \
        "parent chip was not re-read for a flapped logical partition"


def test_dirty_hints_survive_transient_bus_listdir_failure(tmp_path, monkeypatch):
    """A failed PCI listdir defers the tick's dirty hints instead of
    dropping them: the next successful tick still re-reads the flapped
    chip."""
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    snap = discovery.HostSnapshot(make_cfg(host))
    reg1, _ = snap.rescan()
    real_listdir = os.listdir

    def failing(path):
        if path == snap.cfg.pci_base_path:
            raise OSError(5, "Input/output error")
        return real_listdir(path)

    monkeypatch.setattr(discovery.os, "listdir", failing)
    reg2, _ = snap.rescan(dirty={"0000:00:04.0"})
    assert reg2 is reg1                       # last-known-good served
    monkeypatch.setattr(discovery.os, "listdir", real_listdir)
    with discovery.count_reads() as w:
        snap.rescan()                         # no new hints this tick
    assert [p for p in w.paths if "0000:00:04.0" in p], \
        "deferred dirty hint was lost"


def test_accel_entry_removed_under_dirty_hint_is_detected(tmp_path):
    """A dirty hint must not mask accel-class removal: when the flapped
    chip's accelN entry vanished in the same tick, the rebuilt registry
    drops the accel_index instead of serving the stale cached build."""
    import shutil
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", accel_index=0))
    cfg = make_cfg(host)
    snap = discovery.HostSnapshot(cfg)
    reg1, _ = snap.rescan()
    assert reg1.all_devices()[0].accel_index == 0
    shutil.rmtree(os.path.join(cfg.accel_class_path, "accel0"))
    reg2, _ = snap.rescan(dirty={"0000:00:04.0"})
    assert reg2 is not reg1
    assert reg2.all_devices()[0].accel_index is None
