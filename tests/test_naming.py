"""Naming: sanitizer, generation table, pci.ids streaming parser."""

import json

from tpu_device_plugin import naming


def test_sanitize_name():
    assert naming.sanitize_name("TPU v5e / lite.pod") == "TPU_V5E___LITE_POD"
    assert naming.sanitize_name("weird*chars()") == "WEIRDCHARS"


def test_builtin_generations():
    table = naming.load_generation_map(None)
    assert table["0062"].name == "v4"
    assert table["0063"].host_topology == (2, 4)


def test_defaults_are_the_packaged_json():
    """DEFAULT_GENERATIONS must be exactly the packaged data file — the
    single-source contract (no duplicated literal to drift)."""
    from importlib import resources
    raw = json.loads((resources.files("tpu_device_plugin") / "data" /
                      "tpu_ids.json").read_text(encoding="utf-8"))
    data_ids = {k.lower() for k in raw if not k.startswith("_")}
    assert data_ids == set(naming.DEFAULT_GENERATIONS)
    for dev_id in data_ids:
        info = naming.DEFAULT_GENERATIONS[dev_id]
        assert info.name == raw[dev_id]["name"]
        assert info.host_topology == tuple(raw[dev_id]["host_topology"])
        assert info.chips_per_host == raw[dev_id]["chips_per_host"]
        assert info.cores_per_chip == raw[dev_id].get("cores_per_chip", 1)


def test_generation_map_override(tmp_path):
    p = tmp_path / "gens.json"
    p.write_text(json.dumps({
        "00aa": {"name": "v7", "chips_per_host": 4, "host_topology": [2, 2]},
        "bad": {"name": "x"},  # missing fields -> skipped
    }))
    table = naming.load_generation_map(str(p))
    assert table["00aa"].name == "v7"
    assert table["00aa"].host_topology == (2, 2)
    assert "bad" not in table
    assert table["0062"].name == "v4"  # built-ins retained


PCI_IDS_FIXTURE = """\
# test pci.ids with a cross-vendor duplicate device id
10de  NVIDIA Corporation
\t1eb8  TU104GL [Tesla T4]
\tabcd  Fake NVIDIA Thing
1ae0  Google, Inc.
\t001f  NVMe device
\tabcd  Airbrush Edge TPU
\t\t1ae0 0001  subsystem line must be ignored
1af4  Red Hat, Inc.
\tabcd  Virtio Fake
"""


def test_pci_ids_lookup(tmp_path):
    p = tmp_path / "pci.ids"
    p.write_text(PCI_IDS_FIXTURE)
    # picks the right vendor's entry for a duplicated device id
    assert naming.pci_ids_device_name(str(p), "1ae0", "abcd") == "Airbrush Edge TPU"
    assert naming.pci_ids_device_name(str(p), "10de", "abcd") == "Fake NVIDIA Thing"
    assert naming.pci_ids_device_name(str(p), "1ae0", "dead") is None
    assert naming.pci_ids_device_name(str(p), "ffff", "abcd") is None
    assert naming.pci_ids_device_name("/nonexistent", "1ae0", "abcd") is None


def test_resource_name_priority(tmp_path):
    p = tmp_path / "pci.ids"
    p.write_text(PCI_IDS_FIXTURE)
    table = naming.load_generation_map(None)
    # generation table wins
    assert naming.resource_name_for("0062", table, str(p)) == "v4"
    # pci.ids fallback, sanitized
    assert naming.resource_name_for("abcd", table, str(p)) == "AIRBRUSH_EDGE_TPU"
    # raw-id fallback
    assert naming.resource_name_for("dead", table, str(p)) == "TPU_DEAD"
    assert naming.resource_name_for("dead", table, None) == "TPU_DEAD"


def test_bundled_subset_fallback_for_unknown_id():
    """utils/README.md subset contract: an id absent from both the
    generation table and the bundled pci.ids subset still yields a valid,
    unique resource name (raw-id fallback), never an error."""
    import os
    bundled = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "utils", "pci.ids")
    table = naming.load_generation_map(None)
    # known to the bundled subset (display-name fallback path)
    assert naming.resource_name_for("001f", table, bundled) == "NVME_DEVICE"
    # outside the subset entirely
    assert naming.resource_name_for("9999", table, bundled) == "TPU_9999"
