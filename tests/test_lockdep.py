"""Runtime lockdep (tpu_device_plugin/lockdep.py) unit tests.

Everything runs inside lockdep.scoped(), which enables recording with
ISOLATED state — the intentional inversions staged here must never leak
into (and fail) a surrounding TDP_LOCKDEP=1 session's final report.
"""

import threading
import time

from tpu_device_plugin import lockdep


def test_inversion_detected():
    with lockdep.scoped():
        a = lockdep.instrument("t.A", threading.Lock())
        b = lockdep.instrument("t.B", threading.Lock())
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        rep = lockdep.report()
        assert rep.inversions == [("t.A", "t.B")]
        assert any("inversion" in v for v in rep.violations())
        assert "t.A" in rep.render(stacks=True)


def test_consistent_order_is_clean():
    with lockdep.scoped():
        a = lockdep.instrument("t.A", threading.Lock())
        b = lockdep.instrument("t.B", threading.Lock())
        for _ in range(3):
            with a:
                with b:
                    pass
        rep = lockdep.report()
        assert rep.inversions == []
        assert rep.violations() == []
        assert ("t.A", "t.B") in rep.edges


def test_cross_thread_edges_combine():
    """One thread only ever takes A->B, another only B->A: neither alone
    deadlocks, but the union is the classic ABBA — lockdep's whole point."""
    with lockdep.scoped():
        a = lockdep.instrument("t.A", threading.Lock())
        b = lockdep.instrument("t.B", threading.Lock())

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
        assert lockdep.report().inversions == [("t.A", "t.B")]


def test_rlock_reentry_is_not_a_self_edge():
    with lockdep.scoped():
        r = lockdep.instrument("t.R", threading.RLock())
        with r:
            with r:
                pass
        rep = lockdep.report()
        assert ("t.R", "t.R") not in rep.edges
        assert rep.violations() == []


def test_two_instances_same_name_nested_flags_self_inversion():
    """Nesting two INSTANCES sharing a lockdep name (e.g. two per-claim
    locks) is an ABBA hazard between peers: reported as a self-edge."""
    with lockdep.scoped():
        l1 = lockdep.instrument("t.claim", threading.Lock())
        l2 = lockdep.instrument("t.claim", threading.Lock())
        with l1:
            with l2:
                pass
        rep = lockdep.report()
        assert ("t.claim", "t.claim") in rep.inversions
        assert any("t.claim" in v for v in rep.violations())


def test_three_lock_cycle_detected():
    with lockdep.scoped():
        a = lockdep.instrument("t.A", threading.Lock())
        b = lockdep.instrument("t.B", threading.Lock())
        c = lockdep.instrument("t.C", threading.Lock())
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        rep = lockdep.report()
        assert rep.cycles == [["t.A", "t.B", "t.C"]]
        assert any("cycle" in v for v in rep.violations())
        assert rep.inversions == []   # no 2-cycle in this graph


def test_cycle_reported_in_actual_edge_order():
    """Edges A->C, C->B, B->A: the cycle must read A -> C -> B -> A (real
    edges, traceable through exemplar stacks), not the sorted A -> B -> C."""
    with lockdep.scoped():
        a = lockdep.instrument("t.A", threading.Lock())
        b = lockdep.instrument("t.B", threading.Lock())
        c = lockdep.instrument("t.C", threading.Lock())
        with a:
            with c:
                pass
        with c:
            with b:
                pass
        with b:
            with a:
                pass
        rep = lockdep.report()
        assert rep.cycles == [["t.A", "t.C", "t.B"]]
        rendered = rep.render(stacks=True)
        assert "t.A -> t.C -> t.B -> t.A" in rendered
        # every arc of the cycle has its first-seen stack in the render
        assert "('t.A', 't.C')" in rendered
        assert "('t.B', 't.A')" in rendered


def test_long_hold_flagged_for_watched_lock_only():
    with lockdep.scoped(hold_threshold_ms=30, watched={"t.slow"}):
        slow = lockdep.instrument("t.slow", threading.Lock())
        fast = lockdep.instrument("t.fast", threading.Lock())
        with slow:
            time.sleep(0.06)
        with fast:             # unwatched: held long but never reported
            time.sleep(0.06)
        rep = lockdep.report()
        assert [h[0] for h in rep.long_holds] == ["t.slow"]
        assert any("long hold" in v for v in rep.violations())


def test_condition_wait_pauses_the_hold_clock():
    """A waiter is not a holder: a Condition slept on for longer than the
    threshold must NOT count as a long hold (wait releases the lock), and
    the post-wait re-acquire restarts the clock."""
    with lockdep.scoped(hold_threshold_ms=40, watched={"t.cond"}):
        cond = lockdep.instrument("t.cond", threading.Condition())
        with cond:
            cond.wait(timeout=0.1)     # sleeps past the threshold
        assert lockdep.report().long_holds == []


def test_condition_wait_releases_order_stack():
    """While waiting, the condition must not count as held: a lock taken
    by the woken path right after wait() is NOT nested under it from the
    waiting period's perspective... but a lock acquired DURING the wait by
    the same thread (via the predicate path here, simulated directly)
    records no edge from the suspended condition."""
    with lockdep.scoped():
        cond = lockdep.instrument("t.cond", threading.Condition())
        other = lockdep.instrument("t.other", threading.Lock())

        acquired_during_wait = []

        class _Probe:
            calls = 0

            def __call__(self):
                _Probe.calls += 1
                if _Probe.calls == 1:
                    # first predicate check happens with the cond lock
                    # held — a normal nested acquire, edge expected
                    return False
                with other:
                    acquired_during_wait.append(True)
                return True

        def waker():
            time.sleep(0.02)
            with cond:
                cond.notify_all()

        t = threading.Thread(target=waker)
        t.start()
        with cond:
            cond.wait_for(_Probe(), timeout=1.0)
        t.join()
        rep = lockdep.report()
        assert acquired_during_wait
        # the wait_for-internal acquire of t.other happened while the
        # condition's hold record was SUSPENDED: no cond->other edge
        assert ("t.cond", "t.other") not in rep.edges


def test_disabled_instrument_returns_raw_lock():
    was = lockdep.enabled()
    lockdep.disable()
    try:
        raw = threading.Lock()
        assert lockdep.instrument("t.raw", raw) is raw
    finally:
        if was:
            lockdep.enable()


def test_acquire_release_api_and_locked():
    with lockdep.scoped():
        a = lockdep.instrument("t.api", threading.Lock())
        assert a.acquire(True, 1.0)
        assert a.locked()
        a.release()
        assert not a.locked()
        assert "t.api" in repr(a)


def test_scoped_restores_outer_state():
    with lockdep.scoped():
        outer_a = lockdep.instrument("t.outerA", threading.Lock())
        outer_b = lockdep.instrument("t.outerB", threading.Lock())
        with outer_a:
            with outer_b:
                pass
        with lockdep.scoped():
            # isolated: the outer edge is invisible, inner mess stays here
            assert lockdep.report().edges == {}
            x = lockdep.instrument("t.X", threading.Lock())
            y = lockdep.instrument("t.Y", threading.Lock())
            with x:
                with y:
                    pass
            with y:
                with x:
                    pass
            assert lockdep.report().inversions == [("t.X", "t.Y")]
        rep = lockdep.report()
        assert ("t.outerA", "t.outerB") in rep.edges
        assert rep.inversions == []
