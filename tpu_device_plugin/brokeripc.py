"""brokeripc — the wire protocol between the serving daemon and the broker.

The privilege-separated broker (broker.py) owns every vfio/sysfs/iommufd
operation; the unprivileged serving daemon reaches them over a unix
socket. This module is the NARROW, VERSIONED framing both sides speak —
deliberately small enough to audit by reading:

  frame   = MAGIC (4 bytes b"TDPB") + length (4-byte big-endian)
            + payload (UTF-8 JSON object, <= MAX_FRAME bytes)
  fds     = passed as SCM_RIGHTS ancillary data ON the frame's first
            send/recv (socket.send_fds / socket.recv_fds; at most
            MAX_FDS per frame)

Every request object carries:
  op      — the operation name (broker.py's dispatch key)
  seq     — a client-assigned sequence number echoed in the reply, so a
            desynced connection is detected instead of mis-pairing
  span    — the caller's active flight-recorder span context (op + seq +
            thread), so every privilege crossing in the broker's audit
            ring links back to the daemon-side trace (/debug/flight)

and every reply carries `ok` (bool), `seq` (echoed), and either result
fields or `error` + `kind`. The handshake is its own op ("hello"): the
client sends PROTOCOL_VERSION, the broker refuses a mismatch with
kind="version" BEFORE serving anything else — an old daemon can never
drive a new broker into undefined requests, and vice versa.

Robustness rules, enforced on BOTH sides:
  - a frame without the magic, or longer than MAX_FRAME, is a protocol
    error: the receiver raises (server side: replies kind="protocol"
    then closes) — a corrupt length prefix must never turn into a
    multi-GB allocation;
  - short reads (peer died mid-frame) raise BrokerConnectionLost, the
    typed signal broker.BrokerClient turns into "typed unavailable"
    claim errors;
  - received fds the caller did not expect are closed immediately, never
    leaked.

No threading in this module: callers serialize access to a connection
(broker.SocketBrokerClient holds one plain lock around each
request/reply pair; the broker serves each connection on its own
thread).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import List, Optional, Tuple

MAGIC = b"TDPB"
PROTOCOL_VERSION = 1
# one frame must fit a batched revalidation for a large claim plus audit
# context, and nothing else — 1 MiB is orders of magnitude above both
MAX_FRAME = 1 << 20
MAX_FDS = 8

_LEN = struct.Struct(">I")
_HEADER_SIZE = len(MAGIC) + _LEN.size


class BrokerProtocolError(Exception):
    """The peer spoke something that is not this protocol (bad magic,
    oversized/underflowing frame, non-JSON payload, non-object payload,
    mismatched seq). The connection is unusable afterwards."""


class BrokerConnectionLost(Exception):
    """The peer vanished mid-conversation (EOF, ECONNRESET, EPIPE) — the
    kill -9 signal the serving daemon maps to typed-unavailable errors."""


def _encode(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":"),
                         sort_keys=True).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise BrokerProtocolError(
            f"frame payload {len(payload)} bytes exceeds MAX_FRAME "
            f"{MAX_FRAME}")
    return MAGIC + _LEN.pack(len(payload)) + payload


def send_frame(sock: socket.socket, obj: dict,
               fds: Tuple[int, ...] = ()) -> None:
    """Send one frame; `fds` ride as SCM_RIGHTS on the first byte."""
    data = _encode(obj)
    try:
        if fds:
            if len(fds) > MAX_FDS:
                raise BrokerProtocolError(
                    f"{len(fds)} fds exceed MAX_FDS {MAX_FDS}")
            socket.send_fds(sock, [data], list(fds))
        else:
            sock.sendall(data)
    except (BrokenPipeError, ConnectionResetError, OSError) as exc:
        raise BrokerConnectionLost(f"peer gone during send: {exc}") from exc


def _recv_exact(sock: socket.socket, n: int,
                first: bytes = b"") -> bytes:
    buf = first
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (ConnectionResetError, OSError) as exc:
            raise BrokerConnectionLost(
                f"peer gone during recv: {exc}") from exc
        if not chunk:
            raise BrokerConnectionLost("peer closed mid-frame"
                                       if buf else "peer closed")
        buf += chunk
    return buf


def recv_frame(sock: socket.socket, want_fds: int = 0,
               ) -> Tuple[dict, List[int]]:
    """Receive one frame → (object, fds). `want_fds` is the MAXIMUM fd
    count the caller will accept; extras are closed, never leaked."""
    fds: List[int] = []
    if want_fds > 0:
        # the ancillary data arrives with the first data bytes; ask for
        # the whole header in one recv_fds call, then drain the rest
        try:
            head, received, _flags, _addr = socket.recv_fds(
                sock, _HEADER_SIZE, min(want_fds, MAX_FDS))
        except (ConnectionResetError, OSError) as exc:
            raise BrokerConnectionLost(
                f"peer gone during recv: {exc}") from exc
        if not head:
            raise BrokerConnectionLost("peer closed")
        fds = list(received)
        header = _recv_exact(sock, _HEADER_SIZE, first=head)
    else:
        header = _recv_exact(sock, _HEADER_SIZE)
    try:
        if header[:len(MAGIC)] != MAGIC:
            raise BrokerProtocolError(
                f"bad frame magic {header[:len(MAGIC)]!r}")
        (length,) = _LEN.unpack(header[len(MAGIC):])
        if length > MAX_FRAME:
            raise BrokerProtocolError(
                f"frame length {length} exceeds MAX_FRAME {MAX_FRAME}")
        payload = _recv_exact(sock, length)
        try:
            obj = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as exc:
            raise BrokerProtocolError(f"malformed frame payload: {exc}") \
                from exc
        if not isinstance(obj, dict):
            raise BrokerProtocolError(
                f"frame payload is {type(obj).__name__}, not an object")
    except Exception:
        close_fds(fds)
        raise
    return obj, fds


def close_fds(fds) -> None:
    """Best-effort close of received fds (error paths, unwanted extras)."""
    import os
    for fd in fds:
        try:
            os.close(fd)
        except OSError:
            pass


def hello_request(seq: int = 0) -> dict:
    return {"op": "hello", "seq": seq, "version": PROTOCOL_VERSION}


def check_hello_reply(reply: dict) -> None:
    """Raise BrokerProtocolError unless the broker accepted our version."""
    if not reply.get("ok"):
        raise BrokerProtocolError(
            f"broker refused handshake: {reply.get('error', 'unknown')} "
            f"(kind={reply.get('kind')!r}, broker version "
            f"{reply.get('version')!r}, ours {PROTOCOL_VERSION})")
    if reply.get("version") != PROTOCOL_VERSION:
        raise BrokerProtocolError(
            f"broker answered version {reply.get('version')!r}, "
            f"ours {PROTOCOL_VERSION}")


def span_context() -> Optional[dict]:
    """The caller's active flight-recorder span as a small JSON-able
    context (None outside any span, or with tracing disabled). Carried on
    every request so the broker's audit ring links each privilege
    crossing back to the daemon-side trace. Since round 17 the context
    is the FULL trace-propagation carrier — `trace_id`/`span_id` ride
    along (counted as one propagation), so the broker process opens its
    own linked `broker.serve` span and its audit-ring entries join the
    caller's fleet trace (`/debug/fleet/trace?trace=`)."""
    from . import trace
    stack = getattr(trace._tls, "stack", None)
    if not stack:
        return None
    span = stack[-1]
    out = {"op": span.op, "seq": span.seq}
    ctx = trace.propagate_context()
    if ctx is not None:
        out["trace_id"] = ctx["trace_id"]
        out["span_id"] = ctx["span_id"]
    return out
