"""cli.py behavior tests — the production wiring, exercised IN-PROCESS.

The grand integration test drives the daemon as a subprocess, which the
stdlib coverage harness cannot trace (scripts/stdlib_coverage.py
Limitations). These tests run `cli.main()` in the pytest main thread —
signal.signal() requires it — with a controller thread that watches
/status and delivers real SIGUSR1/SIGUSR2/SIGTERM via os.kill, covering
the flag matrix VERDICT r3 item 6 lists as untested: --dra sink
composition (with and without an API server), labeler/feature-file
construction, status-server wiring, drain signal handlers, --root +
explicit path overrides.
"""

import json
import os
import shutil
import signal
import socket
import tempfile
import threading
import time
import urllib.request

import pytest

from tests.fakehost import FakeChip, FakeHost
from tests.test_dra import FakeApiServer
from tpu_device_plugin import cli


@pytest.fixture()
def host():
    root = tempfile.mkdtemp(prefix="tdpcli-")
    h = FakeHost(root)
    for i in range(2):
        h.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                            iommu_group=str(11 + i), numa_node=0))
    yield h, root
    shutil.rmtree(root, ignore_errors=True)


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------- build_config


def test_root_rerooting_with_explicit_overrides(host):
    _, root = host
    cfg, args = cli.build_config(
        ["--root", root,
         "--device-plugin-path", "/explicit/dp",
         "--dra-plugins-path", "/explicit/plugins",
         "--dra-registry-path", "/explicit/registry"])
    # explicit paths win over --root re-rooting (kind e2e contract)
    assert cfg.device_plugin_path == "/explicit/dp"
    assert cfg.kubelet_socket == "/explicit/dp/kubelet.sock"
    assert cfg.dra_plugins_path == "/explicit/plugins"
    assert cfg.dra_registry_path == "/explicit/registry"
    # while sysfs stays re-rooted
    assert cfg.pci_base_path.startswith(root)


def test_root_rerooting_defaults(host):
    _, root = host
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.device_plugin_path.startswith(root)
    assert cfg.dra_plugins_path.startswith(root)


def test_negative_partition_cap_is_usage_error():
    with pytest.raises(SystemExit) as e:
        cli.build_config(["--max-partitions-per-chip", "-1"])
    assert e.value.code == 2


def test_vfio_drivers_flag_parsing(host):
    _, root = host
    cfg, _ = cli.build_config(
        ["--root", root, "--vfio-drivers", "vfio-pci, custom-vfio,"])
    assert cfg.vfio_drivers == ("vfio-pci", "custom-vfio")


def test_lw_debounce_flag_env_parity_and_validation(host, monkeypatch):
    _, root = host
    # default
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.lw_debounce_s == pytest.approx(0.05)
    assert cfg.incremental_rediscovery is True
    # flag (ms -> s)
    cfg, _ = cli.build_config(["--root", root, "--lw-debounce-ms", "200"])
    assert cfg.lw_debounce_s == pytest.approx(0.2)
    # env parity; explicit flag wins over env
    monkeypatch.setenv("TDP_LW_DEBOUNCE_MS", "75")
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.lw_debounce_s == pytest.approx(0.075)
    cfg, _ = cli.build_config(["--root", root, "--lw-debounce-ms", "0"])
    assert cfg.lw_debounce_s == 0.0
    # arm-time validation: negative / NaN / unparseable env all fail loudly
    for bad in (["--lw-debounce-ms", "-5"], ["--lw-debounce-ms", "nan"],
                ["--lw-debounce-ms", "inf"]):
        with pytest.raises(SystemExit) as e:
            cli.build_config(["--root", root] + bad)
        assert e.value.code == 2
    monkeypatch.setenv("TDP_LW_DEBOUNCE_MS", "not-a-number")
    with pytest.raises(SystemExit) as e:
        cli.build_config(["--root", root])
    assert e.value.code == 2


def test_full_rescan_flag_env_parity(host, monkeypatch):
    _, root = host
    cfg, _ = cli.build_config(["--root", root, "--full-rescan"])
    assert cfg.incremental_rediscovery is False
    monkeypatch.setenv("TDP_FULL_RESCAN", "1")
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.incremental_rediscovery is False
    monkeypatch.setenv("TDP_FULL_RESCAN", "0")
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.incremental_rediscovery is True
    monkeypatch.setenv("TDP_FULL_RESCAN", "true")
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.incremental_rediscovery is False
    # fail-loud: a typo'd value must not silently keep incremental mode
    monkeypatch.setenv("TDP_FULL_RESCAN", "ture")
    with pytest.raises(SystemExit):
        cli.build_config(["--root", root])


def test_log_json_formatter(host, capsys):
    _, root = host
    import logging
    old_handlers = logging.root.handlers[:]
    try:
        logging.root.handlers = []
        cli.build_config(["--root", root, "--log-json"])
        logging.getLogger("tdp-test").info("hello %s", "world")
        err = capsys.readouterr().err.strip().splitlines()[-1]
        entry = json.loads(err)
        assert entry["msg"] == "hello world"
        assert entry["level"] == "INFO"
    finally:
        logging.root.handlers = old_handlers


# ------------------------------------------------------- discover-only


def test_discover_only_prints_inventory(host, capsys):
    _, root = host
    rc = cli.main(["--root", root, "--discover-only"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert list(payload["devices"]) == ["0063"]
    assert payload["unmatched_device_ids"] == []
    assert payload["node_facts"]


def test_python_dash_m_entrypoint(host, capsys, monkeypatch):
    """`python -m tpu_device_plugin` (the deployed invocation,
    manifests/*.yaml command) reaches cli.main through the __main__ shim."""
    import runpy
    import sys
    _, root = host
    monkeypatch.setattr(sys, "argv",
                        ["tpu_device_plugin", "--root", root,
                         "--discover-only"])
    with pytest.raises(SystemExit) as exc_info:
        runpy.run_module("tpu_device_plugin", run_name="__main__")
    assert exc_info.value.code == 0
    assert json.loads(capsys.readouterr().out)["node_facts"]


# ----------------------------------------------------- full daemon runs


def _run_main(argv, controller):
    """Run cli.main() in the MAIN thread with `controller(port)` driving
    it from a helper thread; returns (rc, controller_error)."""
    err = []

    def run():
        try:
            controller()
        except Exception as exc:  # surface controller assertion failures
            err.append(exc)
            os.kill(os.getpid(), signal.SIGTERM)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    rc = cli.main(argv)
    t.join(timeout=10)
    if err:
        raise err[0]
    return rc


def _get_status(port, timeout=15):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=2) as r:
                return json.load(r)
        except Exception:
            time.sleep(0.2)
    raise TimeoutError("status endpoint never came up")


def _wait(pred, what, timeout=15):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if pred():
                return
        except Exception:
            pass
        time.sleep(0.2)
    raise TimeoutError(what)


def test_main_full_stack_dra_drain_and_labels(host):
    """One full daemon pass: --dra + --feature-file + --status-port wiring,
    drain via SIGUSR1/SIGUSR2, clean SIGTERM shutdown."""
    _, root = host
    api = FakeApiServer()
    port = free_port()
    feature_file = os.path.join(root, "features.txt")

    def controller():
        s = _get_status(port)
        assert s["running"] is True if "running" in s else True
        _wait(lambda: api.slices, "ResourceSlice published")
        _wait(lambda: _get_status(port)["dra"]["serving"], "DRA serving")
        _wait(lambda: os.path.exists(feature_file), "feature file written")
        os.kill(os.getpid(), signal.SIGUSR1)           # drain
        _wait(lambda: _get_status(port)["draining"], "drain applied")
        os.kill(os.getpid(), signal.SIGUSR2)           # undrain
        _wait(lambda: not _get_status(port)["draining"], "undrained")
        os.kill(os.getpid(), signal.SIGTERM)

    try:
        rc = _run_main(
            ["--root", root, "--dra", "--api-server", api.url,
             "--status-port", str(port), "--status-host", "127.0.0.1",
             "--feature-file", feature_file, "--node-name", "node-cli",
             "--health-poll-seconds", "0.5", "--rediscovery-seconds", "0"],
            controller)
    finally:
        api.stop()
    assert rc == 0
    with open(feature_file) as f:
        content = f.read()
    assert "chips" in content
    # slice was published for the fixture chips
    obj = next(iter(api.slices.values()))
    assert len(obj["spec"]["devices"]) == 2


def test_main_dra_without_api_server(host, monkeypatch):
    """--dra with no --api-server and no in-cluster env: the driver runs
    with api=None (publish degrades, sockets still serve)."""
    _, root = host
    port = free_port()
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)

    def controller():
        _wait(lambda: _get_status(port)["dra"]["serving"], "DRA serving")
        s = _get_status(port)
        assert s["dra"]["kubelet_registered"] is False
        os.kill(os.getpid(), signal.SIGTERM)

    rc = _run_main(
        ["--root", root, "--dra", "--status-port", str(port),
         "--status-host", "127.0.0.1", "--rediscovery-seconds", "0"],
        controller)
    assert rc == 0


def test_main_plain_run_sigterm(host):
    """Minimal flag set: no dra/labeler/status — the bare run loop."""
    _, root = host

    def controller():
        time.sleep(1.0)
        os.kill(os.getpid(), signal.SIGTERM)

    rc = _run_main(["--root", root, "--rediscovery-seconds", "0"],
                   controller)
    assert rc == 0


def test_prepare_workers_flag_env_parity_and_validation(host, monkeypatch):
    _, root = host
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.prepare_workers == 4                     # default
    cfg, _ = cli.build_config(["--root", root, "--prepare-workers", "8"])
    assert cfg.prepare_workers == 8
    # env parity; explicit flag wins over env
    monkeypatch.setenv("TDP_PREPARE_WORKERS", "16")
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.prepare_workers == 16
    cfg, _ = cli.build_config(["--root", root, "--prepare-workers", "2"])
    assert cfg.prepare_workers == 2
    # fail-loud: a 0-worker pool could prepare nothing at all
    for bad_argv in (["--prepare-workers", "0"], ["--prepare-workers", "-3"]):
        with pytest.raises(SystemExit) as e:
            cli.build_config(["--root", root] + bad_argv)
        assert e.value.code == 2
    monkeypatch.setenv("TDP_PREPARE_WORKERS", "not-a-number")
    with pytest.raises(SystemExit) as e:
        cli.build_config(["--root", root])
    assert e.value.code == 2


def test_broker_flag_env_parity_and_validation(host, monkeypatch):
    _, root = host
    # default: in-process seam
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.broker_mode == "inproc"
    assert cfg.broker_socket_path.startswith(root)
    # flag
    cfg, _ = cli.build_config(["--root", root, "--broker", "spawn"])
    assert cfg.broker_mode == "spawn"
    # env supplies the mode when the flag is absent
    monkeypatch.setenv("TDP_BROKER", "spawn")
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.broker_mode == "spawn"
    # the flag wins over the env
    cfg, _ = cli.build_config(["--root", root, "--broker", "inproc"])
    assert cfg.broker_mode == "inproc"
    # a typo'd env mode fails loudly, never silently keeps privileges
    monkeypatch.setenv("TDP_BROKER", "spwan")
    with pytest.raises(SystemExit) as e:
        cli.build_config(["--root", root])
    assert e.value.code == 2
    monkeypatch.delenv("TDP_BROKER")
    # explicit socket wins over --root re-rooting (same rule as DRA paths)
    cfg, _ = cli.build_config(["--root", root,
                               "--broker-socket", "/explicit/broker.sock"])
    assert cfg.broker_socket_path == "/explicit/broker.sock"


def test_policy_flags_validation(host, tmp_path):
    _, root = host
    cfg, _ = cli.build_config(["--root", root])
    assert cfg.policy_dir is None
    cfg, _ = cli.build_config(
        ["--root", root, "--policy-dir", str(tmp_path),
         "--policy-hook-deadline-ms", "50"])
    assert cfg.policy_dir == str(tmp_path)
    assert cfg.policy_hook_deadline_ms == 50.0
    with pytest.raises(SystemExit) as e:
        cli.build_config(["--root", root, "--policy-hook-deadline-ms", "0"])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        cli.build_config(["--root", root, "--policy-hook-deadline-ms",
                          "nan"])
    assert e.value.code == 2


def test_main_spawn_broker_and_policy_dir(host):
    """Full daemon pass in spawn mode with a policy dir: cli spawns the
    privileged broker, installs the SocketBrokerClient seam, loads the
    policy engine, and reaps the broker on clean SIGTERM shutdown."""
    from tpu_device_plugin import broker as broker_mod

    _, root = host
    port = free_port()
    policy_dir = os.path.join(root, "policies")
    os.makedirs(policy_dir)
    with open(os.path.join(policy_dir, "quota.py"), "w") as f:
        f.write("def admit(ctx):\n    return None\n")

    def controller():
        _wait(lambda: _get_status(port).get("broker", {}).get("mode")
              == "spawn", "spawn-mode seam installed")
        s = _get_status(port)
        assert s["policy"]["modules"] == ["quota"]
        # the broker process answers over the IPC
        dbg = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/broker"))
        assert dbg["mode"] == "spawn"
        assert dbg["broker"]["pid"] > 0
        os.kill(os.getpid(), signal.SIGTERM)

    prev = broker_mod.get_client()
    try:
        rc = _run_main(
            ["--root", root, "--broker", "spawn",
             "--policy-dir", policy_dir,
             "--status-port", str(port), "--status-host", "127.0.0.1",
             "--rediscovery-seconds", "0"],
            controller)
    finally:
        # restore the default seam for the rest of the session
        client = broker_mod.set_client(
            prev if isinstance(prev, broker_mod.InProcessBroker) else None)
        if client is not None and client is not prev:
            client.close()
    assert rc == 0
    # the spawned broker was reaped: its socket is gone and no child
    # process is left behind serving it
    assert not os.path.exists(os.path.join(root, "run/broker.sock")) \
        or not _can_connect(os.path.join(root, "run/broker.sock"))


def _can_connect(path):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.connect(path)
        return True
    except OSError:
        return False
    finally:
        s.close()
