"""Minimal Kubernetes API client — stdlib only, no `kubernetes` package.

Shared by the node labeler (PATCH node labels) and the DRA driver
(ResourceSlice publish, ResourceClaim reads). Authenticates with the pod's
service-account token and trusts the in-cluster CA, exactly like the
labeler always has; the dependency-free stance mirrors the reference's
single-static-binary posture (its only runtime deps are grpc + sysfs,
reference: go.mod:1-12 — it never talks to the API server at all).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import socket
import ssl
import threading
import time
from typing import Callable, Optional, Union
from urllib.parse import urlsplit

from . import epoch as epoch_mod
from . import faults
from . import lockdep
from . import trace
from .resilience import BackoffPolicy, CircuitBreaker

log = logging.getLogger(__name__)

# idle keep-alive connections retained per client; excess connections from
# concurrency bursts are closed on return rather than pooled
MAX_IDLE_CONNECTIONS = 4

# failures whose signature is a stale keep-alive connection the server
# idled out — retried ONCE on a brand-new connection when the failed one
# was a reused pool member. Deliberately NARROW: a response-read timeout
# (TimeoutError) means the server may have processed the request, and
# replaying a POST/PUT there would duplicate apiserver writes, so it is
# wrapped as ApiError without retry like every other transport failure.
_RETRYABLE_STALE = (http.client.BadStatusLine,
                    http.client.CannotSendRequest,
                    http.client.ResponseNotReady, BrokenPipeError,
                    ConnectionResetError, ConnectionAbortedError)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# bounded in-call retries for a 429-throttled GET (reads are idempotent;
# writes go through PublishPacer's re-admission instead). 4 retries at
# the jittered 50-500 ms client-wide backoff rides out a boot-storm
# congestion spike without turning one kubelet RPC into an unbounded wait.
THROTTLED_GET_RETRIES = 4


def in_cluster_server() -> Optional[str]:
    """https://host:port of the API server from the in-cluster env, if any."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        return None
    return f"https://{host}:{port}"


class ApiError(Exception):
    """HTTP-level API failure carrying the status code (0 = transport)."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


class ApiClient:
    """Bearer-token REST client for one API server.

    Connections are keep-alive and pooled (up to MAX_IDLE_CONNECTIONS
    idle): a node agent talks to one apiserver for its whole life, and
    per-request TCP+TLS handshakes are both the dominant cost of a DRA
    claim prepare and pointless apiserver load. The pool never blocks —
    a concurrency burst simply opens extra connections and closes them on
    return — so a slow publish cannot stall a claim prepare (the dra.py
    lock-scope rationale). A request that fails at send/first-byte on a
    REUSED connection is retried once on a brand-new one (the server
    idled out the keep-alive); a fresh-connection failure propagates,
    matching the one-attempt behavior this client always had.

    Connections are DIRECT (http.client): HTTP(S)_PROXY env vars, which
    the pre-pool urllib implementation honored, are intentionally not —
    an in-cluster node agent talks straight to its apiserver. A path
    component in the server URL (e.g. an apiserver proxy prefix) is
    preserved and prepended to every request path.
    """

    def __init__(self, server: str,
                 token_path: str = os.path.join(SA_DIR, "token"),
                 ca_path: str = os.path.join(SA_DIR, "ca.crt"),
                 timeout_s: float = 10.0,
                 breaker: Optional[CircuitBreaker] = None):
        self.server = server.rstrip("/")
        self.token_path = token_path
        self.ca_path = ca_path
        self.timeout_s = timeout_s
        split = urlsplit(self.server)
        self._https = split.scheme == "https"
        self._host = split.hostname or self.server
        self._port = split.port
        self._base_path = split.path.rstrip("/")
        self._idle: list = []
        self._pool_lock = lockdep.instrument(
            "kubeapi.ApiClient._pool_lock", threading.Lock())
        # Circuit breaker over the whole client (resilience.py): transport
        # failures and 5xx count as failures, any response < 500 (including
        # 4xx — the server answered) as success. While open, request()
        # fails fast with ApiError instead of burning a connect timeout per
        # call — the callers' own retry loops (lifecycle publish retry, dra
        # republish timer) keep running and land on the half-open probe.
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=5, reset_timeout_s=15.0,
            name=f"kubeapi:{self._host}")
        # brief jittered pause before the single stale-keep-alive retry
        # (below): lets a restarting apiserver finish its listen() instead
        # of immediately eating the one retry the contract allows
        self._stale_backoff = BackoffPolicy(base_s=0.02, cap_s=0.2)
        # jittered client-wide backoff for 429-throttled GETs (below):
        # shared across this client's threads on purpose — when the
        # apiserver sheds load, EVERY reader of this client slows down
        # together instead of each thread independently hammering
        self._throttle_backoff = BackoffPolicy(base_s=0.05, cap_s=0.5)
        # Congestion signals consumed by PublishPacer: 429s (apiserver
        # priority-and-fairness shedding load), the calling thread's
        # last observed RTT (last_rtt_s property), and the thread's last
        # error code. throttled_total is an AtomicCounter (lock-free,
        # exact, client-wide — the /status-style aggregate); everything
        # the pacer classifies from is PER-THREAD (_throttle_tls), so
        # concurrent prepare workers' traffic on the same client can
        # never be misattributed to a publish.
        self.throttled_total = epoch_mod.AtomicCounter()
        self._throttle_tls = threading.local()

    def _new_conn(self) -> http.client.HTTPConnection:
        if self._https:
            # context rebuilt per NEW connection (cheap — pooling makes
            # new connections rare): the projected ca.crt rotates like
            # the token does, and a cached context would pin the old CA,
            # failing every handshake after a cluster CA rotation until
            # pod restart. Established pooled connections are unaffected
            # by rotation (their handshake is done).
            ctx = ssl.create_default_context(
                cafile=self.ca_path if os.path.exists(self.ca_path)
                else None)
            return http.client.HTTPSConnection(
                self._host, self._port, context=ctx,
                timeout=self.timeout_s)
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self.timeout_s)

    def _get_conn(self):
        """→ (connection, was_reused)."""
        with self._pool_lock:
            if self._idle:
                return self._idle.pop(), True
        return self._new_conn(), False

    def _put_conn(self, conn) -> None:
        with self._pool_lock:
            if len(self._idle) < MAX_IDLE_CONNECTIONS:
                self._idle.append(conn)
                return
        conn.close()

    def request(self, path: str, method: str = "GET",
                body: Optional[bytes] = None,
                content_type: Optional[str] = None) -> bytes:
        """Raw request against an API path; raises ApiError on failure.

        Fails fast (without touching the network) while the circuit
        breaker is open; every attempt's outcome feeds the breaker.

        The span (op "kubeapi.request", tdp_kubeapi_rtt_ms) is the
        daemon's apiserver-RTT observability: started inside a claim
        span it inherits the claim_uid, so a prepare stalled on a slow
        ResourceClaim GET is attributable from /debug/flight alone.
        """
        url = self.server + path
        # breaker fast-fail OUTSIDE the span: an open breaker rejects in
        # microseconds, and recording those as RTT samples would collapse
        # tdp_kubeapi_rtt_ms percentiles to ~0 exactly when the apiserver
        # is down — the opposite of what the histogram exists to show
        if not self.breaker.allow():
            raise ApiError(f"{method} {url}: circuit breaker open "
                           f"(apiserver failing; next probe within "
                           f"{self.breaker.reset_timeout_s:.0f}s)",
                           code=0)
        # The 429-GET retry loop sits OUTSIDE the per-attempt span below:
        # the backoff sleeps are client-side waiting, not server RTT, and
        # folding them into tdp_kubeapi_rtt_ms would read seconds for
        # requests the server answered in ~1 ms exactly when the
        # apiserver throttles — the same honesty rule that keeps the
        # breaker fast-fail out of the span. A throttled GET — whose
        # replay cannot duplicate a write — retries behind a client-wide
        # jittered backoff (every reader of this client slows down
        # together); throttled WRITES never retry at this layer — the
        # publish pacer owns their re-admission.
        for attempt in range(THROTTLED_GET_RETRIES + 1):
            try:
                return self._traced_attempt(path, method, body,
                                            content_type, url)
            except ApiError as exc:
                if exc.code == 429 and method == "GET" \
                        and attempt < THROTTLED_GET_RETRIES:
                    time.sleep(self._throttle_backoff.next_delay())
                    continue
                raise
        raise ApiError(f"{method} {url}: throttle retry fell "
                       f"through")  # unreachable

    def _traced_attempt(self, path: str, method: str,
                        body: Optional[bytes],
                        content_type: Optional[str], url: str) -> bytes:
        """One traced wire attempt: its span IS one server round trip
        (tdp_kubeapi_rtt_ms stays an RTT histogram even under throttle
        storms), with breaker + congestion-signal accounting."""
        with trace.span("kubeapi.request", histogram="tdp_kubeapi_rtt_ms",
                        method=method, path=path):
            tls = self._throttle_tls
            t0 = time.monotonic()
            try:
                # fault point "kubeapi.request" (raising): an armed
                # fault fails the request before the wire, as a
                # transport error would
                faults.fire("kubeapi.request", method=method, path=path)
                data = self._request_once(path, method, body,
                                          content_type, url)
            except ApiError as exc:
                tls.rtt = time.monotonic() - t0
                tls.last_code = exc.code
                if exc.code == 429:
                    # apiserver shedding load (priority-and-fairness):
                    # the pacing layer widens its admission window on
                    # this signal; the server ANSWERED, so the breaker
                    # records success like any other 4xx
                    self.throttled_total.add()
                    tls.count = getattr(tls, "count", 0) + 1
                    self.breaker.record_success()
                elif exc.code == 0 or exc.code >= 500:
                    self.breaker.record_failure()
                else:
                    self.breaker.record_success()  # 3xx/4xx: alive
                raise
            except Exception as exc:
                # injected fault of a non-ApiError kind: surface it
                # under the client's one exception contract
                self.breaker.record_failure()
                tls.last_code = 0
                raise ApiError(f"{method} {url}: {exc}") from exc
            tls.rtt = time.monotonic() - t0
            self.breaker.record_success()
            self._stale_backoff.reset()
            self._throttle_backoff.reset()
            return data

    # -- per-thread congestion signals (PublishPacer's classification) ----

    @property
    def last_rtt_s(self) -> float:
        """The CALLING thread's most recent server round-trip time —
        the pacer's slow-RTT signal (per-thread so another worker's
        request can never overwrite the publish's own reading)."""
        return getattr(self._throttle_tls, "rtt", 0.0)

    def thread_throttled_count(self) -> int:
        """429s observed by the CALLING thread's requests."""
        return getattr(self._throttle_tls, "count", 0)

    def reset_thread_error(self) -> None:
        """Clear the calling thread's last-error record (the pacer calls
        this at wave start so a stale code from earlier traffic cannot
        classify this wave)."""
        self._throttle_tls.last_code = None

    def thread_last_error_code(self) -> Optional[int]:
        """HTTP code of the CALLING thread's most recent FAILED request
        (None if none since reset). The pacer classifies a failed wave
        as throttled only when the request that made it give up was a
        429 — a publish whose internal GET drew a retried-away 429 but
        whose PUT then failed 5xx must return to the caller's republish
        machinery, not re-admit."""
        return getattr(self._throttle_tls, "last_code", None)

    def _auth_headers(self, content_type: Optional[str] = None) -> dict:
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        # token re-read per request: in-cluster tokens rotate
        try:
            with open(self.token_path, "r", encoding="ascii") as f:
                headers["Authorization"] = f"Bearer {f.read().strip()}"
        except OSError:
            pass  # no token (e.g. test server without auth)
        return headers

    def stream(self, path: str, read_timeout_s: Optional[float] = None):
        """Context manager: one DEDICATED streaming GET (watch streams).

        Yields the live http.client.HTTPResponse — the caller readline()s
        newline-delimited events off it (http.client decodes chunked
        transfer transparently). The connection is never pooled: a watch
        holds its connection for the stream's whole life, and returning it
        would poison the pool with a half-read body. Breaker contract
        matches request(): fail fast while open, the ESTABLISHMENT outcome
        feeds the breaker (a mid-stream tear is the watch protocol's
        normal rotation signal, not an apiserver-health signal)."""
        return _ApiStream(self, path, read_timeout_s)

    def _request_once(self, path: str, method: str, body: Optional[bytes],
                      content_type: Optional[str], url: str) -> bytes:
        """One logical request: pool checkout, send, narrow stale-keep-alive
        retry, status handling. Raises ApiError on any failure."""
        headers = self._auth_headers(content_type)
        # trace propagation (r17): the active span's context rides every
        # apiserver request as the standard W3C header — the fleetsim
        # fabric threads it into the watch events the write causes, and
        # a real apiserver's audit log records it. Counted propagated.
        traceparent = trace.propagate()
        if traceparent is not None:
            headers["Traceparent"] = traceparent
        for attempt in (0, 1):
            if attempt == 0:
                conn, reused = self._get_conn()
            else:
                # retry leg: ALWAYS a brand-new connection — popping
                # another pool member could hit a second stale keep-alive
                # (apiserver restart with several idle conns) and fail a
                # request a fresh connection would serve
                conn, reused = self._new_conn(), False
            # The SEND phase and the RESPONSE phase have different retry
            # safety: a send-phase failure means the server never got the
            # full request (any method can retry); a response-phase
            # failure means it may have PROCESSED it, so only GET — whose
            # replay cannot duplicate a write — retries there.
            sent = False
            try:
                conn.request(method, self._base_path + path, body=body,
                             headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError) as exc:
                conn.close()
                retry_safe = (not sent) or method == "GET"
                if (attempt == 0 and reused and retry_safe
                        and isinstance(exc, _RETRYABLE_STALE)):
                    # idled-out keep-alive: one fresh retry, after a short
                    # jittered pause (BackoffPolicy; reset on any success)
                    time.sleep(self._stale_backoff.next_delay())
                    continue
                raise ApiError(f"{method} {url}: {exc}") from exc
            if resp.will_close:
                conn.close()
            else:
                self._put_conn(conn)
            if resp.status >= 400:
                detail = data.decode("utf-8", "replace")[:300]
                raise ApiError(
                    f"{method} {url}: HTTP {resp.status} {detail}",
                    code=resp.status)
            if resp.status >= 300:
                # the pre-pool urllib client auto-followed redirects;
                # http.client does not, and silently returning a redirect
                # body would feed HTML into json.loads — surface it as
                # the transport error it is
                raise ApiError(
                    f"{method} {url}: HTTP {resp.status} redirect "
                    f"(redirects unsupported; point --api-server at the "
                    f"final URL)", code=resp.status)
            return data
        raise ApiError(f"{method} {url}: retry fell through")  # unreachable

    # -- JSON convenience wrappers against resource paths ---------------------

    def get_json(self, path: str) -> dict:
        return json.loads(self.request(path))

    def post_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="POST", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def put_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="PUT", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def delete(self, path: str) -> None:
        self.request(path, method="DELETE")

    def patch_strategic(self, path: str, obj: dict) -> bytes:
        return self.request(
            path, method="PATCH", body=json.dumps(obj).encode(),
            content_type="application/strategic-merge-patch+json")


class _StreamLineReader:
    """Newline-delimited reader over a chunked HTTPResponse that can TELL
    a clean stream end from a torn one: readline() returns b"" only when
    the server terminated the chunked body properly; an abrupt tear
    raises http.client.IncompleteRead. (HTTPResponse.readline itself
    cannot — its peek() swallows IncompleteRead by design, so a mid-
    stream connection tear reads exactly like a clean rotation and a
    watch client would silently resume over a window where events may
    have been lost.)"""

    def __init__(self, resp) -> None:
        self._resp = resp
        self._buf = b""

    def readline(self) -> bytes:
        while b"\n" not in self._buf:
            chunk = self._resp.read1(65536)
            if not chunk:
                if self._buf:
                    # mid-line tear: the event was cut off
                    raise http.client.IncompleteRead(self._buf)
                return b""
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\n")
        return line + b"\n"


class _ApiStream:
    """One dedicated streaming GET (ApiClient.stream).

    __enter__ establishes the connection and returns the live
    HTTPResponse; __exit__ closes it. close() is safe from ANOTHER
    thread — it shuts the socket, which unblocks a reader parked in
    readline() (the reflector's stop path)."""

    def __init__(self, api: "ApiClient", path: str,
                 read_timeout_s: Optional[float]):
        self.api = api
        self.path = path
        self.read_timeout_s = read_timeout_s
        self._conn = None
        self._closed = False

    def __enter__(self):
        api = self.api
        url = api.server + self.path
        if not api.breaker.allow():
            raise ApiError(f"GET {url}: circuit breaker open "
                           f"(apiserver failing; next probe within "
                           f"{api.breaker.reset_timeout_s:.0f}s)", code=0)
        conn = api._new_conn()
        if self.read_timeout_s is not None:
            conn.timeout = self.read_timeout_s
        self._conn = conn
        if self._closed:
            # close() raced establishment (Reflector.stop() landing
            # before the connection object existed): without this
            # latch check the connect below would proceed and park in
            # getresponse until the read timeout, defeating the prompt
            # shutdown close() exists to provide
            self.close()
            raise ApiError(f"GET {url}: stream closed", code=0)
        try:
            conn.request("GET", api._base_path + self.path,
                         headers=api._auth_headers())
            resp = conn.getresponse()
        except (http.client.HTTPException, OSError) as exc:
            api.breaker.record_failure()
            self.close()
            raise ApiError(f"GET {url}: {exc}") from exc
        if resp.status >= 300:
            try:
                data = resp.read()
            except (http.client.HTTPException, OSError) as exc:
                # the connection tore mid-error-body: still a typed
                # establishment failure (and a 5xx-shaped one — the
                # server was already failing the request), never a raw
                # exception that skips breaker accounting and leaks the
                # socket until GC
                api.breaker.record_failure()
                self.close()
                raise ApiError(f"GET {url}: HTTP {resp.status}, body "
                               f"torn: {exc}", code=resp.status) from exc
            if resp.status == 429:
                api.throttled_total.add()
            if resp.status >= 500:
                api.breaker.record_failure()
            else:
                api.breaker.record_success()   # answered: alive
            self.close()
            raise ApiError(
                f"GET {url}: HTTP {resp.status} "
                f"{data.decode('utf-8', 'replace')[:300]}",
                code=resp.status)
        api.breaker.record_success()
        return resp

    def close(self) -> None:
        self._closed = True
        conn, self._conn = self._conn, None
        if conn is not None:
            # shutdown BEFORE close: close() alone only drops the fd
            # refcount — a reader parked in recv() on another thread
            # (the reflector's readline) stays blocked until the next
            # bookmark or the read timeout; shutdown() wakes it NOW,
            # which is what makes Reflector.stop() prompt at fleet scale
            sock = getattr(conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


# ------------------------------------------------------------- reflector

# consecutive watch-establishment/stream failures before the reflector
# DEGRADES to paced-relist polling (the pre-watch read/repair shape).
# Degradation is typed, counted (watch_degraded_mode / *_entries_total)
# and self-healing: every degraded cycle still probes the watch, and a
# successful establishment restores event-driven convergence.
WATCH_DEGRADE_AFTER = 3


class Reflector:
    """Informer-style list+watch reflector over one collection path.

    The convergence contract (ISSUE 12):

    - LIST seeds state and the resume resourceVersion; WATCH streams
      events from there, each event (and BOOKMARK) advancing the cursor.
    - A clean stream end (server timeout rotation) re-watches from the
      cursor — no relist, no event loss.
    - A stream BREAK/STALL (transport tear, read deadline, injected
      `kubeapi.watch` fault) relists through the decorrelated-jitter
      backoff; `410 Gone` (cursor compacted, slow-consumer force-close,
      injected `kubeapi.watch.stale`) relists immediately.
    - A periodic RESYNC relist is the missed-event backstop: even an
      event lost to a bug upstream is repaired within one resync period.
    - AT-LEAST-ONCE delivery: relists, resyncs, duplicate deliveries
      (`kubeapi.watch.dup`) and bookmark replays mean every handler MUST
      be idempotent — `on_event(evt)` receives raw watch events,
      `on_sync(items)` full list states, and neither may assume it sees
      a state exactly once.
    - After WATCH_DEGRADE_AFTER consecutive stream failures the
      reflector DEGRADES to paced-relist polling (`poll_interval_s`),
      probing the watch each cycle to recover — convergence never hangs
      on a fabric that lost (or never had) watch support.

    Counters in `stats` mutate under `_lock` (tsalint COUNTERS entry);
    snapshot() is the lock-free fixed-key read /status serves. The run
    thread is tracked and joined by stop() (thread-lifecycle lint)."""

    STAT_KEYS = (
        "watch_streams_active",
        "watch_streams_established_total",
        "watch_events_total",
        "watch_bookmarks_total",
        "watch_relists_total",
        "watch_resyncs_total",
        "watch_410_total",
        "watch_breaks_total",
        "watch_duplicate_deliveries_total",
        "watch_handler_errors_total",
        "watch_degraded_mode",
        "watch_degraded_entries_total",
    )

    def __init__(self, api: ApiClient,
                 path: Union[str, Callable[[], str]],
                 on_event: Optional[Callable[[dict], None]] = None,
                 on_sync: Optional[Callable[[list], None]] = None,
                 name: str = "",
                 resync_interval_s: float = 300.0,
                 poll_interval_s: float = 30.0,
                 watch_timeout_s: float = 30.0,
                 degrade_after: int = WATCH_DEGRADE_AFTER,
                 backoff: Optional[BackoffPolicy] = None,
                 rng: Optional[random.Random] = None,
                 query: str = "",
                 on_list_404: Optional[Callable[[], None]] = None) -> None:
        self.api = api
        # a callable path is re-resolved per request: an owner whose
        # collection lives under a DISCOVERED API version (the DRA
        # slice reconciler) can invalidate its cached version from
        # on_list_404 and the very next relist/watch lands on the
        # re-discovered path — a control-plane upgrade that drops the
        # old version cannot 404 the reflector forever
        self._path_src = path
        self.on_list_404 = on_list_404
        # extra query string (no leading separator) appended to BOTH the
        # list and watch requests — e.g. a fieldSelector narrowing the
        # stream to this node's own slice, so a fleet of N watchers is
        # N streams of 1 object each, not N streams of N objects
        self.query = query
        self.on_event = on_event
        self.on_sync = on_sync
        self.name = name or self.path.rsplit("/", 1)[-1]
        self.resync_interval_s = resync_interval_s
        self.poll_interval_s = poll_interval_s
        self.watch_timeout_s = watch_timeout_s
        self.degrade_after = max(1, degrade_after)
        self.backoff = backoff or BackoffPolicy(base_s=0.2, cap_s=10.0,
                                                rng=rng)
        self._lock = lockdep.instrument(
            "kubeapi.Reflector._lock", threading.Lock())
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._live_stream: Optional[_ApiStream] = None
        self._rv = 0
        self._consec_failures = 0
        # True from a stream establishment until ANY loss of event
        # coverage — a break, a 410 (events were lost to compaction /
        # force-close), a failed relist. stream_live() requires it:
        # "a stream was once established" is not "wipe detection is
        # covered NOW".
        self._stream_ok = False
        self.stats = {key: 0 for key in self.STAT_KEYS}

    # ------------------------------------------------------------ control

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"reflector-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        stream = self._live_stream       # GIL-atomic peek
        if stream is not None:
            stream.close()               # unblocks a parked readline
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)

    def snapshot(self) -> dict:
        """Lock-free stats read (fixed-key dict: C-atomic copy +
        GIL-atomic int reads) — the /status surface."""
        return dict(self.stats)

    @property
    def path(self) -> str:
        src = self._path_src
        return src() if callable(src) else src

    @property
    def degraded(self) -> bool:
        return bool(self.stats["watch_degraded_mode"])

    def stream_live(self) -> bool:
        """True while the watch plane is healthy: a stream has been
        established, the most recent attempt did not fail, and the
        reflector is not degraded. Deliberately TRUE across the clean
        timeout rotation between two long-polls (the cursor carries
        over, nothing can be missed) and FALSE from a stream break until
        the post-relist stream re-establishes — the signal the DRA
        publish path uses to skip its liveness GET. Also FALSE from a
        410 (compaction / slow-consumer force-close: events were LOST)
        or a failed relist until the next establishment — the loop may
        be stuck relisting against a congested apiserver, and skipping
        the liveness GET then would trade a read away for a blind
        spot."""
        thread = self._thread
        return (not self.degraded
                and self._consec_failures == 0
                and self._stream_ok
                and thread is not None and thread.is_alive())

    # ---------------------------------------------------------- run loop

    def _run(self) -> None:
        need_list = True
        next_resync = time.monotonic() + self.resync_interval_s
        while not self._stop.is_set():
            if need_list or time.monotonic() >= next_resync:
                resync = not need_list
                try:
                    self._relist(resync=resync)
                except Exception as exc:
                    log.warning("reflector %s: relist failed: %s",
                                self.name, exc)
                    if (isinstance(exc, ApiError) and exc.code == 404
                            and self.on_list_404 is not None):
                        # the collection path itself may be stale (its
                        # API version dropped by a control-plane
                        # upgrade): let the owner invalidate its cached
                        # version so a callable path re-resolves on the
                        # next attempt
                        try:
                            self.on_list_404()
                        except Exception:
                            log.exception("reflector %s: on_list_404 "
                                          "hook raised", self.name)
                    # a failing LIST is a failing convergence plane: it
                    # climbs the same degradation ladder as stream
                    # breaks — a permanently dead LIST must surface as
                    # watch_degraded_mode=1 + paced polling, not loop
                    # on backoff forever with the gauge still 0
                    self._note_stream_failure(exc, relist=True)
                    continue
                self.backoff.reset()
                need_list = False
                next_resync = time.monotonic() + self.resync_interval_s
            try:
                self._watch_once()
                # clean server-side rotation: re-watch from the cursor
            except ApiError as exc:
                if exc.code == 410:
                    # compacted cursor / slow-consumer force-close: the
                    # stream cannot be caught up event-by-event. Events
                    # were LOST, so the plane is not covering until the
                    # relist + re-watch land; pace the relist (one
                    # backoff step, reset on relist success) so a
                    # sustained overflow loop cannot hammer the
                    # apiserver with back-to-back full LISTs. 410 is
                    # protocol, not failure: it never counts toward the
                    # degradation ladder.
                    self._stream_ok = False
                    with self._lock:
                        self.stats["watch_410_total"] += 1
                    trace.event("kubeapi.watch.gone", path=self.path)
                    need_list = True
                    self._sleep(self.backoff.next_delay())
                    continue
                need_list = self._note_stream_failure(exc)
            except Exception as exc:
                need_list = self._note_stream_failure(exc)

    def _note_stream_failure(self, exc: BaseException, *,
                             relist: bool = False) -> bool:
        """Count a stream break/stall — or a failed relist, which is
        just as much a loss of convergence coverage (relist=True skips
        the break counter but climbs the same degradation ladder) —
        maybe enter degraded mode, sleep the appropriate pace. Returns
        True (a relist is always required: events may have been lost
        mid-tear)."""
        self._stream_ok = False
        if self._stop.is_set():
            # the tear IS the shutdown (stop() closing a parked or
            # establishing stream) — not a fabric failure to count,
            # degrade on, or sleep through
            return True
        self._consec_failures += 1
        with self._lock:
            if not relist:
                self.stats["watch_breaks_total"] += 1
            if (self._consec_failures >= self.degrade_after
                    and not self.stats["watch_degraded_mode"]):
                self.stats["watch_degraded_mode"] = 1
                self.stats["watch_degraded_entries_total"] += 1
                degraded_now = True
            else:
                degraded_now = False
        if degraded_now:
            log.warning(
                "reflector %s: %d consecutive watch failures (%s); "
                "DEGRADED to paced-relist polling every %.1fs (watch "
                "re-probed each cycle)", self.name, self._consec_failures,
                exc, self.poll_interval_s)
            trace.event("kubeapi.watch.degraded", path=self.path)
        else:
            log.debug("reflector %s: watch stream failed (%s); relisting",
                      self.name, exc)
        self._sleep(self.poll_interval_s if self.degraded
                    else self.backoff.next_delay())
        return True

    def _on_healthy(self) -> None:
        """The stream PROVED itself — first event/bookmark read, or a
        clean zero-event rotation. Deliberately NOT called at bare
        establishment: an apiserver/LB that answers the watch GET but
        tears the stream before delivering anything would otherwise
        reset the failure counter every cycle and the degradation
        ladder could never engage."""
        self._consec_failures = 0
        self._stream_ok = True
        with self._lock:
            if self.stats["watch_degraded_mode"]:
                self.stats["watch_degraded_mode"] = 0
                recovered = True
            else:
                recovered = False
        if recovered:
            log.info("reflector %s: watch stream re-established; leaving "
                     "degraded polling", self.name)
            trace.event("kubeapi.watch.recovered", path=self.path)

    def _sleep(self, delay_s: float) -> None:
        self._stop.wait(timeout=delay_s)

    # ---------------------------------------------------------- phases

    def _relist(self, resync: bool) -> None:
        path = (f"{self.path}?{self.query}" if self.query else self.path)
        obj = self.api.get_json(path)
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        try:
            self._rv = int(rv)
        except (TypeError, ValueError):
            pass   # keep the old cursor; the next event will advance it
        with self._lock:
            self.stats["watch_relists_total"] += 1
            if resync:
                self.stats["watch_resyncs_total"] += 1
        if self.on_sync is not None:
            try:
                self.on_sync(obj.get("items") or [])
            except Exception:
                with self._lock:
                    self.stats["watch_handler_errors_total"] += 1
                log.exception("reflector %s: on_sync handler raised",
                              self.name)

    def _watch_once(self) -> None:
        rv = self._rv
        # fault point "kubeapi.watch.stale" (value): resume from a cursor
        # the server compacted long ago — the next answer is 410 Gone
        if faults.fire("kubeapi.watch.stale"):
            rv = -1
        path = (f"{self.path}?watch=1&resourceVersion={rv}"
                f"&timeoutSeconds={self.watch_timeout_s:g}")
        if self.query:
            path += f"&{self.query}"
        stream = self.api.stream(
            path, read_timeout_s=self.watch_timeout_s + 5.0)
        # publish BEFORE establishment: stop() must be able to close a
        # stream still parked in connect/getresponse (_ApiStream.close
        # is safe pre-connect and latches, so establishment cannot
        # resurrect it). The ordering pairs with stop() — it sets
        # _stop, then peeks _live_stream; we set _live_stream, then
        # check _stop — so one side always sees the other.
        self._live_stream = stream
        if self._stop.is_set():
            self._live_stream = None
            stream.close()
            return
        try:
            self._watch_stream(stream)
        finally:
            self._live_stream = None

    def _watch_stream(self, stream: "_ApiStream") -> None:
        with trace.span("kubeapi.watch.stream", path=self.path):
            with stream as resp:
                reader = _StreamLineReader(resp)
                with self._lock:
                    self.stats["watch_streams_active"] += 1
                    self.stats["watch_streams_established_total"] += 1
                healthy = False
                try:
                    while not self._stop.is_set():
                        # fault point "kubeapi.watch" (raising): the
                        # stream read fails — kind=error a break,
                        # kind=timeout a stall past the read deadline
                        faults.fire("kubeapi.watch", path=self.path)
                        line = reader.readline()
                        if not line:
                            # clean rotation: proves the stream even
                            # with zero events; re-watch from _rv
                            if not healthy:
                                self._on_healthy()
                            return
                        self._handle_line(line)
                        if not healthy:
                            # only a line that PARSED as a non-ERROR
                            # event counts as stream health: a
                            # server-sent ERROR (slow-consumer
                            # force-close, a 410-shaped one) raises out
                            # of _handle_line above, and resetting the
                            # ladder first would let a server that
                            # streams an ERROR every establishment pin
                            # _consec_failures at 0 forever
                            healthy = True
                            self._on_healthy()
                finally:
                    with self._lock:
                        self.stats["watch_streams_active"] -= 1

    def _handle_line(self, line: bytes) -> None:
        evt = json.loads(line)
        etype = evt.get("type")
        obj = evt.get("object") or {}
        if etype == "BOOKMARK":
            rv = (obj.get("metadata") or {}).get("resourceVersion")
            try:
                self._rv = int(rv)
            except (TypeError, ValueError):
                pass
            with self._lock:
                self.stats["watch_bookmarks_total"] += 1
            return
        if etype == "ERROR":
            # server-sent error event (slow-consumer force-close sends a
            # 410-shaped one): surface it under the ApiError contract so
            # the run loop's 410/relist classification applies
            code = obj.get("code")
            raise ApiError(f"watch {self.path}: server error event "
                           f"{obj}", code=int(code or 0))
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        try:
            self._rv = int(rv)
        except (TypeError, ValueError):
            pass
        with self._lock:
            self.stats["watch_events_total"] += 1
        self._deliver(evt)
        # fault point "kubeapi.watch.dup" (value): the event is delivered
        # twice — the at-least-once contract every handler must survive
        if faults.fire("kubeapi.watch.dup"):
            with self._lock:
                self.stats["watch_duplicate_deliveries_total"] += 1
                self.stats["watch_events_total"] += 1
            self._deliver(dict(evt))

    def _deliver(self, evt: dict) -> None:
        if self.on_event is None:
            return
        try:
            self.on_event(evt)
        except Exception:
            with self._lock:
                self.stats["watch_handler_errors_total"] += 1
            log.exception("reflector %s: on_event handler raised "
                          "(resync will repair)", self.name)


# ---------------------------------------------------------------- pacing

# Admission-window bounds for PublishPacer. The window starts at the
# configured base (default 0: an unloaded node publishes with zero added
# latency) and adapts: multiplicative increase on a 429 or a slow RTT,
# halving decay on fast successes — AIMD, the same shape TCP and RPCAcc-
# style PCIe RPC pacing use, because the fleet problem is the same: N
# independent senders discovering one server's capacity without a
# coordinator.
PACE_GROW_FLOOR_S = 0.05     # first growth step when the window was ~0
PACE_MAX_WINDOW_S = 2.0      # adaptation ceiling
PACE_SLOW_RTT_S = 0.25       # RTT above this reads as server congestion
PACE_MAX_ATTEMPTS = 8        # throttled-publish retries within one run()


class PublishPacer:
    """Per-client adaptive pacing + coalescing for guarded publishes.

    The fleet congestion shape (ROADMAP item 1 / RPCAcc in PAPERS.md):
    N nodes boot at once and every daemon's guarded ResourceSlice PUT
    lands on the apiserver in the same instant — a thundering herd the
    server answers with 429s, which naive clients retry immediately,
    keeping peak in-flight at N forever. This class bounds that:

    - ADMISSION WINDOW: a publish first waits a jittered delay drawn
      from the current window. The window starts at `base_window_s`
      (default 0 — steady-state single-node publishes pay nothing) and
      adapts on feedback from the ApiClient's congestion signals: a 429
      or a slow RTT doubles it (from PACE_GROW_FLOOR_S when it was ~0),
      a fast success halves it back toward base. Across a fleet the
      jittered, independently-grown windows turn N simultaneous PUTs
      into bounded-rate waves.
    - COALESCING: publishers arriving while a wave is still in its
      admission wait JOIN that wave instead of queueing their own —
      the leader builds the slice body AFTER admission, so the joined
      caller's state rides the same PUT (`publishes_coalesced_total`).
      A health-flip storm inside one daemon becomes one PUT, not one
      per flip.
    - THROTTLE RETRY: a publish the server answered with 429 is retried
      through a re-grown window (bounded by PACE_MAX_ATTEMPTS), so a
      boot storm converges without waiting for the caller's slow
      republish timer. Non-throttle failures return False immediately —
      the existing retry machinery (republish backoff, chaos contracts)
      owns those.

    Exactly-once is untouched: the pacer never replays a publish the
    server may have applied — it only delays, coalesces, and retries
    attempts the server REFUSED (429 = not executed, by definition).

    Counters (`stats`) mutate under `_cond` (tsalint COUNTERS entry);
    admission delays are recorded into the `tdp_pacing_delay_ms`
    histogram (trace.py). `rng` is injectable so fleet simulations are
    deterministic.
    """

    def __init__(self, api: Optional[ApiClient] = None,
                 base_window_s: float = 0.0,
                 max_window_s: float = PACE_MAX_WINDOW_S,
                 slow_rtt_s: float = PACE_SLOW_RTT_S,
                 max_attempts: int = PACE_MAX_ATTEMPTS,
                 rng: Optional[random.Random] = None) -> None:
        self.api = api
        self.base_window_s = max(0.0, base_window_s)
        self.max_window_s = max_window_s
        self.slow_rtt_s = slow_rtt_s
        self.max_attempts = max(1, max_attempts)
        self._rng = rng or random.Random()
        self._cond = lockdep.instrument(
            "kubeapi.PublishPacer._cond", threading.Condition())
        # state machine: idle -> waiting (admission; joinable) ->
        # publishing -> idle. All state below is guarded by _cond.
        self._state = "idle"
        self._window_s = self.base_window_s
        # remediation knob (remediation.py): a floor the drawn window
        # never goes below while a burning attach/prepare SLO has the
        # self-heal plane shedding publish pressure. 0 = no floor.
        self._floor_s = 0.0
        self._wave_seq = 0       # waves opened (leader entered waiting)
        self._done_seq = 0       # waves completed
        self._last_result = False
        self.stats = {
            # publish waves actually sent to the server (leader attempts)
            "publish_waves_total": 0,
            # callers whose state rode another caller's wave
            "publishes_coalesced_total": 0,
            # waves the server answered 429 and the pacer re-admitted
            "publish_throttled_total": 0,
            # admission waits with a non-zero delay
            "pacing_delays_total": 0,
        }

    def snapshot(self) -> dict:
        """Lock-free stats read (fixed-key dict: C-atomic copy + GIL-
        atomic int reads), plus the current admission window — the
        /status surface."""
        out = dict(self.stats)
        out["window_ms"] = round(max(self._window_s, self._floor_s) * 1e3, 3)
        out["backoff_floor_ms"] = round(self._floor_s * 1e3, 3)
        return out

    def set_backoff_floor(self, floor_s: float) -> None:
        """Remediation knob: pin the admission window at >= `floor_s`.

        The AIMD machinery keeps adapting underneath (so organic
        congestion can still grow the window PAST the floor); the floor
        only stops fast successes from collapsing it while the SLO
        plane is actively shedding. Idempotent; clamped to
        [0, max_window_s]."""
        with self._cond:
            self._floor_s = min(self.max_window_s, max(0.0, floor_s))

    def clear_backoff_floor(self) -> None:
        """Rollback: drop the remediation floor; the window decays back
        toward base through the normal fast-success path."""
        self.set_backoff_floor(0.0)

    def _wave_start(self) -> None:
        if self.api is not None:
            self.api.reset_thread_error()

    def _wave_throttled(self, ok: bool) -> bool:
        """A FAILED wave is throttled iff the request that made it give
        up answered 429. publish_fn runs synchronously on this thread,
        and the client's last-error record is per-thread and reset at
        wave start — so neither concurrent workers' traffic nor a
        retried-away internal 429 followed by a 5xx PUT can re-admit a
        wave that must return to the caller's republish machinery."""
        if ok or self.api is None:
            return False
        return self.api.thread_last_error_code() == 429

    def _wave_rtt_s(self, wall_s: float) -> float:
        """The slow-RTT adaptation signal: the publish's own last server
        round trip when a client is wired (per-thread last_rtt_s), the
        whole-wave wall otherwise (tests / detached pacers)."""
        if self.api is not None:
            rtt = self.api.last_rtt_s
            if rtt > 0:
                return rtt
        return wall_s

    def _adapt_locked(self, ok: bool, rtt_s: float, throttled: bool) -> None:
        if throttled:
            self._window_s = min(self.max_window_s,
                                 max(PACE_GROW_FLOOR_S, self._window_s * 2))
        elif rtt_s > self.slow_rtt_s:
            self._window_s = min(self.max_window_s,
                                 max(PACE_GROW_FLOOR_S / 2,
                                     self._window_s * 1.5))
        elif ok:
            decayed = self._window_s / 2
            self._window_s = self.base_window_s \
                if decayed < max(self.base_window_s, 1e-3) else decayed

    def run(self, publish_fn: Callable[[], bool]) -> bool:
        """Publish through the pacer; returns publish_fn's result (or a
        completed wave's result when this caller coalesced onto it).

        publish_fn must build the published body from CURRENT state when
        invoked (the DRA driver's `_publish_locked` does): that is what
        makes joining a wave that has not yet built its body correct.
        """
        cond = self._cond
        with cond:
            while True:
                if self._state == "waiting":
                    # a wave is still in its admission wait: our state
                    # will be in the body it builds after admission
                    joined = self._wave_seq
                    self.stats["publishes_coalesced_total"] += 1
                    cond.wait_for(lambda: self._done_seq >= joined)
                    return self._last_result
                if self._state == "publishing":
                    # too late to join (the body may already be built):
                    # wait for the wave to finish, then lead our own
                    cond.wait_for(lambda: self._state != "publishing")
                    continue
                self._state = "waiting"
                self._wave_seq += 1
                break
        ok = False
        try:
            attempt = 0
            while True:
                with cond:
                    window = max(self._window_s, self._floor_s)
                    # uniform over the FULL window: a fleet of pacers
                    # with the same window then spreads a simultaneous
                    # storm evenly across it (a [w/2, w] draw would
                    # re-clump every node into the window's second half)
                    delay = self._rng.uniform(0.0, window) \
                        if window > 0 else 0.0
                    if delay > 0:
                        self.stats["pacing_delays_total"] += 1
                        deadline = time.monotonic() + delay
                        while True:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            cond.wait(timeout=remaining)
                    self._state = "publishing"
                    self.stats["publish_waves_total"] += 1
                if delay > 0:
                    # 0-delay waves (the unloaded steady state) are not
                    # recorded: they would collapse the histogram's
                    # percentiles to 0 exactly when pacing is idle
                    trace.observe("tdp_pacing_delay_ms", delay * 1e3)
                self._wave_start()
                t0 = time.monotonic()
                ok = publish_fn()
                wall = time.monotonic() - t0
                throttled = self._wave_throttled(ok)
                with cond:
                    self._adapt_locked(ok, self._wave_rtt_s(wall),
                                       throttled)
                    if ok or not throttled \
                            or attempt >= self.max_attempts - 1:
                        return ok
                    # 429: the server refused (never executed) the PUT —
                    # re-admit through the grown window; new arrivals
                    # coalesce onto the retry
                    attempt += 1
                    self.stats["publish_throttled_total"] += 1
                    self._state = "waiting"
        finally:
            with cond:
                self._state = "idle"
                self._done_seq = self._wave_seq
                self._last_result = ok
                cond.notify_all()
