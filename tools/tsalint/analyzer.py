"""The tsalint AST engine.

One pass collects per-module structure (classes, their lock attributes,
resolvable attribute types, methods); a second pass walks every function
with a precise lexical held-lock stack, recording acquisition events,
calls, counter mutations, blocking calls, fault-point consultations and
thread constructions. Interprocedural facts (locks a callee acquires,
blocking calls it makes, locks guaranteed held at a callee's entry) come
from small fixpoints over the resolvable call graph: ``self.m()`` in the
same class (or a base), ``self.attr.m()`` where ``self.attr = Class(...)``
was seen, bare module-level functions, and ``Class(...)`` constructions
(treated as calls to ``__init__``).

The engine is deliberately conservative where Python defeats static
analysis — callbacks, parameters of unknown type, dynamically-built
receivers resolve to nothing rather than to guesses. The runtime half of
the contract (tpu_device_plugin/lockdep.py) covers what this pass cannot
see; the two report the same lock names.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

# the ONE cycle-detection implementation, shared with the runtime half
# (lockdep is stdlib-only and the package __init__ is import-light, so the
# lint environment needs no runtime dependencies for this)
from tpu_device_plugin.lockdep import find_cycles

from .config import LOCKFREE, LintConfig

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
THREAD_FACTORIES = {"Thread", "Timer"}
# broker-boundary rule (rule 7): call names that OPEN files — the only
# primitives a privileged access can enter the process through
PRIV_OPEN_FUNCS = {"open", "io.open", "os.open"}
# sysfs leaves whose write is a driver-rebind (privileged) operation
PRIV_WRITE_LEAVES = {"bind", "unbind", "driver_override"}
# container-mutating method names for the epoch-mutation rule: calling
# one of these on an epoch-rooted receiver mutates published state
EPOCH_MUTATORS = {"update", "clear", "pop", "popitem", "setdefault",
                  "append", "extend", "insert", "remove", "add", "discard"}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    qualname: str
    line: int
    message: str
    detail: str   # stable (line-free) discriminator for the baseline key

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.qualname}|{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.qualname}: "
                f"{self.message}")


def _render(node: ast.AST) -> Optional[str]:
    """Dotted rendering of a name chain; None when not a plain chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _render(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Subscript):
        base = _render(node.value)
        return f"{base}[*]" if base else None
    if isinstance(node, ast.Call):
        return _render(node.func)
    return None


_WORD_RE = re.compile(r"[a-z0-9]+")


def re_split_nonword(text: str) -> List[str]:
    """Lower-cased word tokens of a path/name blob (broker-boundary
    evidence matching: `reconfigure_path` must not read as `config`)."""
    return _WORD_RE.findall(text.lower())


def _epoch_like(name: str) -> bool:
    """Name-level epoch detection for the epoch-mutation rule: `ep`,
    `epoch`, or any `*_epoch` local/attribute segment is treated as
    epoch-rooted (the codebase convention; the `.current` / builder-call
    alias tracking catches differently-named locals)."""
    return name in ("ep", "epoch") or name.endswith("_epoch")


def _unwrap_instrument(call: ast.Call) -> ast.expr:
    """lockdep.instrument("name", <lock factory>) -> the factory expr."""
    name = _render(call.func) or ""
    if name.endswith("instrument") and len(call.args) >= 2:
        return call.args[1]
    return call


def _lock_kind(value: ast.expr) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when `value` constructs one (directly or
    wrapped in lockdep.instrument), else None."""
    if not isinstance(value, ast.Call):
        return None
    inner = _unwrap_instrument(value)
    if not isinstance(inner, ast.Call):
        return None
    name = _render(inner.func) or ""
    leaf = name.rsplit(".", 1)[-1]
    if leaf in LOCK_FACTORIES and (name == leaf
                                   or name.startswith("threading.")):
        return leaf
    return None


@dataclass
class ClassInfo:
    module: str
    name: str
    bases: List[str] = field(default_factory=list)   # rendered base names
    lock_attrs: Dict[str, str] = field(default_factory=dict)   # attr -> node
    lock_kinds: Dict[str, str] = field(default_factory=dict)   # node -> kind
    attr_types: Dict[str, str] = field(default_factory=dict)   # attr -> qual
    methods: Dict[str, ast.AST] = field(default_factory=dict)

    @property
    def qual(self) -> str:
        return f"{self.module}.{self.name}"


@dataclass
class ModuleInfo:
    name: str
    path: str
    tree: ast.AST
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    module_locks: Dict[str, str] = field(default_factory=dict)  # var -> node
    lock_kinds: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, ast.AST] = field(default_factory=dict)
    imported: Dict[str, str] = field(default_factory=dict)  # local -> simple


@dataclass
class _ThreadSite:
    factory: str                  # "Thread" | "Timer"
    qualname: str
    path: str
    line: int
    daemon: bool = False
    self_attr: Optional[str] = None   # "self.X" it ends up stored on
    anonymous: bool = True


@dataclass
class _FuncFacts:
    """Per-function events recorded by the lexical walk."""
    qualname: str
    path: str
    # (held-lock tuple, acquired node, line)
    acquires: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (held-lock tuple, callee id, line); callee id = "module.Class.meth"
    calls: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (held-lock tuple, rendered blocking call, line)
    blocking: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (held-lock tuple, counter attr form, line)
    counters: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (site literal or None, line)
    fire_sites: List[Tuple[Optional[str], int]] = field(default_factory=list)
    threads: List[_ThreadSite] = field(default_factory=list)
    # stop-path evidence: join/cancel targets ("self.<attr>" once local
    # aliases resolve) seen in this function; join carries has-timeout
    join_calls: List[Tuple[str, bool]] = field(default_factory=list)
    cancel_calls: List[str] = field(default_factory=list)
    # (rendered write target, line) for attribute/dict writes (or
    # mutating method calls) on epoch-rooted expressions
    epoch_writes: List[Tuple[str, int]] = field(default_factory=list)
    # (kind, evidence token, line) for privileged calls — device-node
    # opens, sysfs bind/unbind/driver_override writes, config-space
    # reads (broker-boundary rule)
    priv_calls: List[Tuple[str, str, int]] = field(default_factory=list)
    # trace-carrier rule (rule 8) evidence:
    # (callee leaf, kwarg names, positional argc, None-valued kwargs,
    # line) for calls matching a registered call-kwarg carrier
    carrier_calls: List[Tuple[str, FrozenSet[str], int,
                              FrozenSet[str], int]] = field(
        default_factory=list)
    # (string keys, None-valued keys, string-CONSTANT-valued keys,
    # has ** spread, line) for every dict literal — the rule matches
    # marker sets against these
    carrier_dicts: List[Tuple[FrozenSet[str], FrozenSet[str],
                              FrozenSet[str], bool, int]] = field(
        default_factory=list)
    # string-constant subscript-store keys (`x["Traceparent"] = ...`):
    # header-store crossings and late carrier-field stamps
    key_stores: Set[str] = field(default_factory=set)


class _FunctionWalker(ast.NodeVisitor):
    """Lexical walk of ONE function body with a held-lock stack."""

    def __init__(self, analyzer: "Analyzer", module: ModuleInfo,
                 cls: Optional[ClassInfo], qualname: str,
                 func: ast.AST) -> None:
        self.a = analyzer
        self.module = module
        self.cls = cls
        self.facts = _FuncFacts(qualname=qualname, path=module.path)
        self.held: List[str] = []
        self.aliases: Dict[str, str] = {}   # local name -> "self.<attr>"
        # locals known to hold an epoch (bound from a `.current` read, a
        # build_*epoch(...) call, or a parameter with an epoch-like name)
        self.epoch_aliases: set = set()
        self.self_name: Optional[str] = None
        args = getattr(func, "args", None)
        if cls is not None and args is not None and args.args:
            self.self_name = args.args[0].arg
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                if _epoch_like(a.arg) and a.arg != self.self_name:
                    self.epoch_aliases.add(a.arg)
        self._func = func

    # ------------------------------------------------------------ resolve

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        """'X' when node is self.X (or an alias of it)."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == self.self_name:
            return node.attr
        if isinstance(node, ast.Name):
            target = self.aliases.get(node.id)
            if target is not None:
                return target
        return None

    def _lock_node(self, node: ast.AST) -> Optional[str]:
        attr = self._self_attr(node)
        if attr is not None and self.cls is not None:
            found = self.a.class_lock(self.cls, attr)
            if found is not None:
                return found
        name = _render(node)
        if name is not None and name in self.module.module_locks:
            return self.module.module_locks[name]
        # fallback: X.attr on a non-self receiver, when the attr name
        # uniquely identifies one lock across all scanned classes
        if isinstance(node, ast.Attribute):
            return self.a.unique_lock_attr(node.attr)
        return None

    def _epoch_rooted(self, node: ast.AST) -> bool:
        """True when the attribute/subscript chain under `node` is rooted
        at (or passes through) an epoch: a tracked epoch local, any chain
        segment with an epoch-like name, or a `.current` store read
        (`store.current.x = ...` mutates the published epoch directly,
        with no alias for the alias tracking to catch)."""
        segs: List[str] = []
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            if isinstance(node, ast.Attribute):
                # `.current` is only epoch-like as an ATTRIBUTE segment
                # (a store read); a bare local named `current` is not
                if node.attr == "current":
                    return True
                segs.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            if node.id in self.epoch_aliases:
                return True
            segs.append(node.id)
        return any(_epoch_like(s) for s in segs)

    def _note_epoch_write(self, target: ast.AST, line: int) -> None:
        """Record an attribute/dict write whose base is epoch-rooted.
        Rebinding a bare Name is construction, not mutation."""
        if not isinstance(target, (ast.Attribute, ast.Subscript)):
            return
        if self._epoch_rooted(target.value):
            self.facts.epoch_writes.append(
                (_render(target) or "<epoch>", line))

    def _callee(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute):
            recv_attr = self._self_attr(func.value)
            if isinstance(func.value, ast.Name) and \
                    func.value.id == self.self_name and self.cls is not None:
                target = self.a.resolve_method(self.cls, func.attr)
                if target is not None:
                    return target
            if recv_attr is not None and self.cls is not None:
                recv_qual = self.a.class_attr_type(self.cls, recv_attr)
                if recv_qual is not None:
                    target_cls = self.a.class_by_qual(recv_qual)
                    if target_cls is not None:
                        return self.a.resolve_method(target_cls, func.attr)
        elif isinstance(func, ast.Name):
            if func.id in self.module.functions:
                return f"{self.module.name}.{func.id}"
            simple = self.module.imported.get(func.id, func.id)
            cls = self.a.class_by_simple(simple)
            if cls is not None:   # Class(...) construction -> __init__
                return self.a.resolve_method(cls, "__init__")
        return None

    # -------------------------------------------------------------- visits

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = self._lock_node(item.context_expr)
            if lock is not None:
                self.facts.acquires.append(
                    (tuple(self.held), lock, node.lineno))
                self.held.append(lock)
                pushed += 1
            else:
                # the context manager expression itself may call things
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        # trace-carrier rule (rule 8): a constant-key subscript store is
        # a header-store crossing or a late carrier-field stamp
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.slice, ast.Constant) and \
                    isinstance(tgt.slice.value, str):
                self.facts.key_stores.add(tgt.slice.value)
        # local alias tracking: name = self.attr — including the
        # teardown-swap form `name, self.attr = self.attr, None`
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Attribute) and \
                isinstance(node.value.value, ast.Name) and \
                node.value.value.id == self.self_name:
            self.aliases[node.targets[0].id] = node.value.attr
        # epoch alias tracking: `x = <store>.current` and
        # `x = build_*epoch(...)` bind an epoch; a later rebinding to
        # anything else releases the alias
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            value = node.value
            is_epoch = (isinstance(value, ast.Attribute)
                        and value.attr == "current")
            if not is_epoch and isinstance(value, ast.Call):
                rendered_fn = _render(value.func) or ""
                is_epoch = "epoch" in rendered_fn.rsplit(".", 1)[-1]
            if is_epoch:
                self.epoch_aliases.add(name)
            else:
                self.epoch_aliases.discard(name)
        for target in node.targets:
            self._note_epoch_write(target, node.lineno)
        if len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Tuple) and \
                isinstance(node.value, ast.Tuple) and \
                len(node.targets[0].elts) == len(node.value.elts):
            for tgt, val in zip(node.targets[0].elts, node.value.elts):
                if isinstance(tgt, ast.Name) \
                        and isinstance(val, ast.Attribute) \
                        and isinstance(val.value, ast.Name) \
                        and val.value.id == self.self_name:
                    self.aliases[tgt.id] = val.attr
        for target in node.targets:
            self._note_counter_write(target, node.value, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_counter_write(node.target, None, node.lineno,
                                 always=True)
        self._note_epoch_write(node.target, node.lineno)
        self.generic_visit(node)

    def _counter_form(self, target: ast.AST) -> Optional[str]:
        """'attr' / 'attr[*]' when target mutates a self-owned (or aliased)
        name; module-level Name targets render as-is."""
        sub = isinstance(target, ast.Subscript)
        base = target.value if sub else target
        attr = self._self_attr(base)
        if attr is None and isinstance(base, ast.Name) and self.cls is None:
            attr = base.id
        if attr is None:
            return None
        return f"{attr}[*]" if sub else attr

    def _note_counter_write(self, target: ast.AST, value: Optional[ast.AST],
                            line: int, always: bool = False) -> None:
        form = self._counter_form(target)
        if form is None:
            return
        if not always:
            # plain Assign only counts as a counter mutation when it is a
            # read-modify-write (the value mentions the same name) — plain
            # (re)initialization is construction, not counting
            names = {n for n in (
                self._counter_form(v) if isinstance(
                    v, (ast.Attribute, ast.Name, ast.Subscript)) else None
                for v in ast.walk(value)) if n} if value is not None else set()
            base = form.split("[", 1)[0]
            if not any(n.split("[", 1)[0] == base for n in names):
                return
        self.facts.counters.append((tuple(self.held), form, line))

    def visit_Call(self, node: ast.Call) -> None:
        rendered = _render(node.func) or ""
        leaf = rendered.rsplit(".", 1)[-1]

        # threading.Thread( / threading.Timer(
        if leaf in THREAD_FACTORIES and (
                rendered.startswith("threading.") or rendered == leaf):
            if rendered.startswith("threading.") or \
                    self.module.imported.get(leaf) == leaf:
                self._note_thread(node, leaf)

        # faults.fire("site")
        if leaf == "fire" and (rendered == "faults.fire"
                               or rendered.endswith(".fire")
                               and rendered.startswith("faults")):
            site = None
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                site = node.args[0].value
            self.facts.fire_sites.append((site, node.lineno))

        # lock.acquire() on a known lock: an acquisition event (we cannot
        # reliably pair the release, so the held stack is not pushed)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            lock = self._lock_node(node.func.value)
            if lock is not None:
                self.facts.acquires.append(
                    (tuple(self.held), lock, node.lineno))

        # join()/cancel() evidence for the thread-lifecycle rule
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("join", "cancel"):
            target = _render(node.func.value) or ""
            attr = self._self_attr(node.func.value)
            if attr is None and isinstance(node.func.value, ast.Name):
                # loop variable over a tracked list: `for t in
                # self.X: t.join(...)` joins self.X's members
                attr = self._loop_aliases.get(node.func.value.id)
            if attr is not None:
                target = f"self.{attr}"
            if node.func.attr == "join":
                has_timeout = bool(node.args) or any(
                    kw.arg == "timeout" for kw in node.keywords)
                self.facts.join_calls.append((target, has_timeout))
            else:
                self.facts.cancel_calls.append(target)

        # mutating method call on an epoch-rooted receiver
        # (epoch-mutation rule): ep.devices.update(...) etc.
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in EPOCH_MUTATORS and \
                self._epoch_rooted(node.func.value):
            self.facts.epoch_writes.append(
                (f"{_render(node.func) or '<epoch>'}()", node.lineno))

        # privileged call detection (broker-boundary rule): open-family
        # calls whose path expression evidences a device node, a driver
        # rebind write, or a config-space read
        if rendered in PRIV_OPEN_FUNCS:
            priv = self._priv_open_detail(node)
            if priv is not None:
                self.facts.priv_calls.append(
                    (priv[0], priv[1], node.lineno))

        # trace-carrier rule (rule 8): calls into a registered call-kwarg
        # carrier — record the argument shape, judged by the rule pass
        if leaf in self.a.carrier_call_names:
            kwargs = frozenset(kw.arg for kw in node.keywords
                               if kw.arg is not None)
            none_kwargs = frozenset(
                kw.arg for kw in node.keywords
                if kw.arg is not None
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is None)
            self.facts.carrier_calls.append(
                (leaf, kwargs, len(node.args), none_kwargs, node.lineno))

        # blocking calls
        if self.a.is_blocking_name(rendered):
            self.facts.blocking.append(
                (tuple(self.held), rendered, node.lineno))

        # resolvable callees (propagation)
        callee = self._callee(node)
        if callee is not None:
            self.facts.calls.append((tuple(self.held), callee, node.lineno))

        self.generic_visit(node)

    def _priv_open_detail(self, node: ast.Call):
        """(kind, evidence) when this open-family call touches privileged
        state, else None. Evidence is gathered from the PATH expression —
        every string constant in it plus the rendered name chain — so
        both literal paths ("/dev/vfio/11") and conventionally-named
        variables (config_path) are caught; rendered names keep the
        codebase's naming convention load-bearing, which is exactly what
        a lint rule should pin."""
        if not node.args:
            return None
        path_arg = node.args[0]
        texts: List[str] = [c.value for c in ast.walk(path_arg)
                            if isinstance(c, ast.Constant)
                            and isinstance(c.value, str)]
        rendered_path = _render(path_arg)
        if rendered_path:
            texts.append(rendered_path)
        blob = " ".join(texts)
        if "dev/vfio" in blob or "dev/iommu" in blob:
            return ("device-node-open", "dev/vfio|dev/iommu")
        # tokenized word match so `reconfigure` never reads as `config`
        tokens = {t for text in texts
                  for t in re_split_nonword(text) if t}
        if tokens & PRIV_WRITE_LEAVES and self._open_writes(node):
            leaf = sorted(tokens & PRIV_WRITE_LEAVES)[0]
            return ("sysfs-rebind-write", leaf)
        if "config" in tokens:
            return ("config-space-read", "config")
        return None

    @staticmethod
    def _open_writes(node: ast.Call) -> bool:
        """True when the open call's mode/flags evidence a write."""
        mode = None
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                mode = kw.value.value
        if mode is not None:
            return any(ch in mode for ch in "wa+x")
        flags = " ".join(filter(None, (_render(a) for a in node.args[1:])))
        return "O_WRONLY" in flags or "O_RDWR" in flags or "O_APPEND" in flags

    def _note_thread(self, node: ast.Call, factory: str) -> None:
        site = _ThreadSite(factory=factory, qualname=self.facts.qualname,
                           path=self.module.path, line=node.lineno)
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                site.daemon = bool(kw.value.value)
        self.facts.threads.append(site)

    def visit_Dict(self, node: ast.Dict) -> None:
        # trace-carrier rule (rule 8): every dict literal's string-key
        # shape, so the rule pass can match carrier-record marker sets
        keys: Set[str] = set()
        none_keys: Set[str] = set()
        const_keys: Set[str] = set()
        spread = False
        for k, v in zip(node.keys, node.values):
            if k is None:           # {**other}: opaque, can't prove absence
                spread = True
                continue
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
                if isinstance(v, ast.Constant):
                    if v.value is None:
                        none_keys.add(k.value)
                    elif isinstance(v.value, str):
                        const_keys.add(k.value)
        if keys:
            self.facts.carrier_dicts.append(
                (frozenset(keys), frozenset(none_keys),
                 frozenset(const_keys), spread, node.lineno))
        self.generic_visit(node)

    # nested defs run later on other stacks: analyze separately, not inline
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.a.queue_nested(self.module, self.cls,
                            f"{self.facts.qualname}.{node.name}", node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass   # opaque: runs later, elsewhere

    def visit_For(self, node: ast.For) -> None:
        # `for t in self.X:` — inside the loop body, `t` aliases an
        # element of self.X. This is the tracked-thread-LIST pattern
        # (a worker pool appends its threads to one attribute and a
        # stop path loops the list joining each member), which the
        # thread-lifecycle rule must credit like a direct attr join.
        attr = self._self_attr(node.iter)
        scoped = attr is not None and isinstance(node.target, ast.Name)
        if scoped:
            prev = self._loop_aliases.get(node.target.id)
            self._loop_aliases[node.target.id] = attr
        self.generic_visit(node)
        if scoped:
            # the alias means "an element of self.X" only INSIDE the
            # loop body: leaking it past the loop would credit a later
            # unrelated reuse of the name (t = Timer(); ... t.cancel())
            # to the wrong attribute
            if prev is None:
                self._loop_aliases.pop(node.target.id, None)
            else:
                self._loop_aliases[node.target.id] = prev

    def run(self) -> _FuncFacts:
        self._loop_aliases: Dict[str, str] = {}
        for stmt in getattr(self._func, "body", []):
            self.visit(stmt)
        self._finish_threads()
        return self.facts

    def _finish_threads(self) -> None:
        """Post-pass over the raw statements to resolve what each thread
        construction was assigned to and whether `.daemon = True` follows."""
        if not self.facts.threads:
            return
        assigns: List[Tuple[str, int]] = []    # (target render, line)
        daemon_sets: List[str] = []            # target renders
        for stmt in ast.walk(self._func):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(stmt.value, ast.Call):
                    name = _render(stmt.value.func) or ""
                    if name.rsplit(".", 1)[-1] in THREAD_FACTORIES:
                        rendered = self._assign_target(tgt)
                        if rendered:
                            assigns.append((rendered, stmt.lineno))
                elif isinstance(stmt.value, ast.Name):
                    # self.X = t  (local handed to an attribute)
                    rendered = self._assign_target(tgt)
                    if rendered and rendered.startswith("self."):
                        src = stmt.value.id
                        assigns.append((f"{src}->{rendered}", stmt.lineno))
                if isinstance(tgt, ast.Attribute) and tgt.attr == "daemon" \
                        and isinstance(stmt.value, ast.Constant) \
                        and stmt.value.value:
                    base = _render(tgt.value)
                    if base:
                        daemon_sets.append(base)
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Call):
                # self.X.append(t) — the tracked-thread-LIST binding
                # (joined by a stop path's `for t in self.X: t.join()`)
                call = stmt.value
                if isinstance(call.func, ast.Attribute) and \
                        call.func.attr == "append" and call.args and \
                        isinstance(call.args[0], ast.Name):
                    recv = self._assign_target(call.func.value)
                    if recv and recv.startswith("self."):
                        assigns.append(
                            (f"{call.args[0].id}->{recv}", stmt.lineno))
        for site in self.facts.threads:
            direct = [a for a, line in assigns if line == site.line]
            if direct:
                target = direct[0]
                site.anonymous = False
                if target.startswith("self."):
                    site.self_attr = target[5:]
                else:
                    # a local: daemonized via local.daemon = True?
                    if target in daemon_sets:
                        site.daemon = True
                    # handed on to self.X later?
                    for a, _line in assigns:
                        if a.startswith(f"{target}->self."):
                            site.self_attr = a.split("->self.", 1)[1]
            elif site.daemon:
                site.anonymous = True

    def _assign_target(self, tgt: ast.AST) -> Optional[str]:
        if isinstance(tgt, ast.Name):
            return tgt.id
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and \
                tgt.value.id == self.self_name:
            return f"self.{tgt.attr}"
        return None


class Analyzer:
    """Whole-program pass over a set of modules (see module docstring)."""

    def __init__(self, config: LintConfig) -> None:
        self.config = config
        self.modules: Dict[str, ModuleInfo] = {}
        self.facts: Dict[str, _FuncFacts] = {}      # qualname -> facts
        self.func_class: Dict[str, Optional[ClassInfo]] = {}
        self._nested: List[Tuple[ModuleInfo, Optional[ClassInfo],
                                 str, ast.AST]] = []
        self._lock_attr_index: Dict[str, Set[str]] = {}
        self.lock_kinds: Dict[str, str] = {}
        self.carrier_call_names = frozenset(
            c.call for c in (config.carriers or ())
            if c.kind == "call-kwarg")

    # ----------------------------------------------------------- structure

    def add_source(self, path: str, source: str) -> None:
        name = path.rsplit("/", 1)[-1].removesuffix(".py")
        tree = ast.parse(source, filename=path)
        mod = ModuleInfo(name=name, path=path, tree=tree)
        for node in tree.body:
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    mod.imported[alias.asname or alias.name] = alias.name
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imported[alias.asname or alias.name] = alias.name
            elif isinstance(node, ast.Assign):
                kind = _lock_kind(node.value)
                if kind is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            lock = f"{name}.{tgt.id}"
                            mod.module_locks[tgt.id] = lock
                            mod.lock_kinds[lock] = kind
                            self.lock_kinds[lock] = kind
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                cls = ClassInfo(module=name, name=node.name,
                                bases=[b for b in map(_render, node.bases)
                                       if b])
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        cls.methods[item.name] = item
                        self._collect_attrs(cls, item)
                mod.classes[node.name] = cls
        self.modules[name] = mod

    def _collect_attrs(self, cls: ClassInfo, func: ast.AST) -> None:
        args = getattr(func, "args", None)
        self_name = args.args[0].arg if args and args.args else "self"
        for node in ast.walk(func):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == self_name):
                    continue
                kind = _lock_kind(node.value)
                if kind is not None:
                    lock = f"{cls.qual}.{tgt.attr}"
                    cls.lock_attrs[tgt.attr] = lock
                    cls.lock_kinds[lock] = kind
                    self.lock_kinds[lock] = kind
                    self._lock_attr_index.setdefault(tgt.attr, set()).add(lock)
                elif isinstance(node.value, ast.Call):
                    ctor = _render(node.value.func)
                    if ctor and "." not in ctor:
                        cls.attr_types.setdefault(tgt.attr, ctor)

    # ------------------------------------------------------------- lookups

    def class_by_simple(self, simple: str) -> Optional[ClassInfo]:
        for mod in self.modules.values():
            if simple in mod.classes:
                return mod.classes[simple]
        return None

    def class_by_qual(self, qual: str) -> Optional[ClassInfo]:
        mod_name, _, cls_name = qual.rpartition(".")
        mod = self.modules.get(mod_name)
        if mod is not None and cls_name in mod.classes:
            return mod.classes[cls_name]
        return self.class_by_simple(cls_name)

    def _mro(self, cls: ClassInfo) -> List[ClassInfo]:
        out, seen, queue = [], set(), [cls]
        while queue:
            c = queue.pop(0)
            if c.qual in seen:
                continue
            seen.add(c.qual)
            out.append(c)
            for base in c.bases:
                resolved = self.class_by_simple(base.rsplit(".", 1)[-1])
                if resolved is not None:
                    queue.append(resolved)
        return out

    def class_lock(self, cls: ClassInfo, attr: str) -> Optional[str]:
        for c in self._mro(cls):
            if attr in c.lock_attrs:
                return c.lock_attrs[attr]
        return None

    def class_attr_type(self, cls: ClassInfo, attr: str) -> Optional[str]:
        for c in self._mro(cls):
            if attr in c.attr_types:
                target = self.class_by_simple(c.attr_types[attr])
                if target is not None:
                    return target.qual
        return None

    def resolve_method(self, cls: ClassInfo, meth: str) -> Optional[str]:
        for c in self._mro(cls):
            if meth in c.methods:
                return f"{c.qual}.{meth}"
        return None

    def unique_lock_attr(self, attr: str) -> Optional[str]:
        locks = self._lock_attr_index.get(attr, set())
        return next(iter(locks)) if len(locks) == 1 else None

    def counter_owner(self, cls: Optional[ClassInfo], module: ModuleInfo,
                      form: str) -> Optional[str]:
        """Owning lock configured for counter `form`, or None."""
        scopes = ([c.qual for c in self._mro(cls)] if cls is not None
                  else [module.name])
        for scope in scopes:
            table = self.config.counters.get(scope)
            if table and form in table:
                return table[form]
        return None

    def is_blocking_name(self, rendered: str) -> bool:
        if not rendered:
            return False
        if rendered in self.config.blocking_calls:
            return True
        leaf = rendered.rsplit(".", 1)[-1]
        if leaf in self.config.blocking_methods:
            return True
        # suffix match: cfg-rooted aliases like "os.path.join" stay distinct
        return any(rendered.endswith("." + b) if "." in b else False
                   for b in self.config.blocking_calls)

    # ------------------------------------------------------------- walking

    def queue_nested(self, module: ModuleInfo, cls: Optional[ClassInfo],
                     qualname: str, func: ast.AST) -> None:
        self._nested.append((module, cls, qualname, func))

    def _walk_all(self) -> None:
        for mod in self.modules.values():
            for fname, func in mod.functions.items():
                self._walk_one(mod, None, f"{mod.name}.{fname}", func)
            for cls in mod.classes.values():
                for mname, meth in cls.methods.items():
                    self._walk_one(mod, cls, f"{cls.qual}.{mname}", meth)
        while self._nested:
            mod, cls, qualname, func = self._nested.pop()
            self._walk_one(mod, cls, qualname, func)

    def _walk_one(self, module: ModuleInfo, cls: Optional[ClassInfo],
                  qualname: str, func: ast.AST) -> None:
        walker = _FunctionWalker(self, module, cls, qualname, func)
        self.facts[qualname] = walker.run()
        self.func_class[qualname] = cls

    # ------------------------------------------------------------ fixpoints

    def _method_closure(self) -> Tuple[Dict[str, Set[str]],
                                       Dict[str, Set[Tuple[str, int]]]]:
        """(locks each function may acquire, blocking calls it may make),
        transitively over resolvable callees."""
        locks: Dict[str, Set[str]] = {}
        blocking: Dict[str, Set[Tuple[str, int]]] = {}
        for qual, facts in self.facts.items():
            locks[qual] = {node for _, node, _line in facts.acquires}
            blocking[qual] = {(name, line)
                              for _, name, line in facts.blocking}
        changed = True
        while changed:
            changed = False
            for qual, facts in self.facts.items():
                for _, callee, _line in facts.calls:
                    extra = locks.get(callee)
                    if extra and not extra <= locks[qual]:
                        locks[qual] |= extra
                        changed = True
                    extra_b = blocking.get(callee)
                    if extra_b and not extra_b <= blocking[qual]:
                        blocking[qual] |= extra_b
                        changed = True
        return locks, blocking

    def _entry_contexts(self) -> Dict[str, Set[str]]:
        """Locks guaranteed held whenever a function is entered: the
        intersection over all resolved call sites (entry points: none)."""
        TOP = {"<top>"}
        called: Set[str] = set()
        for facts in self.facts.values():
            called |= {c for _, c, _ in facts.calls}
        ctx: Dict[str, Set[str]] = {
            q: (set(self.lock_kinds) | TOP if q in called else set())
            for q in self.facts}
        changed = True
        while changed:
            changed = False
            for qual, facts in self.facts.items():
                caller_ctx = ctx.get(qual, set()) - TOP
                for held, callee, _line in facts.calls:
                    if callee not in ctx:
                        continue
                    incoming = set(held) | caller_ctx
                    new = ctx[callee] & incoming if TOP not in ctx[callee] \
                        else incoming
                    if new != ctx[callee]:
                        ctx[callee] = new
                        changed = True
        return {q: s - TOP for q, s in ctx.items()}

    # --------------------------------------------------------------- rules

    def analyze(self) -> List[Finding]:
        self._walk_all()
        findings: List[Finding] = []
        trans_locks, trans_blocking = self._method_closure()
        entry_ctx = self._entry_contexts()
        findings += self._rule_lock_order(trans_locks)
        findings += self._rule_blocking(trans_blocking, entry_ctx)
        findings += self._rule_counters(entry_ctx)
        findings += self._rule_fault_sites()
        findings += self._rule_threads()
        findings += self._rule_epoch_mutation()
        findings += self._rule_broker_boundary()
        findings += self._rule_trace_carrier()
        order = {r: i for i, r in enumerate((
            "lock-order-cycle", "blocking-under-hot-lock", "counter-lock",
            "fault-site", "thread-lifecycle", "epoch-mutation",
            "broker-boundary", "trace-carrier"))}
        findings.sort(key=lambda f: (order.get(f.rule, 99), f.path, f.line))
        return findings

    def _rule_lock_order(self, trans_locks: Dict[str, Set[str]]
                         ) -> List[Finding]:
        # edge -> exemplar (path, qualname, line)
        edges: Dict[Tuple[str, str], Tuple[str, str, int]] = {}

        def add(a: str, b: str, where: Tuple[str, str, int]) -> None:
            if a == b:
                # reentrant re-entry is legal on RLock/Condition-of-RLock;
                # a plain Lock self-edge is an immediate deadlock
                if self.lock_kinds.get(a) == "Lock":
                    edges.setdefault((a, b), where)
                return
            edges.setdefault((a, b), where)

        for qual, facts in self.facts.items():
            for held, node, line in facts.acquires:
                for h in held:
                    add(h, node, (facts.path, qual, line))
            for held, callee, line in facts.calls:
                if not held:
                    continue
                for target in trans_locks.get(callee, ()):
                    for h in held:
                        add(h, target, (facts.path, qual, line))
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        findings: List[Finding] = []
        for cycle in find_cycles(graph):
            # find_cycles returns nodes in actual edge order, so every
            # consecutive pair (and the closing arc) has an exemplar site
            arc = " -> ".join(cycle + [cycle[0]])
            path, qual, line = edges[(cycle[0], cycle[1 % len(cycle)])]
            findings.append(Finding(
                rule="lock-order-cycle", path=path, qualname=qual, line=line,
                message=f"potential lock-order cycle: {arc}",
                detail=arc))
        return findings

    def _rule_blocking(self, trans_blocking: Dict[str, Set[Tuple[str, int]]],
                       entry_ctx: Dict[str, Set[str]]) -> List[Finding]:
        findings = []
        hot = self.config.hot_locks
        for qual, facts in self.facts.items():
            ctx = entry_ctx.get(qual, set())
            for held, name, line in facts.blocking:
                for lock in (set(held) | ctx) & hot:
                    findings.append(Finding(
                        rule="blocking-under-hot-lock", path=facts.path,
                        qualname=qual, line=line,
                        message=f"blocking call {name}() inside hot lock "
                                f"{lock}",
                        detail=f"{name}@{lock}"))
            for held, callee, line in facts.calls:
                hot_held = (set(held) | ctx) & hot
                if not hot_held:
                    continue
                for name, _bline in sorted(trans_blocking.get(callee, ())):
                    for lock in hot_held:
                        findings.append(Finding(
                            rule="blocking-under-hot-lock", path=facts.path,
                            qualname=qual, line=line,
                            message=f"call to {callee}() while holding hot "
                                    f"lock {lock} reaches blocking "
                                    f"{name}()",
                            detail=f"{callee}:{name}@{lock}"))
        return findings

    def _rule_counters(self, entry_ctx: Dict[str, Set[str]]
                       ) -> List[Finding]:
        findings = []
        for qual, facts in self.facts.items():
            leaf = qual.rsplit(".", 1)[-1]
            if leaf == "__init__" or leaf == "<module>":
                continue
            cls = self.func_class.get(qual)
            mod = self.modules[facts.path.rsplit("/", 1)[-1]
                               .removesuffix(".py")]
            ctx = entry_ctx.get(qual, set())
            for held, form, line in facts.counters:
                owner = self.counter_owner(cls, mod, form)
                if owner is None:
                    continue
                if owner == LOCKFREE:
                    # lock-free-owned counter (epoch.AtomicCounter): ANY
                    # plain attribute mutation breaks the contract — the
                    # sharded cells are the only legal mutation path
                    findings.append(Finding(
                        rule="counter-lock", path=facts.path, qualname=qual,
                        line=line,
                        message=f"lock-free counter {form} mutated as a "
                                f"plain attribute — epoch.AtomicCounter "
                                f"counters mutate only via .add()",
                        detail=f"{form}@{LOCKFREE}"))
                    continue
                if owner not in set(held) | ctx:
                    findings.append(Finding(
                        rule="counter-lock", path=facts.path, qualname=qual,
                        line=line,
                        message=f"counter {form} mutated without its owning "
                                f"lock {owner}",
                        detail=f"{form}@{owner}"))
        return findings

    def _rule_fault_sites(self) -> List[Finding]:
        if self.config.registered_sites is None:
            return []
        registered = self.config.registered_sites
        documented = self.config.documented_sites or set()
        findings = []
        seen: Dict[str, Tuple[str, str, int]] = {}
        for qual, facts in self.facts.items():
            if facts.path.rsplit("/", 1)[-1] == "faults.py":
                continue   # the registry itself
            for site, line in facts.fire_sites:
                if site is None:
                    findings.append(Finding(
                        rule="fault-site", path=facts.path, qualname=qual,
                        line=line,
                        message="faults.fire() with a non-literal site "
                                "cannot be checked against the registry",
                        detail="<dynamic>"))
                    continue
                seen.setdefault(site, (facts.path, qual, line))
                if site not in registered:
                    findings.append(Finding(
                        rule="fault-site", path=facts.path, qualname=qual,
                        line=line,
                        message=f"fault site {site!r} is not registered in "
                                f"faults._SITE_CATEGORY",
                        detail=f"unregistered:{site}"))
                elif site not in documented:
                    findings.append(Finding(
                        rule="fault-site", path=facts.path, qualname=qual,
                        line=line,
                        message=f"fault site {site!r} is not documented in "
                                f"docs/fault-injection.md",
                        detail=f"undocumented:{site}"))
        for site in sorted(registered - set(seen)):
            findings.append(Finding(
                rule="fault-site", path="faults.py", qualname="faults",
                line=0,
                message=f"registered fault site {site!r} has no production "
                        f"fire() call site (dead site)",
                detail=f"dead:{site}"))
        return findings

    def _rule_threads(self) -> List[Finding]:
        findings = []
        # per-class, PER-ATTRIBUTE stop evidence: which self attrs a
        # stop-like method joins (with a timeout) or cancels — local
        # aliases (`thread = self._thread` and the teardown swap
        # `thread, self._thread = self._thread, None`) resolve through
        # the walker's alias map, so `thread.join(timeout=2)` counts for
        # self._thread. Class-wide booleans would let an unjoined thread
        # ride on a sibling's join.
        joined_attrs: Dict[str, Set[str]] = {}
        cancelled_attrs: Dict[str, Set[str]] = {}
        for qual, facts in self.facts.items():
            cls = self.func_class.get(qual)
            if cls is None:
                continue
            leaf = qual.rsplit(".", 1)[-1]
            if leaf not in self.config.stop_methods:
                continue
            for target, has_timeout in facts.join_calls:
                if target.startswith("self.") and has_timeout:
                    joined_attrs.setdefault(cls.qual, set()).add(target[5:])
            for target in facts.cancel_calls:
                if target.startswith("self."):
                    cancelled_attrs.setdefault(cls.qual, set()).add(
                        target[5:])
        for qual, facts in self.facts.items():
            cls = self.func_class.get(qual)
            for site in facts.threads:
                if not site.daemon:
                    findings.append(Finding(
                        rule="thread-lifecycle", path=site.path,
                        qualname=site.qualname, line=site.line,
                        message=f"threading.{site.factory} is not "
                                f"daemonized (daemon=True or "
                                f".daemon = True before start)",
                        detail=f"not-daemon:{site.factory}"))
                joined = joined_attrs.get(cls.qual if cls else "", set())
                cancelled = cancelled_attrs.get(cls.qual if cls else "",
                                                set())
                reaped = site.self_attr is not None and (
                    site.self_attr in joined
                    or (site.factory == "Timer"
                        and site.self_attr in cancelled))
                if not reaped:
                    what = ("joined (with a timeout)"
                            if site.factory == "Thread"
                            else "joined or cancelled")
                    findings.append(Finding(
                        rule="thread-lifecycle", path=site.path,
                        qualname=site.qualname, line=site.line,
                        message=f"threading.{site.factory} is not tracked "
                                f"on an attribute that a stop() path "
                                f"{what}",
                        detail=f"not-joined:{site.factory}"))
        return findings


    def _rule_epoch_mutation(self) -> List[Finding]:
        """No mutation of a published Epoch outside epoch.py's builders:
        epochs are the lock-free read plane, and readers are correct only
        because what they point at can never change — any attribute/dict
        write (or container-mutator call) on an epoch-rooted expression
        in a non-builder module fails the lint. Builder modules
        (config.epoch_modules, default {"epoch"}) are exempt wholesale."""
        findings = []
        exempt = self.config.epoch_modules
        for qual, facts in self.facts.items():
            mod_name = facts.path.rsplit("/", 1)[-1].removesuffix(".py")
            if mod_name in exempt:
                continue
            for target, line in facts.epoch_writes:
                findings.append(Finding(
                    rule="epoch-mutation", path=facts.path, qualname=qual,
                    line=line,
                    message=f"mutation of published epoch state {target!r} "
                            f"outside epoch.py's builders (epochs are "
                            f"immutable: build a successor and publish it)",
                    detail=target))
        return findings

    def _rule_broker_boundary(self) -> List[Finding]:
        """Rule 7: privileged calls — device-node opens (/dev/vfio,
        /dev/iommu), sysfs bind/unbind/driver_override writes, and
        config-space reads — may only appear in the whitelisted seam
        files (config.privileged_modules, matched by path suffix:
        broker.py, discovery.py, the native shim). Everything else must
        route through broker.get_client(), so the privilege boundary
        holds statically, not just by convention. None disables the rule
        (fixture runs without the project whitelist)."""
        allowed = self.config.privileged_modules
        if allowed is None:
            return []
        findings = []
        for qual, facts in self.facts.items():
            if any(facts.path.endswith(suffix) for suffix in allowed):
                continue
            for kind, token, line in facts.priv_calls:
                findings.append(Finding(
                    rule="broker-boundary", path=facts.path,
                    qualname=qual, line=line,
                    message=f"privileged {kind} (evidence: {token}) "
                            f"outside the broker seam — route it through "
                            f"broker.get_client() (docs/design.md "
                            f"'Privilege separation')",
                    detail=f"{kind}:{token}"))
        return findings

    def _stamp_contexts(self, fld: str) -> Set[str]:
        """Functions in whose context a carrier record is guaranteed to
        receive a `rec[fld] = ...` stamp: the function stamps the key
        itself, or (interprocedurally) EVERY resolved caller does —
        the wrapper fixpoint that lets a record builder stay clean when
        its callers thread the context after the call. Least fixpoint,
        so an unresolved or cyclic caller chain stays conservative."""
        callers: Dict[str, Set[str]] = {}
        for qual, facts in self.facts.items():
            for _held, callee, _line in facts.calls:
                callers.setdefault(callee, set()).add(qual)
        stamped = {qual for qual, facts in self.facts.items()
                   if fld in facts.key_stores}
        changed = True
        while changed:
            changed = False
            for qual in self.facts:
                if qual in stamped:
                    continue
                callset = callers.get(qual)
                if callset and callset <= stamped:
                    stamped.add(qual)
                    changed = True
        return stamped

    def _rule_trace_carrier(self) -> List[Finding]:
        """Rule 8: every cross-boundary trace carrier (config.carriers)
        must thread its context field at every crossing, and the
        registry must agree 3-way with docs/observability.md's carrier
        taxonomy table and with the production crossing sites — the
        same usage/registry/docs triangle as the fault-site rule. None
        disables the rule (fixture runs)."""
        if self.config.carriers is None:
            return []
        documented = self.config.documented_carriers or set()
        findings: List[Finding] = []
        seen: Set[str] = set()
        stamped_cache: Dict[str, Set[str]] = {}
        for spec in self.config.carriers:
            for qual, facts in self.facts.items():
                if not spec.in_scope(facts.path):
                    continue
                if spec.kind == "call-kwarg":
                    for leaf, kwargs, argc, none_kwargs, line in \
                            facts.carrier_calls:
                        if leaf != spec.call:
                            continue
                        seen.add(spec.name)
                        threaded = (spec.field in kwargs
                                    and spec.field not in none_kwargs) \
                            or (0 <= spec.arg_index < argc)
                        if not threaded:
                            findings.append(Finding(
                                rule="trace-carrier", path=facts.path,
                                qualname=qual, line=line,
                                message=f"{spec.call}() crosses a traced "
                                        f"boundary without threading "
                                        f"{spec.field}= (carrier "
                                        f"{spec.name}, docs/observability"
                                        f".md 'Trace propagation')",
                                detail=f"unthreaded:{spec.name}"))
                elif spec.kind == "dict-key":
                    for keys, none_keys, const_keys, spread, line in \
                            facts.carrier_dicts:
                        if not spec.markers <= keys or spread \
                                or spec.markers & const_keys:
                            continue
                        seen.add(spec.name)
                        if spec.field in keys and \
                                spec.field not in none_keys:
                            continue
                        if spec.field not in stamped_cache:
                            stamped_cache[spec.field] = \
                                self._stamp_contexts(spec.field)
                        if qual in stamped_cache[spec.field]:
                            continue
                        findings.append(Finding(
                            rule="trace-carrier", path=facts.path,
                            qualname=qual, line=line,
                            message=f"carrier record "
                                    f"{{{', '.join(sorted(spec.markers))}}}"
                                    f" built without its {spec.field!r} "
                                    f"context (carrier {spec.name}, "
                                    f"docs/observability.md "
                                    f"'Trace propagation')",
                            detail=f"unthreaded:{spec.name}"))
                elif spec.kind == "header-store":
                    if spec.field in facts.key_stores:
                        seen.add(spec.name)
        registered = {spec.name for spec in self.config.carriers}
        for name in sorted(registered - documented):
            findings.append(Finding(
                rule="trace-carrier", path="docs/observability.md",
                qualname="trace-propagation", line=0,
                message=f"carrier {name!r} is registered "
                        f"(tsalint config CARRIERS) but missing from the "
                        f"propagation taxonomy table",
                detail=f"undocumented:{name}"))
        for name in sorted(documented - registered):
            findings.append(Finding(
                rule="trace-carrier", path="docs/observability.md",
                qualname="trace-propagation", line=0,
                message=f"carrier {name!r} is documented in the "
                        f"propagation taxonomy table but not registered "
                        f"in the tsalint config CARRIERS",
                detail=f"undeclared:{name}"))
        for name in sorted(registered - seen):
            findings.append(Finding(
                rule="trace-carrier", path="docs/observability.md",
                qualname="trace-propagation", line=0,
                message=f"registered carrier {name!r} has no production "
                        f"crossing site (dead carrier)",
                detail=f"dead:{name}"))
        return findings


def analyze_sources(sources: Sequence[Tuple[str, str]],
                    config: LintConfig) -> List[Finding]:
    analyzer = Analyzer(config)
    for path, text in sources:
        analyzer.add_source(path, text)
    return analyzer.analyze()


def analyze_paths(paths: Sequence[str], config: LintConfig) -> List[Finding]:
    sources = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            sources.append((path.replace("\\", "/"), f.read()))
    return analyze_sources(sources, config)
