#!/usr/bin/env python3
"""KubeVirt externalResourceProvider contract — locally-runnable subset.

The full contract test is the kind-based KubeVirt stage in
scripts/e2e_kind.sh (real kubelet, real virt-controller). This build
environment ships no docker/kind/kubectl, so this runner executes the
CLOSEST LOCAL SUBSET against the REAL plugin daemon:

  real daemon (subprocess)  <-- gRPC -->  DeviceManagerSim (faithful
                                          kubelet devicemanager)
                                             ^
  simulated virt-controller: renders the    |
  virt-launcher "compute" container from ---+
  manifests/e2e/vmi-tpu-e2e.yaml + the same
  permittedHostDevices patch e2e_kind.sh applies

What is REAL here: the plugin daemon (discovery, registration,
ListAndWatch, GetPreferredAllocation, Allocate over unix-socket gRPC), the
kubelet-side admission semantics (tests/kubelet_sim.py mirrors the
devicemanager: version/endpoint checks, preferred-allocation validation,
admission lock), and the fixture host tree (scripts/make_fixture_host.py).

What is SIMULATED: virt-controller's pod rendering and virt-launcher's
env consumption, each implemented from the KubeVirt contract the
reference plugin serves (reference: examples/kubevirt-featuregate-cm.yaml:
10-18 — permittedHostDevices + externalResourceProvider: true delegates
advertisement to the device plugin; examples/vmi-gpu.yaml:17-19 — the VMI
requests the resource via devices.gpus; generic_device_plugin.go:58,
420-424 — virt-launcher reads PCI_RESOURCE_<RESOURCE_NAME> to pick the
PCI devices for QEMU).

Output: docs/e2e_kubevirt_r05.log; exit 0 iff every assertion held.
"""
import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import grpc  # noqa: E402
import yaml  # noqa: E402

from make_fixture_host import build as build_fixture  # noqa: E402
from kubelet_sim import DeviceManagerSim  # noqa: E402
from test_dra import FakeApiServer  # noqa: E402

# The same whitelist e2e_kind.sh patches into the KubeVirt CR.
PERMITTED_HOST_DEVICES = {
    "pciHostDevices": [{
        "pciVendorSelector": "1AE0:0062",
        "resourceName": "cloud-tpus.google.com/v4",
        "externalResourceProvider": True,
    }]
}

LOG_LINES = []


def log(msg):
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} {msg}"
    print(line, flush=True)
    LOG_LINES.append(line)


def fail(msg):
    log(f"FAIL: {msg}")
    _write_log()
    sys.exit(1)


def _write_log():
    path = os.path.join(REPO, "docs", "e2e_kubevirt_r05.log")
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(LOG_LINES) + "\n")


def render_virt_launcher(vmi, permitted):
    """virt-controller's rendering rule for externalResourceProvider GPUs.

    For each spec.domain.devices.gpus[] entry whose deviceName is
    whitelisted in permittedHostDevices with externalResourceProvider:
    true, KubeVirt adds the resource to the compute container's
    requests/limits (quantity = number of entries naming it) and does NOT
    spawn its own device-plugin — advertisement and Allocate stay with the
    external plugin (this repo). A deviceName NOT in the whitelist is an
    admission error (the VMI is rejected by the kubevirt webhook).
    """
    allowed = {d["resourceName"]: d
               for d in permitted.get("pciHostDevices", [])}
    wanted = {}
    for gpu in (vmi["spec"]["domain"]["devices"].get("gpus") or []):
        name = gpu["deviceName"]
        if name not in allowed:
            fail(f"VMI requests {name} which is not in "
                 f"permittedHostDevices — kubevirt would reject the VMI")
        if not allowed[name].get("externalResourceProvider"):
            fail(f"{name} lacks externalResourceProvider: true — KubeVirt "
                 "would try to serve it with its OWN device plugin")
        wanted[name] = wanted.get(name, 0) + 1
    return {
        "name": "compute",
        "resources": {"limits": dict(wanted), "requests": dict(wanted)},
    }


def main():
    root = tempfile.mkdtemp(prefix="kv-e2e-", dir="/tmp")
    log(f"fixture host tree at {root} (scripts/make_fixture_host.py)")
    build_fixture(root)

    kubelet_dir = os.path.join(root, "device-plugins")
    os.makedirs(kubelet_dir, exist_ok=True)
    sim = DeviceManagerSim(kubelet_dir)
    log("kubelet devicemanager sim listening (tests/kubelet_sim.py)")

    apiserver = FakeApiServer()
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               NODE_NAME="kv-e2e-node")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "tpu_device_plugin", "--root", root,
         "--dra", "--api-server", apiserver.url, "-v"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    log("real plugin daemon launched (python -m tpu_device_plugin --dra)")

    try:
        resource = "cloud-tpus.google.com/v4"
        if not sim.wait_for_resource(resource, timeout=30):
            fail(f"plugin never registered {resource} with the kubelet")
        log(f"plugin registered {resource} (Registration gRPC, real socket)")
        if not sim.wait_for_allocatable(resource, 4, timeout=15):
            fail("node allocatable never reached 4 chips")
        log("node allocatable: cloud-tpus.google.com/v4 = 4 "
            "(ListAndWatch, matches e2e_kind.sh's node assert)")

        with open(os.path.join(REPO, "manifests/e2e/vmi-tpu-e2e.yaml"),
                  encoding="utf-8") as f:
            vmi = yaml.safe_load(f)
        log("VMI manifests/e2e/vmi-tpu-e2e.yaml loaded "
            f"(devices.gpus -> {vmi['spec']['domain']['devices']['gpus']})")

        compute = render_virt_launcher(vmi, PERMITTED_HOST_DEVICES)
        req = compute["resources"]["limits"]
        log(f"virt-controller render: compute container requests {req}")
        if req != {resource: 1}:
            fail(f"render produced {req}, want {{{resource!r}: 1}}")

        # kubelet admission: the devicemanager picks devices, calls
        # GetPreferredAllocation + Allocate on the REAL daemon
        try:
            ids, resp = sim.admit_pod(resource, req[resource])
        except Exception as exc:  # ConformanceError or RpcError
            fail(f"virt-launcher pod admission failed: {exc}")
        log(f"virt-launcher pod ADMITTED; kubelet granted {ids}")

        cresp = resp.container_responses[0]
        envs = dict(cresp.envs)
        key = "PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4"
        if key not in envs:
            fail(f"Allocate response lacks {key} (envs: {sorted(envs)})")
        bdfs = envs[key].split(",")
        log(f"env contract: {key}={envs[key]}")

        # virt-launcher's consumption: each env entry must be a PCI
        # address resolvable on the host (it becomes a QEMU hostdev)
        for bdf in bdfs:
            if not re.fullmatch(r"[0-9a-f]{4}:[0-9a-f]{2}:[0-9a-f]{2}\.[0-7]",
                                bdf):
                fail(f"env entry {bdf!r} is not a PCI BDF")
            if not os.path.isdir(
                    os.path.join(root, "sys/bus/pci/devices", bdf)):
                fail(f"env BDF {bdf} does not exist in host sysfs")
        # group expansion: the fixture's group 7 holds two chips, so a
        # 1-chip grant expands to its full IOMMU group iff a group-7 chip
        # was picked
        log(f"virt-launcher would assign {len(bdfs)} PCI hostdev(s) to "
            f"QEMU: {bdfs}")

        mounts = [d.container_path for d in cresp.devices]
        if "/dev/vfio/vfio" not in mounts:
            fail(f"/dev/vfio/vfio missing from device mounts: {mounts}")
        if not any(re.fullmatch(r"/dev/vfio/\d+", m) for m in mounts):
            fail(f"no per-IOMMU-group /dev/vfio/<group> mount: {mounts}")
        log(f"device mounts OK: {mounts}")

        # ---- DRA leg: KubeVirt's forward path (structured resources).
        # The same daemon publishes a ResourceSlice; a scheduler-sim
        # allocates one chip to a claim, and the kubelet-side prepare is
        # driven over the daemon's REAL dra.sock. The prepared claim must
        # carry the same PCI_RESOURCE env contract through its CDI spec.
        from tpu_device_plugin.config import Config
        from tpu_device_plugin.kubeletapi import draapi, drapb

        cfg = Config().with_root(root)
        # matches DraDriver's default (dra.py: cfg.cdi_spec_dir fallback)
        cdi_dir = cfg.cdi_spec_dir or os.path.join(root, "var/run/cdi")
        deadline = time.time() + 30
        while time.time() < deadline and not apiserver.slices:
            time.sleep(0.25)
        if not apiserver.slices:
            fail("daemon never published a ResourceSlice")
        slice_obj = next(iter(apiserver.slices.values()))
        slice_devs = [d["name"] for d in slice_obj["spec"]["devices"]]
        if not slice_devs:
            fail("published ResourceSlice carries zero devices")
        chip = slice_devs[0]
        log(f"ResourceSlice published; scheduler-sim allocates {chip!r}")
        apiserver.add_claim("default", "vmi-tpu-claim", "uid-kv-1",
                            "cloud-tpus.google.com",
                            [{"device": chip}])
        dra_sock = os.path.join(cfg.dra_plugins_path,
                                "cloud-tpus.google.com", "dra.sock")
        claim = drapb.Claim(namespace="default", name="vmi-tpu-claim",
                            uid="uid-kv-1")
        try:
            with grpc.insecure_channel(f"unix://{dra_sock}") as ch:
                stub = draapi.DraPluginStub(ch)
                dresp = stub.NodePrepareResources(
                    drapb.NodePrepareResourcesRequest(claims=[claim]),
                    timeout=10)
                out = dresp.claims["uid-kv-1"]
                if out.error:
                    fail(f"DRA prepare failed: {out.error}")
                if len(out.devices) != 1:
                    fail(f"DRA prepare returned {len(out.devices)} devices")
                log(f"DRA claim PREPARED over dra.sock: {chip!r} "
                    f"(cdi {list(out.devices[0].cdi_device_ids)})")
                specs = glob.glob(
                    os.path.join(cdi_dir, "*claim-uid-kv-1.json"))
                if len(specs) != 1:
                    fail(f"expected one per-claim CDI spec, found {specs}")
                with open(specs[0], encoding="utf-8") as f:
                    spec = json.load(f)
                spec_envs = [
                    e for d in spec.get("devices", [])
                    for e in d.get("containerEdits", {}).get("env", [])]
                if not any(e.startswith(key + "=") and "0000:" in e
                           for e in spec_envs):
                    fail(f"per-claim CDI spec lacks the {key} env: "
                         f"{spec_envs}")
                log(f"per-claim CDI spec carries the env contract: "
                    f"{[e for e in spec_envs if e.startswith(key)]}")
                uresp = stub.NodeUnprepareResources(
                    drapb.NodeUnprepareResourcesRequest(claims=[claim]),
                    timeout=10)
                if uresp.claims["uid-kv-1"].error:
                    fail(f"DRA unprepare failed: "
                         f"{uresp.claims['uid-kv-1'].error}")
                if glob.glob(os.path.join(cdi_dir, "*claim-uid-kv-1.json")):
                    fail("CDI spec not removed on unprepare")
                log("DRA claim UNPREPARED; per-claim CDI spec removed")
        except grpc.RpcError as exc:
            fail(f"DRA leg RPC failed: {exc.code()}: {exc.details()}")

        log("KUBEVIRT CONTRACT PASS: virt-launcher admitted with the TPU "
            "resource + PCI_RESOURCE env on BOTH the classic device-plugin "
            "path and the DRA claim path (LOCAL SUBSET: real daemon + "
            "faithful kubelet sim + simulated virt-controller render; "
            "kind/docker unavailable in this build env — the full-cluster "
            "stage remains scripts/e2e_kind.sh KUBEVIRT=1)")
        _write_log()
        return 0
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()
        apiserver.stop()


if __name__ == "__main__":
    sys.exit(main())
