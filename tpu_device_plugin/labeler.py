"""Node topology labeler — publish per-node TPU facts for multi-node slices.

SURVEY.md §7 stage 8 / BASELINE config 5: a v5p-16 slice spans hosts, and the
scheduler (or the human writing VMI templates) needs per-node facts —
generation, chip count, host torus shape — to place one VMI per host without
hand-rolled nodeSelectors. The reference has no analogue (it predates NFD);
this is TPU-first capability on top of the same DaemonSet.

Two publication paths, both dependency-free:

1. **Node labels** via the API server: a strategic-merge PATCH of
   `metadata.labels` on this node object, authenticated with the pod's
   service-account token (stdlib urllib; no kubernetes client package).
   The DaemonSet needs a Role allowing `patch` on `nodes` and the node name
   from the downward API (`NODE_NAME`).
2. **NFD feature file**: `key=value` lines under
   `/etc/kubernetes/node-feature-discovery/features.d/`, picked up by
   node-feature-discovery's local source for clusters that already run NFD
   (no extra RBAC needed).

Facts published (keys under the resource namespace):

    cloud-tpus.google.com/<gen>.chips  = "4"        per discovered generation
    cloud-tpus.google.com/<gen>.torus  = "2x2x1"    host-local ICI torus
    cloud-tpus.google.com/vtpu.<type>  = "8"        per partition type
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Dict, Optional

from .config import Config
from .kubeapi import SA_DIR, ApiClient, ApiError, in_cluster_server
from .naming import GenerationInfo
from .registry import Registry

log = logging.getLogger(__name__)


def node_facts(cfg: Config, registry: Registry,
               generations: Dict[str, GenerationInfo]) -> Dict[str, str]:
    """Label map describing this node's TPU inventory."""
    facts: Dict[str, str] = {}
    ns = cfg.resource_namespace
    for model, devs in sorted(registry.devices_by_model.items()):
        info = generations.get(model)
        gen = info.name if info else f"tpu-{model}"
        facts[f"{ns}/{gen}.chips"] = str(len(devs))
        if info is not None:
            facts[f"{ns}/{gen}.torus"] = "x".join(
                str(d) for d in info.host_topology)
    for type_name, parts in sorted(registry.partitions_by_type.items()):
        facts[f"{ns}/vtpu.{type_name}"] = str(len(parts))
    return facts


def write_feature_file(path: str, facts: Dict[str, str]) -> bool:
    """Atomically write the NFD local-source feature file; False on failure."""
    tmp = None
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   suffix=".tmp")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            for key in sorted(facts):
                f.write(f"{key}={facts[key]}\n")
        os.replace(tmp, path)
    except OSError as exc:
        log.error("could not write feature file %s: %s", path, exc)
        if tmp is not None:
            # NFD parses every file in features.d — never leave a half-
            # written tmp behind
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False
    log.info("wrote %d node facts to %s", len(facts), path)
    return True


class NodeLabeler:
    """Publishes node facts; safe to call repeatedly (idempotent PATCH)."""

    def __init__(
        self,
        node_name: Optional[str] = None,
        api_server: Optional[str] = None,
        token_path: str = os.path.join(SA_DIR, "token"),
        ca_path: str = os.path.join(SA_DIR, "ca.crt"),
        feature_file: Optional[str] = None,
        require_api: bool = False,
        label_prefix: str = "cloud-tpus.google.com",
    ) -> None:
        self.node_name = node_name or os.environ.get("NODE_NAME")
        # only an explicit api_server or --label-node (require_api) may use
        # the API path; ambient in-cluster env must NOT trigger PATCHes in
        # feature-file-only mode (no RBAC there — every attempt would 403
        # and poison the retry loop)
        self._api_requested = api_server is not None
        self.api_server = api_server or self._in_cluster_server()
        self.token_path = token_path
        self.ca_path = ca_path
        self.feature_file = feature_file
        # --label-node was explicitly requested: a missing NODE_NAME/API
        # server must warn even when a feature file is also configured
        self.require_api = require_api
        self.label_prefix = label_prefix
        self._published_keys: set = set()
        self._api_client: Optional[ApiClient] = None

    @staticmethod
    def _in_cluster_server() -> Optional[str]:
        return in_cluster_server()

    def _client(self) -> ApiClient:
        # one client for the labeler's lifetime: the keep-alive pool only
        # pays off when the publish-retry PATCHes ride the same client
        if self._api_client is None:
            self._api_client = ApiClient(self.api_server,
                                         token_path=self.token_path,
                                         ca_path=self.ca_path)
        return self._api_client

    def publish(self, facts: Dict[str, str]) -> bool:
        """Write the feature file and/or PATCH node labels; True only when
        every *configured* path succeeded (False ⇒ caller should retry)."""
        ok = True
        any_path = False
        if self.feature_file:
            any_path = True
            ok = write_feature_file(self.feature_file, facts) and ok
        want_api = self.require_api or self._api_requested
        if want_api and self.node_name and self.api_server:
            any_path = True
            ok = self._patch_labels(facts) and ok
        elif self.require_api:
            log.warning("node labeling requested but %s is missing; labels "
                        "NOT published",
                        "NODE_NAME" if not self.node_name else "API server")
            ok = False
        if not any_path and not self.require_api:
            log.warning("labeler has neither a feature file nor node name + "
                        "API server; nothing published")
            return False
        return ok

    def _patch_labels(self, facts: Dict[str, str]) -> bool:
        # Strategic merge only adds/overwrites; facts for inventory that
        # disappeared (or that a previous pod incarnation published) must be
        # nulled out explicitly, so fetch our namespaced keys first.
        labels: Dict[str, Optional[str]] = dict(facts)
        stale = (self._published_keys | self._live_label_keys()) - set(facts)
        for key in stale:
            labels[key] = None
        path = f"/api/v1/nodes/{self.node_name}"
        try:
            self._client().patch_strategic(
                path, {"metadata": {"labels": labels}})
        except ApiError as exc:
            log.error("node label PATCH %s failed: %s", path, exc)
            return False
        self._published_keys = set(facts)
        log.info("labeled node %s with %d TPU facts (%d stale removed)",
                 self.node_name, len(facts), len(stale))
        return True

    def _live_label_keys(self) -> set:
        """This labeler's namespaced label keys currently on the node (so a
        restarted pod can prune labels a previous incarnation published).
        Empty set on any failure — pruning then degrades to session memory."""
        try:
            node = self._client().get_json(f"/api/v1/nodes/{self.node_name}")
        except (ApiError, ValueError) as exc:
            log.debug("node GET for label pruning failed: %s", exc)
            return set()
        labels = (node.get("metadata") or {}).get("labels") or {}
        return {k for k in labels if k.startswith(self.label_prefix + "/")}
