"""Line coverage via stdlib sys.monitoring (PEP 669) — no pytest-cov needed.

The build image ships no coverage/pytest-cov, and the reference enforces
coverage in CI (reference: Makefile:59-61 + .github/workflows/golang.yml
Coveralls job). This harness measures line coverage of `tpu_device_plugin`
with the 3.12 monitoring API: a LINE callback records the first hit per
location and then DISABLEs that location, so steady-state overhead is near
zero. Executable lines come from compiling each source file and walking
`co_lines()` over the nested code objects — the same universe coverage.py
uses, minus its branch/exclusion pragmas, so numbers are comparable but not
identical.

Usage:  python scripts/stdlib_coverage.py --fail-under 75 [--json-out f]
            [-- pytest args...]

Limitations: code running in subprocesses (multi-node rendezvous tests,
daemon-spawning tests) is not traced — identical to a default pytest-cov
setup without COVERAGE_PROCESS_START.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tpu_device_plugin")


def executable_lines(path: str) -> set:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    code = compile(src, path, "exec")
    lines = set()
    stack = [code]
    while stack:
        c = stack.pop()
        for _start, _end, line in c.co_lines():
            if line is not None and line > 0:
                lines.add(line)
        for const in c.co_consts:
            if isinstance(const, type(code)):
                stack.append(const)
    return lines


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fail-under", type=float, default=0.0)
    ap.add_argument("--json-out", default=None)
    ap.add_argument("pytest_args", nargs="*",
                    help="args after -- go to pytest (default: tests/ -q)")
    args = ap.parse_args()

    mon = sys.monitoring
    tool = mon.COVERAGE_ID
    prefix = PKG + os.sep
    hits: dict = {}

    def on_line(code, line):
        fn = code.co_filename
        if fn.startswith(prefix):
            hits.setdefault(fn, set()).add(line)
        # first hit recorded; disable this location either way so non-package
        # code costs one event total
        return mon.DISABLE

    mon.use_tool_id(tool, "stdlib-cov")
    mon.register_callback(tool, mon.events.LINE, on_line)
    mon.set_events(tool, mon.events.LINE)
    try:
        import pytest
        rc = pytest.main(args.pytest_args or ["tests/", "-q"])
    finally:
        mon.set_events(tool, 0)
        mon.free_tool_id(tool)
    if rc != 0:
        print(f"stdlib-cov: pytest failed (rc={rc}); not scoring coverage")
        return int(rc)

    total_exec = total_hit = 0
    per_file = {}
    for dirpath, _dirs, files in os.walk(PKG):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            want = executable_lines(path)
            if not want:
                continue
            got = hits.get(path, set()) & want
            total_exec += len(want)
            total_hit += len(got)
            rel = os.path.relpath(path, REPO)
            per_file[rel] = round(100.0 * len(got) / len(want), 1)
    pct = 100.0 * total_hit / total_exec if total_exec else 0.0
    for rel in sorted(per_file, key=per_file.get):
        print(f"{per_file[rel]:6.1f}%  {rel}")
    print(f"TOTAL {pct:.1f}% ({total_hit}/{total_exec} lines)")
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump({"total_pct": round(pct, 1), "files": per_file}, f,
                      indent=1, sort_keys=True)
    if pct < args.fail_under:
        print(f"FAIL: coverage {pct:.1f}% < required {args.fail_under}%")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
