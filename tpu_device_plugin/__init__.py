"""tpu_device_plugin — a TPU-native KubeVirt device plugin.

A Kubernetes device plugin (DaemonSet) that discovers Google Cloud TPU PCIe
endpoints bound to vfio-pci (for PCI passthrough into KubeVirt VMIs) plus
`/dev/accel*` character devices, advertises them to the kubelet as
`cloud-tpus.google.com/<generation>` extended resources, serves the kubelet
Device Plugin gRPC API v1beta1 over unix sockets, prefers ICI-adjacent chip
groups in `GetPreferredAllocation`, and health-monitors devices with an
inotify watcher plus a native libtpu liveness shim.

Capability parity target: NVIDIA/kubevirt-gpu-device-plugin (see SURVEY.md).
Architecture is TPU-first, not a port: discovery models ICI torus topology,
allocation keeps slices contiguous, and the guest-side validator
(`tpu_device_plugin.validator`) proves a passed-through slice is usable by
running an SPMD JAX workload over `jax.devices()`.
"""

__version__ = "0.1.0"
