"""CDI support: spec generation and CDIDevice names in Allocate."""

import json
import os
from dataclasses import replace

import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin import cdi
from tpu_device_plugin.allocate import allocate_response
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.lifecycle import PluginManager


@pytest.fixture
def host2(tmp_path):
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", accel_index=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12"))
    return host


def test_spec_contents(host2, tmp_path):
    cfg = replace(Config().with_root(host2.root),
                  cdi_spec_dir=str(tmp_path / "cdi"))
    registry, _ = discover_passthrough(cfg)
    devs = registry.devices_by_model["0062"]
    path = cdi.write_spec(cfg, cdi.device_entries(cfg, devs), "v4")
    assert path and os.path.exists(path)
    spec = json.loads(open(path).read())
    assert spec["cdiVersion"] == "0.6.0"
    assert spec["kind"] == "cloud-tpus.google.com/tpu"
    assert spec["containerEdits"]["deviceNodes"][0]["path"] == "/dev/vfio/vfio"
    by_name = {d["name"]: d for d in spec["devices"]}
    nodes4 = by_name["0000:00:04.0"]["containerEdits"]["deviceNodes"]
    assert {n["path"] for n in nodes4} == {"/dev/vfio/11", "/dev/accel0"}
    nodes5 = by_name["0000:00:05.0"]["containerEdits"]["deviceNodes"]
    assert {n["path"] for n in nodes5} == {"/dev/vfio/12"}


def test_write_spec_disabled_returns_none(host2):
    cfg = Config().with_root(host2.root)
    registry, _ = discover_passthrough(cfg)
    assert cdi.write_spec(
        cfg, cdi.device_entries(cfg, registry.devices_by_model["0062"]),
        "v4") is None


def test_allocate_includes_cdi_names_when_enabled(host2, tmp_path):
    cfg = replace(Config().with_root(host2.root),
                  cdi_spec_dir=str(tmp_path / "cdi"))
    registry, _ = discover_passthrough(cfg)
    req = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"])])
    resp = allocate_response(cfg, registry, "v4", req)
    cresp = resp.container_responses[0]
    assert [c.name for c in cresp.cdi_devices] == \
        ["cloud-tpus.google.com/tpu=0000:00:04.0"]
    # classic specs + env stay for non-CDI kubelets
    assert cresp.devices and cresp.envs


def test_allocate_no_cdi_by_default(host2):
    cfg = Config().with_root(host2.root)
    registry, _ = discover_passthrough(cfg)
    req = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"])])
    resp = allocate_response(cfg, registry, "v4", req)
    assert len(resp.container_responses[0].cdi_devices) == 0


def test_manager_writes_specs_at_startup(short_root, tmp_path):
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = replace(Config().with_root(host.root),
                  cdi_spec_dir=str(tmp_path / "cdi"))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kubelet.wait_for(1)
        files = os.listdir(cfg.cdi_spec_dir)
        assert files == ["cloud-tpus.google.com-v4.json"]
    finally:
        manager.stop()
        kubelet.stop()


def test_cdi_names_suppressed_when_spec_write_fails(short_root, tmp_path):
    """Unwritable spec dir: plugin serves classic DeviceSpecs, no CDI names."""
    import grpc
    from tpu_device_plugin import kubeletapi as api
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    blocked = tmp_path / "blocked"
    blocked.write_text("")  # a FILE, so makedirs/mkstemp under it fails
    cfg = replace(Config().with_root(host.root),
                  cdi_spec_dir=str(blocked / "cdi"))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kubelet.wait_for(1)
        sock = os.path.join(cfg.device_plugin_path, "tpukubevirt-v4.sock")
        with grpc.insecure_channel(f"unix://{sock}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"])]),
                timeout=5)
            cresp = resp.container_responses[0]
            assert len(cresp.cdi_devices) == 0   # no unresolvable names
            assert cresp.devices                 # classic path intact
    finally:
        manager.stop()
        kubelet.stop()


def test_mdev_partitions_get_no_cdi_names(short_root, tmp_path):
    """An mdev's VFIO group is allocate-time knowledge (destroy/recreate under
    the same UUID moves it); freezing it into a CDI spec at startup would hand
    the kubelet a stale node. mdevs ride the classic DeviceSpec path only."""
    import grpc
    from tpu_device_plugin import kubeletapi as api
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_mdev("uuid-1", "TPU vhalf", "0000:00:04.0", iommu_group="21")
    cfg = replace(Config().with_root(host.root),
                  cdi_spec_dir=str(tmp_path / "cdi"))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kubelet.wait_for(2)
        files = sorted(os.listdir(cfg.cdi_spec_dir))
        # vtpu spec files are namespaced like the vtpu socket, so a partition
        # type named after a generation can never clobber the passthrough spec
        assert files == ["cloud-tpus.google.com-v4.json",
                         "cloud-tpus.google.com-vtpu-TPU_vhalf.json"]
        spec = json.loads(open(os.path.join(
            cfg.cdi_spec_dir, "cloud-tpus.google.com-vtpu-TPU_vhalf.json")).read())
        assert spec["devices"] == []  # no frozen mdev group nodes
        sock = os.path.join(cfg.device_plugin_path,
                            "tpukubevirt-vtpu-TPU_vhalf.sock")
        with grpc.insecure_channel(f"unix://{sock}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["uuid-1"])]),
                timeout=5)
            cresp = resp.container_responses[0]
            assert [c.name for c in cresp.cdi_devices] == []
            # classic path carries the injection, resolved live
            assert [d.container_path for d in cresp.devices] == \
                ["/dev/vfio/vfio", "/dev/vfio/21"]
    finally:
        manager.stop()
        kubelet.stop()


def test_accel_partitions_get_cdi_names(short_root, tmp_path):
    """Logical partitions with a static accel node DO get CDI entries+names."""
    import grpc
    from tpu_device_plugin import kubeletapi as api
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"per_core": True}))
    cfg = replace(Config().with_root(host.root),
                  cdi_spec_dir=str(tmp_path / "cdi"),
                  partition_config_path=str(pc))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kubelet.wait_for(1)
        sock = os.path.join(cfg.device_plugin_path,
                            "tpukubevirt-vtpu-v4-core.sock")
        with grpc.insecure_channel(f"unix://{sock}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(
                        devices_ids=["0000:00:04.0-core0"])]),
                timeout=5)
            names = [c.name for c in resp.container_responses[0].cdi_devices]
            assert names == ["cloud-tpus.google.com/tpu=0000:00:04.0-core0"]
    finally:
        manager.stop()
        kubelet.stop()


def test_partition_cdi_entries_carry_node_permissions(tmp_path):
    """The CDI spec must carry --partition-node-permissions: without it a
    CDI-aware kubelet injects the accel node with runtime-default (rwm)
    access, bypassing the operator's read-only policy."""
    from tpu_device_plugin.cdi import partition_entries
    from tpu_device_plugin.registry import TpuPartition
    cfg = replace(Config().with_root(str(tmp_path)),
                  partition_node_permissions="r")
    parts = [TpuPartition(uuid="u0", type_name="v4-core",
                          parent_bdf="0000:00:04.0", numa_node=0,
                          provider="logical", accel_index=0)]
    entries = partition_entries(cfg, parts)
    node = entries[0]["containerEdits"]["deviceNodes"][0]
    assert node["permissions"] == "r"


def test_prune_stale_specs(host2, tmp_path):
    cfg = replace(Config().with_root(host2.root),
                  cdi_spec_dir=str(tmp_path / "cdi"))
    registry, _ = discover_passthrough(cfg)
    devs = registry.devices_by_model["0062"]
    kept = cdi.write_spec(cfg, cdi.device_entries(cfg, devs), "v4")
    stale = cdi.write_spec(cfg, [], "v99")
    foreign = os.path.join(cfg.cdi_spec_dir, "other-vendor.json")
    with open(foreign, "w") as f:
        f.write("{}")
    cdi.prune_specs(cfg, [kept])
    left = sorted(os.listdir(cfg.cdi_spec_dir))
    assert os.path.basename(kept) in left
    assert os.path.basename(stale) not in left
    assert "other-vendor.json" in left  # never touches foreign specs


# --------------------------------------------------- failure degradation


def test_write_spec_unwritable_dir_degrades_to_none(host2, tmp_path):
    """A failed spec write returns None (the resource then stays on the
    classic DeviceSpec path) instead of raising into plugin startup."""
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("file where the spec dir should be")
    cfg = replace(Config().with_root(host2.root),
                  cdi_spec_dir=str(blocker))
    assert cdi.write_spec(cfg, [], "v5e") is None


def test_write_spec_replace_failure_cleans_tmp(host2, tmp_path,
                                               monkeypatch):
    """os.replace failing mid-write must return None AND remove the temp
    file — a litter of .tmp files in /var/run/cdi would confuse CDI-spec
    scanners."""
    cfg = replace(Config().with_root(host2.root),
                  cdi_spec_dir=str(tmp_path / "cdi"))
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("simulated replace failure")

    monkeypatch.setattr(os, "replace", boom)
    assert cdi.write_spec(cfg, [], "v5e") is None
    monkeypatch.setattr(os, "replace", real_replace)
    assert [f for f in os.listdir(tmp_path / "cdi")
            if f.endswith(".tmp")] == []


def test_prune_specs_missing_dir_is_quiet(host2, tmp_path):
    cfg = replace(Config().with_root(host2.root),
                  cdi_spec_dir=str(tmp_path / "never-created"))
    cdi.prune_specs(cfg, [])          # must not raise


def test_prune_specs_unlink_failure_is_nonfatal(host2, tmp_path,
                                                monkeypatch):
    """One stubborn stale spec must not abort pruning (or the plugin)."""
    cfg = replace(Config().with_root(host2.root),
                  cdi_spec_dir=str(tmp_path / "cdi"))
    os.makedirs(cfg.cdi_spec_dir, exist_ok=True)
    stale = os.path.join(cfg.cdi_spec_dir,
                         "cloud-tpus.google.com-stale.json")
    with open(stale, "w") as f:
        f.write("{}")

    def boom(path):
        raise OSError("simulated unlink failure")

    monkeypatch.setattr(os, "unlink", boom)
    cdi.prune_specs(cfg, [])          # must not raise
