"""Injectable fault points: named failure sites, armed by tests or env.

The robustness claims in this repo (plugins survive kubelet restarts,
devices recover when nodes return, apiserver blips never duplicate writes)
used to be exercised only by whatever failures a test could induce from
outside the process — deleting sockets, killing fake servers. This
registry lets failures be injected *at the seam where they occur* with a
deterministic, seedable schedule, so tests/test_chaos.py can script
"registration fails 3 times, then works" without monkeypatching internals.

A fault point is a named call site that the production code consults:

    faults.fire("kubelet.register")       # raising site
    if faults.fire("dra.publish"): ...    # value site: True = fault fired

When nothing is armed (production), `fire()` is a single module-global
boolean check — no locks, no dict lookups.

Instrumented sites and their semantics:

  kubelet.register   raising — register() fails with the armed exception
  kubeapi.request    raising — the HTTP request fails before the wire
                     (the ApiClient wraps non-ApiError kinds as ApiError)
  native.probe       value   — the liveness probe reports the chip dead
  inotify.poll       value   — the poll's inotify events are dropped
                     (exercises the periodic existence-scan reconciliation)
  dra.publish        value   — the slice publish fails as if the API
                     server had refused it (exercises the republish retry)
  checkpoint.write   raising — the group-commit checkpoint write fails
                     before reaching disk (every claim waiting on that
                     commit window must error, roll back, and never be
                     silently ACKed)
  pci.hotunplug      value   — presence evidence for a device is
                     inverted: the lifecycle FSM reads the next
                     observation as a PCIe surprise removal (allocated
                     devices orphan their claims)
  pci.replug         value   — the replug identity reconciliation reads
                     as an identity swap (different silicon in the same
                     slot); readmission happens under a NEW identity and
                     prior claims stay orphaned
  migration.handoff  raising — emitting the migration handoff record
                     during NodeUnprepareResources fails before the
                     checkpoint mutation: the unprepare errors per-claim
                     and the kubelet retry re-runs it (exactly-once)
  kubeapi.watch      raising — the watch stream read fails mid-stream
                     (armed kind=error models a stream BREAK, kind=
                     timeout a STALL that tripped the read deadline);
                     the reflector's recovery is backoff + relist
  kubeapi.watch.dup  value   — the next watch event is delivered TWICE
                     (at-least-once pressure: every downstream handler
                     must be idempotent)
  kubeapi.watch.stale value  — the reflector resumes its next watch from
                     a resourceVersion the server has long compacted:
                     the server answers 410 Gone and the reflector must
                     relist without losing or double-applying events
  broker.ipc         value   — the next broker crossing (broker.py
                     client) fails as if the privileged broker process
                     had died: the caller gets the typed
                     BrokerUnavailable, the serving daemon degrades to
                     per-claim/per-RPC unavailable errors, recovery is
                     respawn + handshake
  policy.hook        raising — the operator policy hook raises (or, with
                     kind=timeout, is "slow") inside the engine's
                     guarded invocation: the engine keeps builtin
                     behavior, counts the failure, and trips the hook's
                     circuit breaker after repetition

Arming — programmatic:

    faults.arm("kubelet.register", kind="error", count=3)
    with faults.injected("dra.publish", count=1): ...

or via environment (read by cli.main at startup):

    TDP_FAULTS='kubelet.register:error:count=3,kubeapi.request:timeout:p=0.5'
    TDP_FAULTS_SEED=1337

Spec grammar: `site[:kind][:count=N][:p=F][:delay=S][:jitter=J][:ramp=R]`
joined by commas. `kind` is one of error (FaultInjected), timeout
(TimeoutError), oserror (ConnectionResetError), drop/false (non-raising;
`fire` returns True), or delay (LATENCY injection: `fire` sleeps
`delay=S` seconds then returns False — the call proceeds, just slow;
honored at EVERY site regardless of category because it neither raises
nor alters the return — the SLO plane's burn-rate drills arm it on the
attach path), defaulting to the site's natural kind (error for raising
sites, drop for value sites). The delay kind takes two optional shaping
knobs (docs/fault-injection.md "Latency shaping"): `jitter=J` spreads
each sleep uniformly over [delay-J, delay+J] (clamped at 0, drawn from
the module RNG so seeded schedules replay), and `ramp=R` scales the
sleep linearly from 0 at arm time to full strength R seconds later — a
soak can model gradual degradation instead of a step function. Each
site honors only its own category — see `_SITE_CATEGORY` — and env
specs reject unknown sites outright, so a typo'd schedule aborts the
run instead of silently injecting nothing. `count` bounds how many
times the fault fires (default unlimited); `p` is the per-call fire
probability (default 1.0), drawn from the module RNG so a seeded run
replays the same schedule.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, Optional

from . import lockdep
from . import trace

log = logging.getLogger(__name__)

__all__ = ["FaultInjected", "arm", "disarm", "reset", "fire", "stats",
           "seed", "configure", "configure_from_env", "injected"]


class FaultInjected(Exception):
    """Default exception raised by a fault point armed with kind='error'."""


_RAISING_KINDS: Dict[str, Callable[[str], BaseException]] = {
    "error": lambda site: FaultInjected(f"injected fault at {site}"),
    "timeout": lambda site: TimeoutError(f"injected timeout at {site}"),
    "oserror": lambda site: ConnectionResetError(
        f"injected connection reset at {site}"),
}
_VALUE_KINDS = ("drop", "false")
# the latency kind: fire() sleeps then returns False — the call
# PROCEEDS, just slow. Neither raising nor value, so it is honored at
# every site whatever its category (the SLO burn-rate drills arm it on
# attach-path sites like kubeapi.request).
_DELAY_KIND = "delay"

# What each instrumented production site can honor. A raising kind armed
# on a value site would not simulate the documented failure — it would
# propagate out of a daemon thread (health hub, watcher loop) and kill
# it; a value kind on a raising site is ignored by the call site, so the
# run reports fires while injecting nothing. arm() enforces the category
# for known sites (unknown sites stay open for tests to invent).
_SITE_CATEGORY: Dict[str, str] = {
    "kubelet.register": "raising",
    "kubeapi.request": "raising",
    "kubeapi.watch": "raising",
    "kubeapi.watch.dup": "value",
    "kubeapi.watch.stale": "value",
    "native.probe": "value",
    "inotify.poll": "value",
    "dra.publish": "value",
    "checkpoint.write": "raising",
    "pci.hotunplug": "value",
    "pci.replug": "value",
    "migration.handoff": "raising",
    "broker.ipc": "value",
    "broker.ring": "value",
    "policy.hook": "raising",
    "discovery.snapshot": "value",
}
_DEFAULT_KIND = {"raising": "error", "value": "drop"}


class _FaultPoint:
    __slots__ = ("kind", "remaining", "probability", "exc_factory",
                 "fires", "delay_s", "jitter_s", "ramp_s", "armed_at")

    def __init__(self, kind: str, remaining: Optional[int],
                 probability: float,
                 exc_factory: Optional[Callable[[], BaseException]],
                 delay_s: float = 0.0, jitter_s: float = 0.0,
                 ramp_s: float = 0.0, armed_at: float = 0.0):
        self.kind = kind
        self.remaining = remaining    # None = unlimited
        self.probability = probability
        self.exc_factory = exc_factory
        self.fires = 0
        self.delay_s = delay_s        # kind="delay" only
        self.jitter_s = jitter_s      # uniform spread around delay_s
        self.ramp_s = ramp_s          # linear ramp-in from arm time
        self.armed_at = armed_at      # ramp reference point


_lock = lockdep.instrument("faults._lock", threading.Lock())
_points: Dict[str, _FaultPoint] = {}
_fired: Dict[str, int] = {}     # per-site lifetime fire counts (stats)
_rng = random.Random()
_armed = False                  # fast-path flag: False ⇒ fire() is a no-op


def seed(n: int) -> None:
    """Seed the probability RNG so probabilistic schedules replay."""
    _rng.seed(n)


def arm(site: str, kind: str = "error", count: Optional[int] = 1,
        probability: float = 1.0,
        exc: Optional[Callable[[], BaseException]] = None,
        delay_s: float = 0.0, jitter_s: float = 0.0,
        ramp_s: float = 0.0) -> None:
    """Arm `site`: the next `count` consultations fire (raise, return
    True, or sleep `delay_s` per kind) with the given probability. `exc`
    overrides the kind's exception factory (a zero-arg callable
    returning the exception). For kind='delay', `jitter_s` spreads each
    sleep uniformly over [delay_s-jitter_s, delay_s+jitter_s] (clamped
    at 0) and `ramp_s` scales the sleep linearly from 0 at arm time to
    full strength `ramp_s` seconds later."""
    global _armed
    if exc is None and kind not in _RAISING_KINDS \
            and kind not in _VALUE_KINDS and kind != _DELAY_KIND:
        raise ValueError(
            f"unknown fault kind {kind!r} (known: "
            f"{sorted(_RAISING_KINDS) + list(_VALUE_KINDS) + [_DELAY_KIND]})")
    if count is not None and count < 1:
        raise ValueError("count must be >= 1 (or None for unlimited)")
    if jitter_s < 0 or ramp_s < 0:
        raise ValueError("jitter_s and ramp_s must be >= 0")
    if (jitter_s or ramp_s) and kind != _DELAY_KIND:
        raise ValueError(
            "jitter_s/ramp_s shape LATENCY only — they need kind='delay' "
            f"(got kind={kind!r})")
    if kind == _DELAY_KIND and exc is None:
        if delay_s <= 0:
            raise ValueError("kind='delay' needs delay_s > 0")
        # latency is category-agnostic: the consulted call proceeds
        # unchanged after the sleep, so no site contract is violated
    else:
        category = "raising" if (exc is not None or kind in _RAISING_KINDS) \
            else "value"
        expected = _SITE_CATEGORY.get(site)
        if expected is not None and category != expected:
            raise ValueError(
                f"site {site!r} honors only {expected} kinds, not {kind!r} — "
                f"a mismatched kind would {'kill the daemon thread' if expected == 'value' else 'inject nothing while counting fires'}")
    factory = exc
    if factory is None and kind in _RAISING_KINDS:
        maker = _RAISING_KINDS[kind]
        factory = lambda: maker(site)  # noqa: E731 — site-bound closure
    with _lock:
        _points[site] = _FaultPoint(kind, count, probability, factory,
                                    delay_s=delay_s, jitter_s=jitter_s,
                                    ramp_s=ramp_s,
                                    armed_at=time.monotonic())
        _armed = True
    log.warning("fault point ARMED: %s kind=%s count=%s p=%g delay=%gs "
                "jitter=%gs ramp=%gs",
                site, kind, count if count is not None else "inf",
                probability, delay_s, jitter_s, ramp_s)


def disarm(site: Optional[str] = None) -> None:
    """Disarm one site (or all when site is None). Stats are kept."""
    global _armed
    with _lock:
        if site is None:
            _points.clear()
        else:
            _points.pop(site, None)
        _armed = bool(_points)


def reset() -> None:
    """Disarm everything and clear the stats (test teardown)."""
    global _armed
    with _lock:
        _points.clear()
        _fired.clear()
        _armed = False


def fire(site: str, **ctx: object) -> bool:
    """Consult fault point `site`. Disarmed: returns False (one bool read).

    Armed with a raising kind: raises the armed exception. Armed with a
    value kind (drop/false): returns True. Either way the fault's budget
    (`count`) is decremented and the fire recorded for `stats()`.
    """
    if not _armed:
        return False
    with _lock:
        point = _points.get(site)
        if point is None:
            return False
        if point.probability < 1.0 and _rng.random() >= point.probability:
            return False
        if point.remaining is not None:
            point.remaining -= 1
            if point.remaining <= 0:
                _points.pop(site, None)
                _refresh_armed_locked()
        point.fires += 1
        _fired[site] = _fired.get(site, 0) + 1
        factory = point.exc_factory
        kind = point.kind
        delay_s = point.delay_s
        if kind == _DELAY_KIND:
            # shape the sleep under the lock (the RNG draw must be
            # serialized for seeded replay); the sleep itself stays out
            if point.jitter_s > 0:
                delay_s += _rng.uniform(-point.jitter_s, point.jitter_s)
            if point.ramp_s > 0:
                elapsed = time.monotonic() - point.armed_at
                delay_s *= min(1.0, max(0.0, elapsed / point.ramp_s))
            delay_s = max(0.0, delay_s)
    log.warning("fault point FIRED: %s%s", site,
                f" ({ctx})" if ctx else "")
    # flight-recorder marker: an injected fault becomes a span event —
    # fired inside an instrumented span (probe, checkpoint commit, claim
    # prepare) it inherits that span's attrs, so chaos runs read as
    # traces, not just counters. Outside the armed path this line is
    # never reached (fire() returns above on the one-bool fast path).
    trace.event(f"fault.{site}",
                **{k: str(v) for k, v in ctx.items()})
    if factory is not None:
        raise factory()
    if kind == _DELAY_KIND:
        # latency injection: sleep OUTSIDE the lock, then let the call
        # proceed — False tells the site "not injected", which is true:
        # nothing was dropped or failed, it was only made slow
        time.sleep(delay_s)
        return False
    return True


def _refresh_armed_locked() -> None:
    global _armed
    _armed = bool(_points)


def stats() -> Dict[str, int]:
    """Per-site lifetime fire counts (survive disarm; cleared by reset).
    Lock-free read (the /status lockdep gate): dict(d) is one C-atomic
    copy, so a racing fire() costs at most a one-fire-stale count."""
    return dict(_fired)


def armed_sites() -> Dict[str, Dict[str, object]]:
    """Currently armed points, for the /status debugging surface.
    Lock-free read: list(d.items()) is a C-atomic copy and each point
    FIELD is one GIL-atomic read. fire() mutates `remaining`/`fires` in
    place under _lock, so a mid-fire snapshot can pair a decremented
    `remaining` with a not-yet-incremented `fires` — fine for a
    diagnostic listing, but do NOT derive compound facts (e.g. an armed
    budget) from two fields of one snapshot."""
    return {site: {"kind": p.kind, "remaining": p.remaining,
                   "probability": p.probability, "fires": p.fires,
                   "delay_s": p.delay_s, "jitter_s": p.jitter_s,
                   "ramp_s": p.ramp_s}
            for site, p in list(_points.items())}


@contextmanager
def injected(site: str, kind: str = "error", count: Optional[int] = 1,
             probability: float = 1.0,
             exc: Optional[Callable[[], BaseException]] = None,
             delay_s: float = 0.0, jitter_s: float = 0.0,
             ramp_s: float = 0.0) -> Iterator[None]:
    """Scope-bound arming for tests: disarms the site on exit even when
    the fault's budget was not exhausted."""
    arm(site, kind=kind, count=count, probability=probability, exc=exc,
        delay_s=delay_s, jitter_s=jitter_s, ramp_s=ramp_s)
    try:
        yield
    finally:
        disarm(site)


def configure(spec: str) -> None:
    """Arm fault points from a spec string (see module docstring grammar)."""
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        site = fields[0]
        category = _SITE_CATEGORY.get(site)
        if category is None:
            # env specs address production sites only — a typo'd site
            # must abort the run, not silently inject nothing
            raise ValueError(f"unknown fault site {site!r} in {part!r} "
                             f"(known: {sorted(_SITE_CATEGORY)})")
        kind = (fields[1] if len(fields) > 1 and fields[1]
                else _DEFAULT_KIND[category])
        count: Optional[int] = None
        probability = 1.0
        delay_s = 0.0
        jitter_s = 0.0
        ramp_s = 0.0
        for opt in fields[2:]:
            key, _, value = opt.partition("=")
            if key == "count":
                count = int(value)
            elif key == "p":
                probability = float(value)
            elif key == "delay":
                delay_s = float(value)
            elif key == "jitter":
                jitter_s = float(value)
            elif key == "ramp":
                ramp_s = float(value)
            else:
                raise ValueError(f"unknown fault option {opt!r} in {part!r}")
        arm(site, kind=kind, count=count, probability=probability,
            delay_s=delay_s, jitter_s=jitter_s, ramp_s=ramp_s)


def configure_from_env(env: str = "TDP_FAULTS",
                       seed_env: str = "TDP_FAULTS_SEED") -> bool:
    """Arm from $TDP_FAULTS (and seed from $TDP_FAULTS_SEED); True if any
    spec was found. Called once by cli.main — a production pod without the
    variable pays one getenv."""
    seed_val = os.environ.get(seed_env)
    if seed_val:
        seed(int(seed_val))
    spec = os.environ.get(env)
    if not spec:
        return False
    configure(spec)
    return True
